"""Golden + property tests for the exception-edge CFG and dataflow
solver (ISSUE 17 tentpole): ``kubeflow_tpu/analysis/cfg.py``.

Layer 1 — golden graphs: small functions whose leak/clean verdict is
derivable by hand. Each test encodes one structural law of the builder
(finally inlining per continuation, collector-funneled exception
routing, kill-before-throw, loop back-edges, unwind through finally on
return/break) as a dataflow result: GEN one token at the acquire line,
KILL at the release lines, and assert exactly which exit kinds still
carry the token.

Layer 2 — seeded property tests: a deterministic random program
generator (nesting if/for/try/finally/raise/return/break) feeding the
builder and solver. Pins termination, run-to-run determinism of the
fixpoint, and structural sanity of every generated graph. The
serial-vs---jobs byte-identity law for the RES/WIRE rules that ride on
this engine lives in tests/test_tpulint.py with the other families.
"""

import ast
import random
import textwrap

import pytest

from kubeflow_tpu.analysis import cfg

pytestmark = pytest.mark.lint


def _cfg(src: str) -> cfg.CFG:
    tree = ast.parse(textwrap.dedent(src))
    return cfg.build_cfg(tree.body[0])


def _nodes_at(graph: cfg.CFG, line: int):
    got = [n for n in graph.stmt_nodes() if n.line == line]
    assert got, f"no stmt node at line {line}"
    return got


def _leaks(graph: cfg.CFG, acquire_line: int, release_lines=()):
    """Exit kinds (with source lines) still carrying the single token
    GEN'd at ``acquire_line`` after KILLs at ``release_lines``."""
    gen = {n.idx: frozenset({0}) for n in _nodes_at(graph, acquire_line)}
    kill = {}
    for line in release_lines:
        for n in _nodes_at(graph, line):
            kill[n.idx] = frozenset({0})
    ins = cfg.solve_forward(graph, gen, kill)
    return sorted(
        (e.kind, graph.nodes[e.src].line)
        for e, fact in cfg.exit_facts(graph, ins, gen, kill) if fact)


# -- golden: straight-line and exception basics ------------------------------


def test_leak_on_raise_between_acquire_and_release():
    """The motivating bug shape: a throwing call between acquire and
    release leaks on the exception edge and ONLY there."""
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            self.use(h)
            self.r.give(h)
    """)
    assert _leaks(g, 2, [4]) == [("exc", 3)]


def test_acquires_own_exception_edge_carries_no_token():
    """Kill-before-throw's dual: GEN is suppressed on the generating
    statement's own exception edge — if take() raised, nothing was
    taken."""
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            self.r.give(h)
    """)
    assert _leaks(g, 2, [3]) == []


def test_release_that_throws_has_still_released():
    """Kill-before-throw: the release statement's exception edge does
    not resurrect the token."""
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            self.r.give(h)
            self.done()
    """)
    assert _leaks(g, 2, [3]) == []


# -- golden: try/finally inlining --------------------------------------------


def test_release_in_finally_covers_every_continuation():
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            try:
                self.use(h)
            finally:
                self.r.give(h)
    """)
    assert _leaks(g, 2, [6]) == []


def test_finally_body_is_inlined_once_per_continuation():
    """Normal fall-through and the exception path each get their own
    copy of the finally body (collector-funneled: one exception copy
    per try, not per throwing statement)."""
    g = _cfg("""\
        def f(self):
            try:
                self.a()
                self.b()
            finally:
                self.fin()
    """)
    assert len(_nodes_at(g, 6)) == 2


def test_return_through_finally_runs_the_finally():
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            try:
                return self.use(h)
            finally:
                self.r.give(h)
    """)
    assert _leaks(g, 2, [6]) == []


def test_nested_try_finally_inner_and_outer_both_prove():
    src = """\
        def f(self):
            a = self.r.take()
            try:
                b = self.q.take()
                try:
                    self.use(a, b)
                finally:
                    self.q.give(b)
            finally:
                self.r.give(a)
    """
    g = _cfg(src)
    assert _leaks(g, 2, [10]) == []          # outer token, outer finally
    assert _leaks(g, 4, [8]) == []           # inner token, inner finally
    # the inner finally alone does NOT cover the outer token
    assert ("exc", 10) in _leaks(g, 2, [8])


def test_break_and_continue_unwind_through_finally():
    g = _cfg("""\
        def f(self, items):
            for x in items:
                h = self.r.take()
                try:
                    if x:
                        break
                    self.use(h)
                finally:
                    self.r.give(h)
            return None
    """)
    assert _leaks(g, 3, [9]) == []
    # three inlined copies: fall-through, exception, break-unwind
    assert len(_nodes_at(g, 9)) == 3


# -- golden: handlers ---------------------------------------------------------


def test_release_in_catch_all_handler_is_proven():
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            try:
                self.use(h)
            except Exception:
                self.r.give(h)
                raise
            self.r.give(h)
    """)
    assert _leaks(g, 2, [6, 8]) == []


def test_bare_reraise_before_handler_release_leaks():
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            try:
                self.use(h)
            except Exception:
                raise
            self.r.give(h)
    """)
    assert ("raise", 6) in _leaks(g, 2, [7])


def test_narrow_handler_lets_other_exceptions_escape():
    """A non-catch-all handler's collector keeps an onward exception
    edge: releasing only inside ``except KeyError`` is not proof."""
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            try:
                self.use(h)
            except KeyError:
                self.r.give(h)
                return None
            self.r.give(h)
    """)
    leaks = _leaks(g, 2, [6, 8])
    assert leaks and all(kind == "exc" for kind, _ in leaks)


def test_with_header_and_body_carry_exception_edges():
    g = _cfg("""\
        def f(self):
            h = self.r.take()
            with self.ctx():
                self.use(h)
            self.r.give(h)
    """)
    assert _leaks(g, 2, [5]) == [("exc", 3), ("exc", 4)]


# -- golden: loops ------------------------------------------------------------


def test_loop_has_back_edge_and_facts_survive_it():
    g = _cfg("""\
        def f(self, items):
            h = self.r.take()
            for x in items:
                self.use(x)
            return h
    """)
    assert any(e.kind == "loop" for e in g.edges)
    # the token survives the loop and is live at the return
    assert ("return", 5) in _leaks(g, 2)


def test_acquire_inside_loop_released_inside_loop_is_clean():
    g = _cfg("""\
        def f(self, items):
            for x in items:
                h = self.r.take()
                self.r.give(h)
            return None
    """)
    assert _leaks(g, 3, [4]) == []


# -- solver laws --------------------------------------------------------------


def test_solver_is_deterministic_and_idempotent():
    g = _cfg("""\
        def f(self, items):
            h = self.r.take()
            for x in items:
                try:
                    self.use(h)
                except ValueError:
                    continue
            self.r.give(h)
    """)
    gen = {n.idx: frozenset({0}) for n in _nodes_at(g, 2)}
    kill = {n.idx: frozenset({0}) for n in _nodes_at(g, 8)}
    first = cfg.solve_forward(g, gen, kill)
    second = cfg.solve_forward(g, gen, kill)
    assert first == second
    # resolving from the fixpoint changes nothing
    assert cfg.exit_facts(g, first, gen, kill) == \
        cfg.exit_facts(g, second, gen, kill)


def test_builder_is_deterministic():
    src = """\
        def f(self, items):
            for x in items:
                try:
                    if x:
                        return self.use(x)
                finally:
                    self.fin()
            raise ValueError(items)
    """
    a, b = _cfg(src), _cfg(src)
    assert [(n.idx, n.kind, n.line) for n in a.nodes] == \
        [(n.idx, n.kind, n.line) for n in b.nodes]
    assert a.edges == b.edges


# -- seeded random-program property tests ------------------------------------


_SIMPLE = (
    "self.use()",
    "h = self.r.take()",
    "self.r.give(h)",
    "x = 1",
)


def _gen_block(rng: random.Random, depth: int, in_loop: bool,
               out: list, ind: str) -> None:
    """Append 1-3 valid statements at this indent, recursing into
    compound statements while depth allows."""
    for _ in range(rng.randint(1, 3)):
        roll = rng.random()
        if depth >= 3 or roll < 0.40:
            stmt = rng.choice(_SIMPLE)
            if in_loop and rng.random() < 0.15:
                stmt = rng.choice(("break", "continue"))
            elif rng.random() < 0.10:
                stmt = rng.choice(
                    ("return self.done()", "raise ValueError()"))
            out.append(ind + stmt)
        elif roll < 0.55:
            out.append(ind + "if self.p():")
            _gen_block(rng, depth + 1, in_loop, out, ind + "    ")
            if rng.random() < 0.5:
                out.append(ind + "else:")
                _gen_block(rng, depth + 1, in_loop, out, ind + "    ")
        elif roll < 0.70:
            out.append(ind + "for it in self.items():")
            _gen_block(rng, depth + 1, True, out, ind + "    ")
        elif roll < 0.80:
            out.append(ind + "with self.ctx():")
            _gen_block(rng, depth + 1, in_loop, out, ind + "    ")
        else:
            out.append(ind + "try:")
            _gen_block(rng, depth + 1, in_loop, out, ind + "    ")
            shape = rng.randrange(3)
            if shape in (0, 2):
                handler = rng.choice(("Exception", "KeyError"))
                out.append(ind + f"except {handler}:")
                _gen_block(rng, depth + 1, in_loop, out, ind + "    ")
            if shape in (1, 2):
                out.append(ind + "finally:")
                _gen_block(rng, depth + 1, in_loop, out, ind + "    ")


def _random_fn(seed: int) -> str:
    rng = random.Random(seed)
    lines = ["def f(self):"]
    _gen_block(rng, 0, False, lines, "    ")
    return "\n".join(lines) + "\n"


@pytest.mark.parametrize("seed", range(40))
def test_random_cfg_solver_terminates_deterministically(seed):
    src = _random_fn(seed)
    tree = ast.parse(src)  # the generator only emits valid programs
    g = cfg.build_cfg(tree.body[0])

    # structural sanity: edges stay in range, EXIT terminates
    n = len(g.nodes)
    assert all(0 <= e.src < n and 0 <= e.dst < n for e in g.edges)
    assert g.succ(cfg.EXIT) == []
    assert all(g.nodes[i].idx == i for i in range(n))

    # arbitrary-but-seeded gen/kill maps exercise the fixpoint
    rng = random.Random(seed + 1000)
    universe = [frozenset({i % 7}) for i in range(n)]
    gen = {i: universe[i] for i in range(n) if rng.random() < 0.3}
    kill = {i: universe[(i + 3) % n] for i in range(n)
            if rng.random() < 0.2}
    first = cfg.solve_forward(g, gen, kill)
    second = cfg.solve_forward(g, gen, kill)
    assert first == second
    assert set(first) == {node.idx for node in g.nodes}

    # the fixpoint really is one: one more round of transfers over
    # every edge adds nothing
    for e in g.edges:
        base = first[e.src]
        k = kill.get(e.src, frozenset())
        out = (base - k if e.kind in cfg.EXC_KINDS
               else (base | gen.get(e.src, frozenset())) - k)
        assert out <= first[e.dst], (seed, e)


@pytest.mark.parametrize("seed", range(10))
def test_random_cfg_rebuild_is_identical(seed):
    src = _random_fn(seed)
    a = cfg.build_cfg(ast.parse(src).body[0])
    b = cfg.build_cfg(ast.parse(src).body[0])
    assert a.edges == b.edges
    assert [(x.kind, x.line) for x in a.nodes] == \
        [(x.kind, x.line) for x in b.nodes]
