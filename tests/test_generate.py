"""KV-cache generation: decode path must agree exactly with the full
(training) forward — the teacher-forcing consistency check."""

import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.runtime.generate import generate, init_cache


def make_model_and_params(seed=0, **kw):
    model = get_model("transformer-test", max_seq_len=64, **kw)
    tok = jnp.zeros((2, 8), jnp.int32)
    variables = meta.unbox(model.init(jax.random.PRNGKey(seed), tok))
    return model, variables


def test_greedy_matches_full_forward():
    model, variables = make_model_and_params()
    rng = jax.random.PRNGKey(1)
    prompt = jax.random.randint(rng, (2, 8), 0, 256, jnp.int32)
    out = generate(model, variables, prompt, max_new_tokens=6)
    assert out.shape == (2, 14)
    np.testing.assert_array_equal(np.asarray(out[:, :8]), np.asarray(prompt))

    # teacher forcing: each generated token is the argmax of the FULL
    # (non-cached) forward at its position -> cache semantics are exact.
    logits = model.apply(variables, out[:, :-1], train=False)
    for i in range(6):
        pos = 8 + i - 1  # logits at pos predict token pos+1
        want = jnp.argmax(logits[:, pos], axis=-1)
        np.testing.assert_array_equal(
            np.asarray(out[:, 8 + i]), np.asarray(want),
            err_msg=f"generated token {i} diverges from full forward")


def test_sampling_is_seeded_and_in_range():
    model, variables = make_model_and_params()
    prompt = jnp.ones((2, 4), jnp.int32)
    a = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=1.0, top_k=10, seed=3)
    b = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=1.0, top_k=10, seed=3)
    c = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=1.0, top_k=10, seed=4)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a)[:, 4:] >= 0).all()
    assert (np.asarray(a)[:, 4:] < 256).all()
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_gqa_cache_shapes():
    model, variables = make_model_and_params()
    cache = init_cache(model, batch=3)
    leaves = jax.tree.leaves(cache)
    assert leaves, "no cache variables created"
    for leaf in leaves:
        assert leaf.shape[0] == 3 and leaf.shape[1] == 64  # B, max_seq
        assert leaf.shape[2] == 2  # n_kv_heads of transformer-test


def test_left_padded_prompt_with_pad_len_matches_unpadded():
    """Masked left-padding is exact: a row left-padded to Lp with its
    pad positions masked must generate the same greedy tokens as the
    same prompt run unpadded (RoPE is relative, pads are invisible)."""
    model, variables = make_model_and_params()
    real = jnp.asarray([[7, 3, 11, 5]], jnp.int32)
    out_ref = generate(model, variables, real, max_new_tokens=6)

    pad = 5
    padded = jnp.concatenate(
        [jnp.zeros((1, pad), jnp.int32), real], axis=1)
    out_pad = generate(model, variables, padded, max_new_tokens=6,
                       pad_len=jnp.asarray([pad], jnp.int32))
    np.testing.assert_array_equal(
        np.asarray(out_ref)[:, 4:], np.asarray(out_pad)[:, 4 + pad:])

    # and WITHOUT the mask the pads leak into attention: the decode
    # logits differ (argmax may coincide on a tiny model, logits won't)
    def last_logits(pad_len):
        cache = init_cache(model, 1)
        kw = {} if pad_len is None else {"pad_len": pad_len}
        logits = None
        for i in range(padded.shape[1]):
            logits, mut = model.apply(
                {"params": variables["params"], "cache": cache},
                padded[:, i:i + 1], train=False, decode_index=i,
                mutable=["cache"], **kw)
            cache = mut["cache"]
        return np.asarray(logits)

    masked = last_logits(jnp.asarray([pad], jnp.int32))
    unmasked = last_logits(None)
    assert not np.allclose(masked, unmasked)


def test_ragged_batch_rows_match_their_solo_runs():
    """Different pad_len per row in one batch: each row generates what
    it would generate alone."""
    model, variables = make_model_and_params()
    a = [2, 9, 4]
    b = [8, 1, 6, 3, 10, 12]
    lp = 6
    batch = jnp.asarray([
        [0] * (lp - len(a)) + a,
        [0] * (lp - len(b)) + b,
    ], jnp.int32)
    pad = jnp.asarray([lp - len(a), lp - len(b)], jnp.int32)
    out = np.asarray(generate(model, variables, batch, max_new_tokens=4,
                              pad_len=pad))
    solo_a = np.asarray(generate(
        model, variables, jnp.asarray([a], jnp.int32), max_new_tokens=4))
    solo_b = np.asarray(generate(
        model, variables, jnp.asarray([b], jnp.int32), max_new_tokens=4))
    np.testing.assert_array_equal(out[0, lp:], solo_a[0, len(a):])
    np.testing.assert_array_equal(out[1, lp:], solo_b[0, len(b):])


# ---- chunked prefill vs the per-token oracle ---------------------------


def test_chunked_prefill_matches_per_token_oracle():
    """prefill_scan (chunked) must produce the same cache and last
    logits as the one-position-per-tick oracle, with and without
    left-padding — any drift is a chunk-mask/position bug."""
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.runtime.generate import (
        init_cache, prefill_per_token, prefill_scan)

    model = get_model("transformer-test", dtype=jnp.float32, max_seq_len=64)
    prompt = (jnp.arange(24, dtype=jnp.int32).reshape(2, 12) * 11 + 3) % 250
    variables = model.init(jax.random.PRNGKey(0), prompt, train=False)
    params = {"params": variables["params"]}
    for pad in (None, jnp.asarray([0, 4], jnp.int32)):
        c_new, l_new = prefill_scan(
            model, params, init_cache(model, 2), prompt, pad)
        c_old, l_old = prefill_per_token(
            model, params, init_cache(model, 2), prompt, pad)
        np.testing.assert_allclose(np.asarray(l_new), np.asarray(l_old),
                                   rtol=1e-5, atol=1e-5)

        def cmp(a, b):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            if pad is not None and a.ndim == 4:
                # pad positions hold garbage in BOTH paths (their empty
                # attention rows are masked out of every real query);
                # compare the real positions only
                for r, p in enumerate(np.asarray(pad)):
                    np.testing.assert_allclose(a[r, p:], b[r, p:],
                                               rtol=1e-5, atol=1e-5)
            else:
                np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)

        jax.tree.map(cmp, c_new, c_old)


def test_chunked_prefill_multi_chunk_and_remainder(monkeypatch):
    """Force several full chunks PLUS a remainder chunk (lp=12, width 5
    -> ticks at 0/5 and a remainder of 2): chunk-start offsets, carry
    threading, and cross-chunk attention all exercised — a single-chunk
    run would validate none of them."""
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.runtime import generate as G

    monkeypatch.setattr(G, "PREFILL_CHUNK", 5)
    model = get_model("transformer-test", dtype=jnp.float32, max_seq_len=64)
    prompt = (jnp.arange(24, dtype=jnp.int32).reshape(2, 12) * 7 + 1) % 250
    variables = model.init(jax.random.PRNGKey(1), prompt, train=False)
    params = {"params": variables["params"]}
    c_new, l_new = G.prefill_scan(
        model, params, G.init_cache(model, 2), prompt, None)
    c_old, l_old = G.prefill_per_token(
        model, params, G.init_cache(model, 2), prompt, None)
    np.testing.assert_allclose(np.asarray(l_new), np.asarray(l_old),
                               rtol=1e-5, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-5, atol=1e-5),
        c_new, c_old)


def test_prefill_empty_prompt_is_noop():
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.runtime.generate import init_cache, prefill_scan

    model = get_model("transformer-test", dtype=jnp.float32, max_seq_len=64)
    tok1 = jnp.zeros((1, 1), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tok1, train=False)
    cache0 = init_cache(model, 1)
    cache, logits = prefill_scan(
        model, {"params": variables["params"]}, cache0,
        jnp.zeros((1, 0), jnp.int32), None)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), cache, cache0)
    assert logits.shape == (1, model.cfg.vocab_size)


def test_prefill_chunk_env_override(monkeypatch):
    """KFTPU_PREFILL_CHUNK forces a width (the hardware A/B hook) and
    the result still matches the oracle."""
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.runtime import generate as G

    monkeypatch.setenv("KFTPU_PREFILL_CHUNK", "3")
    model = get_model("transformer-test", dtype=jnp.float32, max_seq_len=64)
    prompt = (jnp.arange(20, dtype=jnp.int32).reshape(2, 10) * 3 + 2) % 250
    variables = model.init(jax.random.PRNGKey(2), prompt, train=False)
    params = {"params": variables["params"]}
    _, l_new = G.prefill_scan(
        model, params, G.init_cache(model, 2), prompt, None)
    _, l_old = G.prefill_per_token(
        model, params, G.init_cache(model, 2), prompt, None)
    np.testing.assert_allclose(np.asarray(l_new), np.asarray(l_old),
                               rtol=1e-5, atol=1e-5)


def test_windowed_decode_matches_windowed_full_forward():
    """attention_window decode == the windowed training forward,
    token for token (train/serve parity — the reason decode masks the
    cache with the same window instead of rejecting the knob)."""
    model, variables = make_model_and_params(
        dtype=jnp.float32, attention_window=6, attention_impl="reference")
    rng = jax.random.PRNGKey(4)
    prompt = jax.random.randint(rng, (2, 8), 0, 256, jnp.int32)
    out = generate(model, variables, prompt, max_new_tokens=6)
    logits = model.apply(variables, out[:, :-1], train=False)
    for i in range(6):
        pos = 8 + i - 1
        want = jnp.argmax(logits[:, pos], axis=-1)
        np.testing.assert_array_equal(
            np.asarray(out[:, 8 + i]), np.asarray(want),
            err_msg=f"windowed decode token {i} diverges from train fwd")


class TestRollingKvCache:
    """rolling_kv_cache: the bounded cache (last W positions only) must
    be token-for-token equal to the full cache under the same sliding
    window — a memory layout change, never a semantics change."""

    def _pair(self, window, seed=3, dtype=jnp.float32, **kw):
        # f32 by default: the equality is exact only when both paths do
        # the same arithmetic; bf16 re-association noise would force a
        # tolerance and weaken the pin
        full = get_model("transformer-test", max_seq_len=64, dtype=dtype,
                         attention_window=window, **kw)
        roll = get_model("transformer-test", max_seq_len=64, dtype=dtype,
                         attention_window=window, rolling_kv_cache=True,
                         **kw)
        tok = jnp.zeros((2, 8), jnp.int32)
        variables = meta.unbox(full.init(jax.random.PRNGKey(seed), tok))
        return full, roll, variables

    def test_cache_is_window_sized(self):
        _, roll, variables = self._pair(window=16)
        cache = init_cache(roll, batch=2)
        leaf = jax.tree.leaves(cache)[0]
        assert leaf.shape[1] == 16  # W, not max_seq_len

    def test_greedy_equal_to_full_cache_past_the_wrap(self):
        full, roll, variables = self._pair(window=16)
        rng = jax.random.PRNGKey(7)
        prompt = jax.random.randint(rng, (2, 12), 0, 256, jnp.int32)
        # 12 prompt + 24 new = 36 positions: wraps the 16-slot cache twice
        a = generate(full, variables, prompt, max_new_tokens=24)
        b = generate(roll, variables, prompt, max_new_tokens=24)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_equal_with_left_padding(self):
        full, roll, variables = self._pair(window=8)
        rng = jax.random.PRNGKey(9)
        real = jax.random.randint(rng, (1, 6), 0, 256, jnp.int32)
        padded = jnp.concatenate(
            [jnp.zeros((1, 3), jnp.int32), real], axis=1)
        pad_len = jnp.array([3], jnp.int32)
        a = generate(full, variables, padded, max_new_tokens=10,
                     pad_len=pad_len)
        b = generate(roll, variables, padded, max_new_tokens=10,
                     pad_len=pad_len)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_continuous_batching_slots_equal(self):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        full, roll, variables = self._pair(window=16)
        prompts = [[5, 9, 2, 7, 11, 3], [4, 4, 8]]
        outs = {}
        for name, model in (("full", full), ("roll", roll)):
            dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                              max_new_tokens=20)
            try:
                outs[name] = [dec.submit(p) for p in prompts]
            finally:
                dec.close()
        assert outs["full"] == outs["roll"]

    def test_equal_with_int8_kv_cache(self):
        """int8 parity: the rolling path quantizes the chunk BEFORE
        attending (the full path attends the just-written dequantized
        cache), so both see the same quantize->dequantize round trip."""
        full, roll, variables = self._pair(window=16,
                                           kv_cache_dtype="int8")
        rng = jax.random.PRNGKey(11)
        prompt = jax.random.randint(rng, (2, 10), 0, 256, jnp.int32)
        a = generate(full, variables, prompt, max_new_tokens=20)
        b = generate(roll, variables, prompt, max_new_tokens=20)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rolling_without_window_refuses(self):
        import pytest

        model = get_model("transformer-test", max_seq_len=64,
                          rolling_kv_cache=True)
        tok = jnp.zeros((1, 4), jnp.int32)
        variables = meta.unbox(model.init(jax.random.PRNGKey(0), tok))
        with pytest.raises(ValueError, match="attention_window"):
            generate(model, variables, tok, max_new_tokens=2)
