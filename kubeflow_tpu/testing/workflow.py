"""Workflow DAG runner — the Argo-workflow equivalent.

The reference expresses E2E as Argo DAGs in jsonnet
(testing/workflows/components/kfctl_go_test.jsonnet): steps with
dependencies, a per-step deadline (50 min, :94), an artifacts directory,
and exit-handler steps (copy-artifacts, teardown) that run regardless of
DAG outcome. This runner provides that shape as plain Python:

    wf = Workflow("e2e", artifacts_dir=...)
    wf.step("checkout", fn)
    wf.step("build", fn, deps=["checkout"])
    wf.step("deploy", fn, deps=["build"])
    wf.exit_handler("teardown", fn)
    result = wf.run()

Independent steps run concurrently (thread pool — steps are IO/subprocess
bound like the reference's). Each step's outcome lands in a junit
TestSuite for the testgrid contract.
"""

from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import logging
import time
from typing import Any, Callable

from kubeflow_tpu.testing.junit import TestSuite

log = logging.getLogger("kubeflow_tpu.testing")

DEFAULT_STEP_DEADLINE_S = 3000.0  # kfctl_go_test.jsonnet:94


@dataclasses.dataclass
class Step:
    name: str
    fn: Callable[["Context"], Any]
    deps: list[str] = dataclasses.field(default_factory=list)
    deadline_s: float = DEFAULT_STEP_DEADLINE_S
    # filled by run():
    status: str = "Pending"   # Pending | Running | Succeeded | Failed | Skipped
    error: str | None = None
    output: Any = None
    time_s: float = 0.0


@dataclasses.dataclass
class Context:
    """Passed to every step fn: shared scratch + artifact sink."""

    artifacts_dir: str | None = None
    values: dict[str, Any] = dataclasses.field(default_factory=dict)

    def put(self, key: str, value: Any) -> None:
        self.values[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self.values.get(key, default)


class Workflow:
    def __init__(self, name: str, artifacts_dir: str | None = None,
                 max_workers: int = 8):
        self.name = name
        self.ctx = Context(artifacts_dir=artifacts_dir)
        self.steps: dict[str, Step] = {}
        self.exit_handlers: list[Step] = []
        self.max_workers = max_workers

    def step(self, name: str, fn: Callable, deps: list[str] | None = None,
             deadline_s: float = DEFAULT_STEP_DEADLINE_S) -> Step:
        if name in self.steps:
            raise ValueError(f"duplicate step {name!r}")
        for d in deps or []:
            if d not in self.steps:
                raise ValueError(f"step {name!r} depends on unknown {d!r}")
        s = Step(name, fn, list(deps or []), deadline_s)
        self.steps[name] = s
        return s

    def exit_handler(self, name: str, fn: Callable,
                     deadline_s: float = DEFAULT_STEP_DEADLINE_S) -> Step:
        """Always runs after the DAG, success or failure (Argo onExit:
        copy-artifacts + teardown, kfctl_go_test.jsonnet:351)."""
        s = Step(name, fn, [], deadline_s)
        self.exit_handlers.append(s)
        return s

    # -- execution ----------------------------------------------------------

    def _run_step(self, s: Step) -> None:
        import threading

        s.status = "Running"
        t0 = time.monotonic()
        box: dict = {}

        def target():
            try:
                box["output"] = s.fn(self.ctx)
            except BaseException as e:  # incl. SystemExit from CLI wrappers:
                box["error"] = e        # anything non-returning is a failure

        # Daemon thread + join(timeout), NOT an executor: executor shutdown
        # waits for the fn, so a hung step would hang the whole DAG past
        # its deadline. A step that outlives its deadline is marked Failed
        # and abandoned (Python can't kill a thread; the daemon flag keeps
        # it from blocking process exit — Argo's activeDeadlineSeconds pod
        # kill is the real-cluster analogue).
        t = threading.Thread(target=target, daemon=True,
                             name=f"wf-step-{s.name}")
        t.start()
        t.join(timeout=s.deadline_s)
        if t.is_alive():
            s.status = "Failed"
            s.error = f"deadline {s.deadline_s}s exceeded"
        elif "error" in box:
            s.status = "Failed"
            e = box["error"]
            s.error = f"{type(e).__name__}: {e}"
        else:
            s.output = box.get("output")
            s.status = "Succeeded"
        s.time_s = time.monotonic() - t0
        log.info("step %s: %s (%.1fs)%s", s.name, s.status, s.time_s,
                 f" — {s.error}" if s.error else "")

    def run(self) -> "WorkflowResult":
        pending = dict(self.steps)
        done: dict[str, Step] = {}
        with cf.ThreadPoolExecutor(max_workers=self.max_workers) as pool:
            futures: dict[cf.Future, Step] = {}
            while pending or futures:
                # schedule every step whose deps are all Succeeded
                for name in list(pending):
                    s = pending[name]
                    dep_steps = [done.get(d) for d in s.deps]
                    if any(d and d.status in ("Failed", "Skipped") for d in dep_steps):
                        s.status = "Skipped"
                        s.error = "upstream failed"
                        done[name] = pending.pop(name)
                        continue
                    if all(d and d.status == "Succeeded" for d in dep_steps) or not s.deps:
                        futures[pool.submit(self._run_step, s)] = s
                        pending.pop(name)
                if not futures:
                    if pending:  # only skipped steps remained
                        continue
                    break
                finished, _ = cf.wait(list(futures),
                                      return_when=cf.FIRST_COMPLETED)
                for f in finished:
                    s = futures.pop(f)
                    done[s.name] = s
        for h in self.exit_handlers:
            self._run_step(h)
        return WorkflowResult(self)


class WorkflowResult:
    def __init__(self, wf: Workflow):
        self.workflow = wf
        self.steps = dict(wf.steps)
        self.exit_handlers = list(wf.exit_handlers)

    @property
    def succeeded(self) -> bool:
        return all(s.status == "Succeeded" for s in self.steps.values())

    def junit(self) -> TestSuite:
        suite = TestSuite(self.workflow.name)
        for s in list(self.steps.values()) + self.exit_handlers:
            fail = None
            if s.status == "Failed":
                fail = s.error or "failed"
            skip = s.error if s.status == "Skipped" else None
            from kubeflow_tpu.testing.junit import TestCase

            suite.cases.append(TestCase(
                name=s.name, class_name=self.workflow.name,
                time_s=s.time_s, failure=fail, skipped=skip))
        return suite

    def write_junit(self, path: str) -> str:
        return self.junit().write(path)
