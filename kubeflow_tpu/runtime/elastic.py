"""Worker-side elastic coordinator: shrink/grow the training world in
place instead of dying with the gang.

The control-plane half lives in the JAXJob controller (docs/elastic.md):
on node loss/preemption it condemns only the lost pods, re-stamps the
surviving pods' world annotation (jaxjob/types.py ANNOTATION_WORLD, a
serialized ``parallel.dist.WorldSpec``), and the downward API projects
that annotation into each pod at $JAXJOB_WORLD_FILE. This module is the
in-pod half:

- poll the world source once per step (piggybacked on the trainer's
  ``stop`` flag, exactly like the preemption notice);
- on a CHANGED world: the trainer's stop path checkpoints the current
  step, then the coordinator tears down the old ``jax.distributed``
  state (``dist.shutdown()`` — the re-entrancy contract), re-forms at
  the new size/rank/coordinator, rebuilds mesh + shardings (a fresh
  Trainer — ``parallel/shardings.py`` re-infers placement for the new
  mesh) and resumes from the checkpoint: save-at-N/restore-at-M
  resharding is ``runtime/checkpoint.py``'s restore-onto-template path;
- the global batch is PRESERVED across the resize by default (survivors
  absorb the lost shards via gradient accumulation, so the loss curve
  is continuous) or SCALED with the world per spec.elastic.batchPolicy;
- a replacement pod whose name is absent from the current world stamp
  waits in the JOIN BARRIER until a grow resize admits it;
- a real preemption notice (runtime/preemption.py) always wins: a
  SIGTERM'd pod is being terminated, so it exits EX_TEMPFAIL (the
  controller restarts the gang) instead of burning its remaining grace
  — surfaced via ``PreemptionNotice.remaining_grace()`` — on a doomed
  in-place re-formation.

Import-light: jax/trainer imports are deferred to run() so the control
plane and tests can import the contract pieces freely.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Callable

from kubeflow_tpu.parallel import dist as D

log = logging.getLogger("kubeflow_tpu.elastic")

# Batch policies — re-exported from the wire contract (parallel/dist.py,
# the ONE spelling; jaxjob's spec.elastic.batchPolicy re-exports the
# same values). The controller ships the value via $JAXJOB_BATCH_POLICY
# so the worker needs no kube client.
BATCH_PRESERVE = D.BATCH_PRESERVE
BATCH_SCALE = D.BATCH_SCALE


def file_world_source(path: str) -> Callable[[], D.WorldSpec | None]:
    """World source over the downward-API projection: the kubelet keeps
    the file in sync with the pod's world annotation. Missing/partial
    files read as None (keep the current world) — the projection is
    atomically symlink-swapped but may not exist before the first
    sync."""

    def read() -> D.WorldSpec | None:
        try:
            with open(path) as f:
                return D.WorldSpec.from_json(f.read())
        except OSError:
            return None

    return read


class WorldMembershipError(RuntimeError):
    """Asked to form a world this worker is not a member of — the stamp
    moved between the membership check and env construction. Forming
    anyway would default the rank to 0 and collide with the world's
    real coordinator."""


@dataclasses.dataclass
class ResizeExit:
    """Why run() returned (summary["elastic"] mirrors this)."""

    kind: str        # "completed" | "preempted"
    resizes: int
    worlds: list[int]


class ElasticCoordinator:
    """Drives Trainer.fit across world incarnations.

    Injectable seams (hermetic CPU tests; production uses defaults):

    - ``source``: () -> WorldSpec | None — current world (file source in
      pods, a FakeCluster-annotation reader in tests).
    - ``form_world``: WorldSpec -> None — joins/re-forms the
      jax.distributed world (default: dist.initialize_from_env on the
      world's env; single-process worlds no-op there).
    - ``mesh_fn``: (TrainConfig, world_size) -> Mesh | None — the mesh
      for a world (default None: the Trainer builds from cfg over all
      visible devices, correct on real multi-host deployments where
      jax.devices() IS the world).
    """

    def __init__(
        self,
        source: Callable[[], "D.WorldSpec | None"],
        *,
        my_name: str | None = None,
        notice=None,
        batch_policy: str = BATCH_PRESERVE,
        form_world: "Callable[[D.WorldSpec], None] | None" = None,
        mesh_fn=None,
        join_timeout_s: float = 600.0,
        join_poll_s: float = 1.0,
        sleep=time.sleep,
        clock=time.monotonic,
    ):
        self.source = source
        self.my_name = my_name
        self.notice = notice
        self.batch_policy = batch_policy
        self.form_world = form_world if form_world is not None \
            else self._default_form_world
        self.mesh_fn = mesh_fn
        self.join_timeout_s = join_timeout_s
        self.join_poll_s = join_poll_s
        self._sleep = sleep
        self._clock = clock

    # -- world plumbing ------------------------------------------------------

    def world_env(self, world: D.WorldSpec,
                  base_env: dict | None = None) -> dict:
        """The JAXJOB_* env describing this worker's place in ``world``
        (rank = membership position, coordinator = members[0]).

        Slice-stamped worlds additionally override the pod's static
        JAXJOB_NUM_SLICES/SLICE_ID: after a slice shrink the SURVIVING
        slice set is smaller than the pod env's full-gang values, and
        the backend must re-form (and lay the dcn mesh axis) over
        survivors only. Slice ranks are renumbered dense (original ids
        stay in the world stamp; the env is the backend's view)."""
        env = dict(os.environ if base_env is None else base_env)
        env[D.ENV_NPROC] = str(world.size)
        if self.my_name is None:
            rank = 0  # untracked membership: single-pod/test contract
        else:
            rank = world.rank_of(self.my_name)
            if rank is None:
                raise WorldMembershipError(
                    f"{self.my_name} is not in world gen {world.gen} "
                    f"{world.members}")
        env[D.ENV_PID] = str(rank)
        if world.coordinator:
            env[D.ENV_COORD] = world.coordinator
        if world.slices is not None:
            survivors = sorted(set(world.slices))
            env[D.ENV_NUM_SLICES] = str(len(survivors))
            env[D.ENV_SLICE_ID] = str(survivors.index(world.slices[rank]))
        return env

    def _default_form_world(self, world: D.WorldSpec) -> None:
        D.initialize_from_env(self.world_env(world))

    def _member_world(self) -> "D.WorldSpec | None":
        """Current world IF this worker is a member (or membership is
        untracked because my_name is unset)."""
        w = self.source()
        if w is None:
            return None
        if self.my_name is not None and w.rank_of(self.my_name) is None:
            return None
        return w

    def wait_for_membership(self) -> D.WorldSpec:
        """The JOIN BARRIER: a replacement pod starts before the
        controller's grow resize names it a member; block until the
        world stamp includes us (the grow re-stamp) rather than join a
        world that did not plan for this rank."""
        deadline = self._clock() + self.join_timeout_s
        while True:
            w = self._member_world()
            if w is not None:
                return w
            if self._clock() > deadline:
                raise TimeoutError(
                    f"{self.my_name}: not admitted into the elastic world "
                    f"within {self.join_timeout_s}s")
            self._sleep(self.join_poll_s)

    def _stop_flag(self, world: D.WorldSpec) -> Callable[[], bool]:
        """Polled once per step by Trainer.fit: true on a real
        preemption notice OR a world stamp differing from the one this
        incarnation trained under — either way the trainer checkpoints
        the in-flight step and returns."""

        def stop() -> bool:
            if self.notice is not None and self.notice():
                return True
            cur = self.source()
            return cur is not None and \
                (cur.gen, cur.members) != (world.gen, world.members)

        return stop

    # -- run -----------------------------------------------------------------

    def run(self, cfg, *, full_world: int | None = None,
            callback=None, trainer_factory=None):
        """Train ``cfg`` to completion across resizes; returns
        (state, summary) like Trainer.fit, with summary["elastic"]
        describing the incarnations. cfg.checkpoint_dir must be set —
        the checkpoint IS the resize transport."""
        from kubeflow_tpu.runtime.trainer import Trainer

        if not cfg.checkpoint_dir:
            raise ValueError("elastic training requires checkpoint_dir "
                             "(the resize resumes from the checkpoint)")
        if not cfg.resume:
            # resume=False would make every resize silently retrain
            # from step 0 — the opposite of the continuity contract
            raise ValueError("elastic training requires resume=True "
                             "(a resized incarnation restores the "
                             "checkpointed step)")
        make_trainer = trainer_factory or (
            lambda c, world: Trainer(
                c, mesh=self.mesh_fn(c, world) if self.mesh_fn else None))
        # ALWAYS through the join barrier: a None source read at start
        # means the downward-API file has not synced yet (the launcher
        # only builds a coordinator when the controller wired the world
        # file), never "train solo" — a fabricated size-1 world would
        # have every not-yet-synced pod training as an independent
        # rank 0 against the shared checkpoint directory.
        world = self.wait_for_membership()
        if full_world is None:
            full_world = world.size
        worlds: list[int] = [world.size]
        resizes = 0
        state = summary = None
        from kubeflow_tpu.obs import trace as obs_trace

        # A RE-formation (any pass after the first) is resize-rebuild
        # time: teardown + world re-form + mesh/shardings/trainer
        # rebuild. The span feeds the goodput ledger's `resize_rebuild`
        # bucket (obs/goodput.py); the FIRST formation is cold start
        # and stays un-spanned (it lands in blocked_on_admission with
        # the rest of startup).
        rebuild_span = None

        def _finish_rebuild(status: str = "OK") -> None:
            nonlocal rebuild_span
            if rebuild_span is not None:
                rebuild_span.status = status
                obs_trace.TRACER.finish(rebuild_span)
                rebuild_span = None

        while True:
            if resizes and rebuild_span is None:
                rebuild_span = obs_trace.TRACER.begin(
                    "elastic.rebuild", gen=world.gen, size=world.size)
            try:
                self.form_world(world)
            except Exception as e:
                # formation at a STALE world. The canonical case is
                # partial admission: pods carry the full-gang stamp at
                # creation, and the controller's shrink-to-admitted
                # re-stamp lands while initialize blocks waiting for
                # peers that were never admitted. If the stamp moved
                # while we were blocked, retry at the CURRENT world —
                # crashing here would read as a non-75 exit and burn
                # the restart budget. A failure with an unchanged stamp
                # is a genuine bootstrap error and propagates.
                cur = self._member_world()
                if cur is None or (cur.gen, cur.members) == \
                        (world.gen, world.members):
                    _finish_rebuild("ERROR")
                    raise
                # the stamp moved: the retry below is STILL rebuild
                # time — the open span keeps covering it
                log.warning(
                    "world formation at size %d failed (%s: %s); the "
                    "world moved to gen %d size %d — retrying there",
                    world.size, type(e).__name__, e, cur.gen, cur.size)
                D.shutdown()  # no-op after a failed init; typed on real failure
                world = cur
                worlds.append(world.size)
                resizes += 1
                continue
            try:
                # scale_config inside the try: the Scale policy's
                # divisibility error on a resized world needs the same
                # exit-for-restart treatment as an unbuildable trainer
                wcfg = scale_config(cfg, full_world, world.size,
                                    self.batch_policy)
                trainer = make_trainer(wcfg, world.size)
            except ValueError:
                if world.size == full_world:
                    _finish_rebuild("ERROR")
                    raise  # a bad config at FULL size fails loudly
                # the RESIZED world is incompatible with the config
                # (e.g. global_batch not divisible by the survivor
                # count): crashing here would burn the restart budget
                # through a crash loop — exit EX_TEMPFAIL instead, so
                # the controller gang-restarts at the full size and the
                # checkpoint survives. docs/elastic.md: pick a
                # global_batch divisible by every world size you allow.
                log.exception(
                    "world of %d is incompatible with the config; "
                    "exiting for a gang restart instead of crash-looping",
                    world.size)
                exit_ = ResizeExit("preempted", resizes, worlds)
                _finish_rebuild("ERROR")
                break
            _finish_rebuild()  # re-formation + rebuild done: fit resumes
            state, summary = trainer.fit(stop=self._stop_flag(world),
                                         callback=callback)
            if not summary.get("preempted"):
                exit_ = ResizeExit("completed", resizes, worlds)
                break
            # fit stopped early: a resize signal, a real preemption
            # notice, or both. The checkpoint at the interrupted step is
            # already durable (fit's stop path saved it).
            new = self._member_world()
            resized = new is not None and \
                (new.gen, new.members) != (world.gen, world.members)
            if self.notice is not None and self.notice():
                # SIGTERM means THIS pod is being terminated: always
                # exit EX_TEMPFAIL for the gang restart. Re-forming in
                # place would burn the remaining grace on a tear-down/
                # re-init/restore cycle whose stop flag is already set
                # (the notice is sticky) — pure wasted SIGKILL risk.
                grace = self.notice.remaining_grace()
                log.warning(
                    "preemption notice (%s grace left%s): exiting for "
                    "a gang restart",
                    f"{grace:.1f}s" if grace is not None else "unknown",
                    "; resize pending" if resized else "")
                exit_ = ResizeExit("preempted", resizes, worlds)
                break
            if not resized:
                # stop fired with neither a notice nor a stamp change
                # (a source flicker): exiting for a restart is the safe
                # answer — the checkpoint at this step is durable
                exit_ = ResizeExit("preempted", resizes, worlds)
                break
            # in-place re-formation: tear down the old world first (the
            # dist re-entrancy contract). If teardown fails, in-place
            # resize is off the table — fall back to exit-and-restart.
            try:
                D.shutdown()
            except D.WorldTeardownError:
                log.exception("world teardown failed; exiting for a "
                              "gang restart instead")
                exit_ = ResizeExit("preempted", resizes, worlds)
                break
            log.info("elastic resize: world %d (gen %d) -> %d (gen %d), "
                     "resuming from the checkpoint",
                     world.size, world.gen, new.size, new.gen)
            world = new
            worlds.append(world.size)
            resizes += 1
        summary = dict(summary or {})  # None: never reached a fit()
        summary["elastic"] = {"exit": exit_.kind, "resizes": exit_.resizes,
                              "worlds": exit_.worlds}
        if exit_.kind == "preempted":
            summary["preempted"] = True
        else:
            summary.pop("preempted", None)
        return state, summary


def scale_config(cfg, full_world: int, world: int, policy: str):
    """TrainConfig for one world incarnation.

    Preserve (default): the global batch — and therefore the loss curve
    and the optimizer's schedule semantics — is IDENTICAL at every
    world size; a shrunken world pays more wall time per step instead
    (each device holds a larger batch shard). A config ALREADY using
    gradient accumulation gets grad_accum_steps scaled up (when
    divisibility allows) so the per-device microbatch stays constant;
    accumulation is never INTRODUCED by a resize — splitting a batch
    that used to run in one shot would silently change BatchNorm-style
    per-batch statistics, breaking the very loss-curve continuity
    Preserve promises.

    Scale: the global batch scales linearly with the world (classic
    throughput-first elasticity; the loss curve changes and the LR
    schedule is the caller's to re-tune — documented in
    docs/elastic.md)."""
    if policy not in (BATCH_PRESERVE, BATCH_SCALE):
        raise ValueError(f"unknown batch policy {policy!r}")
    if world == full_world:
        return cfg
    if policy == BATCH_SCALE:
        scaled = cfg.global_batch * world
        if scaled % full_world:
            raise ValueError(
                f"global_batch {cfg.global_batch} x {world}/{full_world} "
                f"is not integral; Scale policy needs divisibility")
        return dataclasses.replace(cfg, global_batch=scaled // full_world)
    base = cfg.grad_accum_steps
    if base <= 1:
        return cfg  # single-shot stays single-shot (see docstring)
    scaled = base * full_world
    accum = scaled // world if scaled % world == 0 else base
    if cfg.global_batch % accum:
        accum = base  # keep the global batch; memory scaling is best-effort
    return dataclasses.replace(cfg, grad_accum_steps=accum)
