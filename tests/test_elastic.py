"""Elastic gangs (ISSUE 6): shrink-to-survivors on preemption, grow-back
on readmission, spot pools, and checkpoint resharding.

Layers under test:

- spec.elastic validation + the elastic pod surface (spot toleration,
  downward-API world projection, scheduler elastic-min annotation);
- the JAXJob controller's resize path: preemption/node-loss/vanish
  shrink WITHOUT burning maxRestarts/maxPreemptions, grow-back when
  replacements come up, elastic completion, world reset on gang restart;
- scheduler spot pools (tainted, preferred for elastic gangs) and
  partial admission down to minReplicas (all-or-nothing stays the law
  for rigid gangs) + the grow-back queue semantics;
- parallel/dist.py re-entrant world formation;
- runtime/preemption.py grace deadlines;
- property-style checkpoint resharding: save at world N, restore at
  M != N, bitwise-equal unsharded params + optimizer state;
- the hermetic CPU e2e: a 4-worker elastic job loses 2 workers
  mid-training, shrinks, continues from the checkpointed step with a
  CONTINUOUS loss curve, and grows back to 4 on readmission.
"""

import json
import os

import numpy as np
import pytest

import test_scheduler as S

from kubeflow_tpu.control.jaxjob import types as T
from kubeflow_tpu.control.jaxjob.controller import (
    build_controller, job_world, worker_name,
)
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.runtime import seed_controller
from kubeflow_tpu.control.scheduler import (
    ANNOTATION_ELASTIC_MIN, GATE_GANG, LABEL_SPOT,
)
from kubeflow_tpu.control.scheduler.nodes import (
    feasible, new_tpu_node, node_view, spot_taint,
)
from kubeflow_tpu.control.scheduler.scheduler import build_scheduler
from kubeflow_tpu.parallel import dist
from kubeflow_tpu.runtime import elastic
from kubeflow_tpu.runtime.metrics import MetricsRegistry
from kubeflow_tpu.runtime.preemption import PreemptionNotice

pytestmark = pytest.mark.elastic

TOPOLOGY_FOR = {1: "2x2", 2: "2x4", 3: "3x4", 4: "4x4"}


@pytest.fixture(autouse=True)
def _no_compile_cache():
    """This image's jaxlib corrupts the heap ("corrupted double-linked
    list" / segfault in a later pjit) when the persistent compilation
    cache is combined with meshes over device SUBSETS — the same
    pre-existing crash family that kills tests/test_checkpoint.py here.
    Elastic resizes are exactly subset meshes, so this file opts out of
    the (pure-speedup, conftest-enabled) cache for its duration and
    restores it afterwards."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def elastic_job(name="train", replicas=4, elastic_min=2, **kw):
    return T.new_jaxjob(
        name, replicas=replicas,
        accelerator=kw.pop("accelerator", "tpu-v5-lite-podslice"),
        topology=kw.pop("topology", TOPOLOGY_FOR[replicas]),
        chips_per_worker=kw.pop("chips_per_worker", 4),
        elastic_min=elastic_min, **kw)


# -- spec validation ---------------------------------------------------------


class TestElasticSpec:
    def test_valid_elastic_spec(self):
        assert T.validate(elastic_job()) == []
        el = T.elastic_spec(elastic_job()["spec"])
        assert el == {"minReplicas": 2, "maxReplicas": 4,
                      "resizePolicy": T.RESIZE_RESIZE,
                      "batchPolicy": T.BATCH_PRESERVE,
                      "maxResizes": T.DEFAULT_MAX_RESIZES,
                      "slicePolicy": T.SLICE_RESTART,
                      "minSlices": 1}
        assert T.is_elastic(elastic_job()["spec"])
        assert not T.is_elastic(T.new_jaxjob("rigid")["spec"])

    def test_min_above_max_rejected(self):
        job = elastic_job(elastic_min=5)
        assert any("minReplicas 5 > maxReplicas 4" in e
                   for e in T.validate(job))

    def test_max_must_equal_gang_size(self):
        job = elastic_job()
        job["spec"]["elastic"]["maxReplicas"] = 3
        assert any("must equal replicas x sliceCount" in e
                   for e in T.validate(job))

    def test_multislice_resize_rejected(self):
        # worker-granular Resize on a multislice gang: the pre-slice
        # spelling gets a MIGRATION error pointing at slicePolicy, not
        # a silent behavior change
        job = T.new_jaxjob("ms", replicas=2, slice_count=2,
                           accelerator="tpu-v5-lite-podslice",
                           topology="2x4", chips_per_worker=4,
                           elastic_min=2)
        job["spec"]["elastic"]["maxReplicas"] = 4
        assert any("add elastic.slicePolicy" in e for e in T.validate(job))
        # resizePolicy Restart (spot opt-in only) IS allowed multislice
        job["spec"]["elastic"]["resizePolicy"] = T.RESIZE_RESTART
        assert T.validate(job) == []
        assert not T.is_elastic(job["spec"])

    def test_multislice_slice_policy_shrink_accepted(self):
        job = T.new_jaxjob("ms", replicas=2, slice_count=2,
                           accelerator="tpu-v5-lite-podslice",
                           topology="2x4", chips_per_worker=4,
                           elastic_min=2,
                           slice_policy=T.SLICE_SHRINK, min_slices=1)
        job["spec"]["elastic"]["maxReplicas"] = 4
        assert T.validate(job) == []
        assert T.is_slice_elastic(job["spec"])
        assert T.is_elastic(job["spec"])
        # floor is slice-granular: minSlices x replicas
        assert T.elastic_floor(job["spec"]) == 2
        # bad values are rejected with field-specific messages
        job["spec"]["elastic"]["slicePolicy"] = "Halve"
        assert any("slicePolicy must be" in e for e in T.validate(job))
        job["spec"]["elastic"]["slicePolicy"] = T.SLICE_SHRINK
        job["spec"]["elastic"]["minSlices"] = 3
        assert any("minSlices 3 > sliceCount 2" in e
                   for e in T.validate(job))

    @pytest.mark.parametrize("field,value,needle", [
        ("minReplicas", 0, "positive int"),
        ("minReplicas", True, "positive int"),
        ("resizePolicy", "Shrink", "resizePolicy"),
        ("batchPolicy", "Halve", "batchPolicy"),
        ("maxResizes", 0, "maxResizes"),
    ])
    def test_bad_fields_rejected(self, field, value, needle):
        job = elastic_job()
        job["spec"]["elastic"][field] = value
        assert any(needle in e for e in T.validate(job)), T.validate(job)

    def test_elastic_must_be_object(self):
        job = elastic_job()
        job["spec"]["elastic"] = "yes"
        assert any("must be an object" in e for e in T.validate(job))

    def test_resize_with_user_command_rejected(self):
        # a payload after "--" never runs the ElasticCoordinator, so it
        # could not follow a resize — reject at admission
        cmd = ["python", "-m", "kubeflow_tpu.runtime.launcher",
               "--", "python", "train.py"]
        job = elastic_job(command=cmd)
        assert any("built-in trainer" in e for e in T.validate(job))
        # Restart (spot opt-in, whole-gang restart semantics) is fine
        job["spec"]["elastic"]["resizePolicy"] = T.RESIZE_RESTART
        assert T.validate(job) == []
        # and so is the built-in trainer even with a trailing "--"
        job2 = elastic_job(command=[
            "python", "-m", "kubeflow_tpu.runtime.launcher",
            "--config", "/etc/cfg.yaml"])
        assert T.validate(job2) == []


# -- the elastic pod surface -------------------------------------------------


@pytest.fixture()
def world():
    cluster = FakeCluster()
    ctl = seed_controller(build_controller(cluster, record_events=True))
    kubelet = FakeKubelet(cluster)
    return cluster, ctl, kubelet


def drain(ctl, rounds=6):
    for _ in range(rounds):
        ctl.run_until_idle(advance_delayed=True)


def job_status(cluster, name="train"):
    return cluster.get(T.API_VERSION, T.KIND, name, "default")["status"]


def pod_world(cluster, pod_name) -> dist.WorldSpec:
    p = cluster.get("v1", "Pod", pod_name, "default")
    return dist.WorldSpec.from_json(
        ob.annotations_of(p).get(T.ANNOTATION_WORLD))


class TestElasticPodSurface:
    def test_elastic_pods_carry_the_resize_contract(self, world):
        cluster, ctl, _ = world
        cluster.create(elastic_job())
        drain(ctl)
        p = cluster.get("v1", "Pod", worker_name("train", 1), "default")
        # spot toleration: elastic workers may land on reclaimable pools
        assert {"key": LABEL_SPOT, "operator": "Equal", "value": "true",
                "effect": "NoSchedule"} in p["spec"]["tolerations"]
        # the initial world stamp: full gang, gen 0, rank order
        w = pod_world(cluster, worker_name("train", 1))
        assert w.gen == 0 and w.size == 4
        assert w.members == tuple(worker_name("train", i) for i in range(4))
        assert w.coordinator == "train-worker-0.train.default.svc:8476"
        # downward-API projection + env pointing the worker at it
        env = {e["name"]: e["value"]
               for e in p["spec"]["containers"][0]["env"]}
        assert env[T.ENV_WORLD_FILE] == T.WORLD_FILE_PATH
        assert env[T.ENV_BATCH_POLICY] == T.BATCH_PRESERVE
        vol = next(v for v in p["spec"]["volumes"]
                   if v["name"] == "jaxjob-world")
        assert T.ANNOTATION_WORLD in \
            vol["downwardAPI"]["items"][0]["fieldRef"]["fieldPath"]
        assert any(m["name"] == "jaxjob-world"
                   for m in p["spec"]["containers"][0]["volumeMounts"])

    def test_gang_scheduled_elastic_pods_carry_the_floor(self, world):
        cluster, ctl, _ = world
        cluster.create(elastic_job(gang_schedule=True))
        drain(ctl)
        p = cluster.get("v1", "Pod", worker_name("train", 0), "default")
        assert ob.annotations_of(p)[ANNOTATION_ELASTIC_MIN] == "2"

    def test_rigid_pods_carry_none_of_it(self, world):
        cluster, ctl, _ = world
        cluster.create(T.new_jaxjob("train", replicas=2,
                                    accelerator="tpu-v5-lite-podslice",
                                    topology="2x4", chips_per_worker=4,
                                    gang_schedule=True))
        drain(ctl)
        p = cluster.get("v1", "Pod", worker_name("train", 0), "default")
        assert not p["spec"].get("tolerations")
        ann = ob.annotations_of(p)
        assert T.ANNOTATION_WORLD not in ann
        assert ANNOTATION_ELASTIC_MIN not in ann
        env = {e["name"] for e in p["spec"]["containers"][0]["env"]}
        assert T.ENV_WORLD_FILE not in env

    def test_restart_policy_opts_into_spot_but_not_resize(self, world):
        cluster, ctl, _ = world
        cluster.create(elastic_job(resize_policy=T.RESIZE_RESTART))
        drain(ctl)
        p = cluster.get("v1", "Pod", worker_name("train", 0), "default")
        assert p["spec"].get("tolerations")  # spot opt-in stays
        assert T.ANNOTATION_WORLD not in ob.annotations_of(p)
        env = {e["name"] for e in p["spec"]["containers"][0]["env"]}
        assert T.ENV_WORLD_FILE not in env


# -- controller resize path --------------------------------------------------


class TestShrinkToSurvivors:
    def _running_gang(self, world, **kw):
        cluster, ctl, kubelet = world
        cluster.create(elastic_job(**kw))
        drain(ctl)
        kubelet.step()
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_RUNNING)
        return job

    def test_preemption_shrinks_without_burning_budgets(self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world)
        for i in (1, 3):
            kubelet.fail(worker_name("train", i),
                         exit_code=T.EXIT_PREEMPTED, message="reclaimed")
        drain(ctl)
        st = job_status(cluster)
        assert st.get("restarts", 0) == 0
        assert st.get("preemptions", 0) == 0
        assert st["resizes"] == 1
        assert st["activeReplicas"] == 2
        assert st["world"]["members"] == [worker_name("train", 0),
                                          worker_name("train", 2)]
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_get(job, T.COND_RESIZING)["status"] == "True"
        # job stays Running: the survivors never stopped training
        assert ob.cond_is_true(job, T.COND_RUNNING)
        # survivors re-stamped with the shrunken world
        w = pod_world(cluster, worker_name("train", 2))
        assert w.gen == 1 and w.members == (worker_name("train", 0),
                                            worker_name("train", 2))
        # lost workers replaced by fresh Pending pods (the grow queue)
        phases = {ob.meta(p)["name"]: (p.get("status") or {}).get(
            "phase", "Pending")
            for p in cluster.list("v1", "Pod", namespace="default")}
        assert phases == {worker_name("train", 0): "Running",
                          worker_name("train", 1): "Pending",
                          worker_name("train", 2): "Running",
                          worker_name("train", 3): "Pending"}
        reasons = {e["reason"] for e in cluster.list(
            "v1", "Event", namespace="default")}
        assert "GangShrunk" in reasons and "GangRestart" not in reasons

    def test_grow_back_when_replacements_run(self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world)
        for i in (1, 3):
            kubelet.fail(worker_name("train", i),
                         exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        kubelet.step()  # capacity back: replacements run
        drain(ctl)
        st = job_status(cluster)
        assert st["resizes"] == 2
        assert st["activeReplicas"] == 4
        assert st.get("restarts", 0) == 0 and st.get("preemptions", 0) == 0
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_get(job, T.COND_RESIZING)["status"] == "False"
        w = pod_world(cluster, worker_name("train", 1))
        assert w.gen == 2 and w.size == 4
        reasons = {e["reason"] for e in cluster.list(
            "v1", "Event", namespace="default")}
        assert "GangGrown" in reasons

    def test_resize_metric_counts_directions(self, world):
        import prometheus_client as prom

        def sample(direction):
            return prom.REGISTRY.get_sample_value(
                "jaxjob_resizes_total",
                {"direction": direction}) or 0.0

        cluster, ctl, kubelet = world
        before = sample("shrink"), sample("grow")
        self._running_gang(world)
        kubelet.fail(worker_name("train", 0), exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        assert sample("shrink") == before[0] + 1
        assert sample("grow") == before[1] + 1

    def test_crash_still_burns_the_restart_budget(self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world)
        kubelet.fail(worker_name("train", 1), exit_code=1)
        drain(ctl)
        st = job_status(cluster)
        assert st.get("restarts", 0) == 1  # a bug is a bug, elastic or not
        assert "resizes" not in st

    def test_shrink_below_min_falls_back_to_preemption_restart(self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world, elastic_min=2)
        for i in (0, 1, 3):
            kubelet.fail(worker_name("train", i),
                         exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        st = job_status(cluster)
        assert st.get("preemptions", 0) == 1  # whole-gang preemption restart
        assert "resizes" not in st
        assert st.get("restarts", 0) == 0

    def test_vanished_worker_shrinks_instead_of_restarting(self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world)
        cluster.delete("v1", "Pod", worker_name("train", 2), "default")
        drain(ctl)
        st = job_status(cluster)
        assert st.get("restarts", 0) == 0 and st.get("preemptions", 0) == 0
        assert st["resizes"] == 1
        assert st["world"]["members"] == [worker_name("train", i)
                                          for i in (0, 1, 3)]
        # the vanished index was re-provisioned for grow-back
        p = cluster.get("v1", "Pod", worker_name("train", 2), "default")
        assert (p.get("status") or {}).get("phase", "Pending") == "Pending"

    def test_node_loss_condemns_only_the_lost_pods(self, world):
        cluster, ctl, kubelet = world
        cluster.create(elastic_job())
        drain(ctl)
        for node in ("tpu-a", "tpu-b"):
            n = ob.new_object("v1", "Node", node)
            n["status"] = {"conditions": [
                {"type": "Ready", "status": "True"}]}
            cluster.create(n)
        for i in range(4):
            p = cluster.get("v1", "Pod", worker_name("train", i), "default")
            p["spec"]["nodeName"] = "tpu-a" if i < 2 else "tpu-b"
            cluster.update(p)
        kubelet.step()
        drain(ctl)
        node = cluster.get("v1", "Node", "tpu-b")
        node["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
        cluster.update_status(node)
        drain(ctl)
        st = job_status(cluster)
        assert st.get("preemptions", 0) == 0  # would be 1 pre-elastic
        assert st["resizes"] == 1
        assert st["world"]["members"] == [worker_name("train", 0),
                                          worker_name("train", 1)]
        # the coordinator survived on tpu-a; workers 2,3 were condemned
        # and re-provisioned
        phases = {ob.meta(p)["name"]: (p.get("status") or {}).get(
            "phase", "Pending")
            for p in cluster.list("v1", "Pod", namespace="default")}
        assert phases[worker_name("train", 0)] == "Running"
        assert phases[worker_name("train", 2)] == "Pending"

    def test_coordinator_loss_elects_new_coordinator(self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world)
        kubelet.fail(worker_name("train", 0), exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        st = job_status(cluster)
        # worker 0 died: the new world's coordinator is its first member
        assert st["world"]["members"][0] == worker_name("train", 1)
        assert st["world"]["coordinator"].startswith(
            f"{worker_name('train', 1)}.train.default.svc:")

    def test_completion_with_running_replacement_still_completes(
            self, world):
        """Members finish while a grow-back replacement has just come
        up (Running, stuck in its join barrier — a grow re-stamp can
        never happen once the members exited): the job must complete
        and reap the replacement, not stall until its join timeout
        crashes it into the restart budget."""
        cluster, ctl, kubelet = world
        self._running_gang(world)
        for i in (1, 3):
            kubelet.fail(worker_name("train", i),
                         exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        # members succeed FIRST...
        for i in (0, 2):
            kubelet.succeed(worker_name("train", i))
        # ...and the replacements start in the same instant
        kubelet.step()
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_SUCCEEDED)
        names = {ob.meta(p)["name"]
                 for p in cluster.list("v1", "Pod", namespace="default")}
        assert names == {worker_name("train", 0), worker_name("train", 2)}
        st = job_status(cluster)
        assert st.get("restarts", 0) == 0 and st.get("preemptions", 0) == 0

    def test_shrunken_world_completion_succeeds_and_reaps_leftovers(
            self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world)
        for i in (1, 3):
            kubelet.fail(worker_name("train", i),
                         exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        # the shrunken world finishes before capacity ever returns
        for i in (0, 2):
            kubelet.succeed(worker_name("train", i))
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_SUCCEEDED)
        assert not ob.cond_is_true(job, T.COND_FAILED)
        # waiting replacements were reaped, never run
        names = {ob.meta(p)["name"]
                 for p in cluster.list("v1", "Pod", namespace="default")}
        assert names == {worker_name("train", 0), worker_name("train", 2)}

    def test_gang_restart_resets_the_world_to_full(self, world):
        cluster, ctl, kubelet = world
        self._running_gang(world)
        kubelet.fail(worker_name("train", 1), exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        assert job_status(cluster)["world"]["size"] == 3
        # now a real crash: the whole (shrunken) gang restarts at FULL size
        kubelet.fail(worker_name("train", 2), exit_code=1)
        drain(ctl)
        st = job_status(cluster)
        assert st.get("restarts", 0) == 1
        assert "world" not in st and "activeReplicas" not in st
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_get(job, T.COND_RESIZING)["status"] == "False"
        drain(ctl)
        pods = cluster.list("v1", "Pod", namespace="default")
        assert len(pods) == 4
        assert job_world(job).size == 4

    def test_succeeded_member_on_dead_node_is_not_a_resize(self, world):
        """A node dying under an already-Succeeded member condemns
        nothing: no resize (the finished member must not be shrunk out,
        disrupting every running worker), no restart, and no 0.05s
        reconcile hot loop — completion handles the member's exit."""
        cluster, ctl, kubelet = world
        cluster.create(elastic_job())
        drain(ctl)
        for node in ("tpu-a", "tpu-b"):
            n = ob.new_object("v1", "Node", node)
            n["status"] = {"conditions": [
                {"type": "Ready", "status": "True"}]}
            cluster.create(n)
        for i in range(4):
            p = cluster.get("v1", "Pod", worker_name("train", i), "default")
            p["spec"]["nodeName"] = "tpu-b" if i == 3 else "tpu-a"
            cluster.update(p)
        kubelet.step()
        drain(ctl)
        kubelet.succeed(worker_name("train", 3))
        drain(ctl)
        # worker 3's node dies AFTER it finished
        node = cluster.get("v1", "Node", "tpu-b")
        node["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
        cluster.update_status(node)
        drain(ctl)
        st = job_status(cluster)
        assert "resizes" not in st
        assert st.get("restarts", 0) == 0 and st.get("preemptions", 0) == 0
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert not ob.cond_is_true(job, T.COND_RESTARTING)
        # and the job still completes normally
        for i in range(3):
            kubelet.succeed(worker_name("train", i))
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_SUCCEEDED)

    def test_resize_ceiling_falls_back_to_restart_semantics(self, world):
        cluster, ctl, kubelet = world
        cluster.create(elastic_job())
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        job["spec"]["elastic"]["maxResizes"] = 1
        cluster.update(job)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        kubelet.fail(worker_name("train", 3), exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        assert job_status(cluster)["resizes"] == 1
        # the shrink consumed the LAST resize: no replacement pod is
        # provisioned — it could never be admitted into the world (a
        # grow re-stamp needs a resize) and would die by join-barrier
        # timeout, tearing down the healthy shrunken world
        names = {ob.meta(p)["name"]
                 for p in cluster.list("v1", "Pod", namespace="default")}
        assert names == {worker_name("train", i) for i in range(3)}
        kubelet.step()
        drain(ctl)
        st = job_status(cluster)
        assert st["resizes"] == 1  # ceiling holds
        # next preemption: ceiling spent => normal preemption restart
        kubelet.fail(worker_name("train", 0), exit_code=T.EXIT_PREEMPTED)
        drain(ctl)
        st = job_status(cluster)
        assert st.get("preemptions", 0) == 1


def test_worker_index_unparseable_sorts_last():
    """A pod name that does not parse must never alias to replica 0 —
    that would let a malformed leftover steal the coordinator slot in
    world-membership ordering and the partial-admission prefix. It
    sorts after every real replica instead."""
    from kubeflow_tpu.control.jaxjob.controller import worker_index

    names = ["train-worker-10", "leftover", "train-worker-2",
             "train-worker-0"]
    assert sorted(names, key=worker_index) == [
        "train-worker-0", "train-worker-2", "train-worker-10", "leftover"]


def test_recreate_indices_only_real_replica_slots():
    """Lost-pod recreate lists must carry only real replica slots: an
    unparseable name (worker_index's sort sentinel) or an out-of-range
    index has no slot to re-provision — passing it through would
    create a bogus '<job>-worker-<sentinel>' pod on every shrink."""
    from kubeflow_tpu.control.jaxjob.controller import recreate_indices

    pods = [{"metadata": {"name": n}}
            for n in ["train-worker-3", "leftover", "train-worker-1",
                      "train-worker-9"]]
    assert recreate_indices(pods, 4) == [3, 1]


# -- scheduler: spot pools + partial admission -------------------------------


def gang_elastic_job(name="train", replicas=4, elastic_min=2, **kw):
    return elastic_job(name, replicas=replicas, elastic_min=elastic_min,
                       gang_schedule=True, **kw)


def sched_world(fc):
    cluster = FakeCluster()
    registry = MetricsRegistry()
    jax_ctl = seed_controller(build_controller(cluster, record_events=False))
    sched_ctl = seed_controller(build_scheduler(
        cluster, registry=registry, record_events=False, clock=fc))
    kubelet = FakeKubelet(cluster, auto_bind=False)
    return cluster, jax_ctl, sched_ctl, kubelet, registry


def pump(ctls, fc, kubelet=None, rounds=10):
    for _ in range(rounds):
        for c in ctls:
            c.run_until_idle(advance_delayed=True)
        if kubelet is not None:
            kubelet.step()
        fc.advance(1.0)


def bindings(cluster):
    return {ob.meta(p)["name"]: p["spec"].get("nodeName")
            for p in cluster.list("v1", "Pod", namespace="default")}


class TestSpotPools:
    def test_spot_node_surface(self):
        node = new_tpu_node("s0", topology="2x4", spot=True)
        v = node_view(node)
        assert v.spot
        assert v.labels[LABEL_SPOT] == "true"
        assert spot_taint() in [dict(t) for t in v.taints]
        assert not node_view(new_tpu_node("n0")).spot

    def test_rigid_pods_never_land_on_spot(self):
        # the taint alone keeps untolerating (rigid) workers off
        v = node_view(new_tpu_node("s0", topology="2x4", spot=True))
        pod = {"spec": {"containers": [{"name": "jax"}],
                        "nodeSelector": {
                            T.NODESELECTOR_ACCEL: "tpu-v5-lite-podslice",
                            T.NODESELECTOR_TOPOLOGY: "2x4"}}}
        assert not feasible(pod, v)
        # the elastic toleration (the one generate_pod adds) opens it
        pod["spec"]["tolerations"] = [
            {"key": LABEL_SPOT, "operator": "Equal", "value": "true",
             "effect": "NoSchedule"}]
        assert feasible(pod, v)

    def test_elastic_gang_prefers_spot_nodes(self):
        fc = S.FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        # spot and on-demand both feasible; elastic workers must pack
        # onto spot, leaving on-demand for rigid work
        for i in range(2):
            cluster.create(new_tpu_node(f"ond{i}", topology="2x4"))
        for i in range(2):
            cluster.create(new_tpu_node(f"spot{i}", topology="2x4",
                                        spot=True))
        cluster.create(gang_elastic_job(replicas=2, elastic_min=1))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        assert sorted(b.values()) == ["spot0", "spot1"], b
        assert 'scheduler_spot_admissions_total{namespace="default"} 1.0' \
            in reg.render()

    def test_spot_is_preferred_not_required(self):
        fc = S.FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("spot0", topology="2x4", spot=True))
        cluster.create(new_tpu_node("ond0", topology="2x4"))
        cluster.create(gang_elastic_job(replicas=2, elastic_min=1))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        # spot pool (1 host) can't fit both: one worker overflows to
        # on-demand rather than the gang waiting
        assert sorted(bindings(cluster).values()) == ["ond0", "spot0"]


class TestPartialAdmission:
    def test_elastic_gang_admits_down_to_the_floor(self):
        fc = S.FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        for i in range(2):
            cluster.create(new_tpu_node(f"n{i}", topology="4x4"))
        cluster.create(gang_elastic_job())  # 4 workers, floor 2, 2 hosts
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        bound = {k for k, v in b.items() if v}
        # lowest indices bound (worker 0 — the coordinator pick — first)
        assert bound == {worker_name("train", 0), worker_name("train", 1)}
        # the controller started the world at the admitted size
        st = job_status(cluster)
        assert st["activeReplicas"] == 2
        assert st["world"]["members"] == sorted(bound)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_RUNNING)
        assert ob.cond_get(job, T.COND_RESIZING)["status"] == "True"
        # the remainder still queued (gated) for grow-back
        for i in (2, 3):
            p = cluster.get("v1", "Pod", worker_name("train", i), "default")
            assert any(g["name"] == GATE_GANG
                       for g in p["spec"]["schedulingGates"])

    def test_rigid_gang_keeps_the_all_or_nothing_law(self):
        fc = S.FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        for i in range(2):
            cluster.create(new_tpu_node(f"n{i}", topology="4x4"))
        cluster.create(S.gang_job("rigid", replicas=4, topology="4x4"))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert all(v is None for v in bindings(cluster).values())

    def test_grow_back_binds_the_remainder_when_capacity_returns(self):
        fc = S.FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        for i in range(2):
            cluster.create(new_tpu_node(f"n{i}", topology="4x4"))
        cluster.create(gang_elastic_job())
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert job_status(cluster)["activeReplicas"] == 2
        for i in range(2, 4):
            cluster.create(new_tpu_node(f"n{i}", topology="4x4"))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        st = job_status(cluster)
        assert st["activeReplicas"] == 4
        assert st["resizes"] == 2  # shrink-start + grow-back
        assert st.get("restarts", 0) == 0 and st.get("preemptions", 0) == 0
        assert all(v for v in bindings(cluster).values())
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_get(job, T.COND_RESIZING)["status"] == "False"

    def test_partial_prefix_keeps_numeric_index_order(self):
        """12-worker gang, room for 5: the admitted prefix must be
        workers 0-4 by NUMERIC index (plain name order would pick
        0,1,10,11,2 — stranding the coordinator's low-rank block)."""
        from kubeflow_tpu.control.scheduler.nodes import node_view
        from kubeflow_tpu.control.scheduler.queue import GangQueue
        from kubeflow_tpu.control.scheduler.scheduler import GangScheduler

        sched = GangScheduler(queue=GangQueue(clock=S.FakeClock()),
                              registry=MetricsRegistry(),
                              record_events=False)
        views = {f"n{i}": node_view(new_tpu_node(f"n{i}", topology="4x4"))
                 for i in range(5)}
        free = {n: v.allocatable_chips for n, v in views.items()}

        def mk(i):
            pod = ob.new_object("v1", "Pod", f"train-worker-{i}", "default")
            pod["spec"] = {"containers": [{"name": "jax", "resources": {
                "limits": {T.RESOURCE_TPU: 4}}}]}
            return pod

        pods = sorted((mk(i) for i in range(12)),
                      key=lambda p: ob.meta(p)["name"])  # lexicographic in
        a = sched._assign_partial(pods, views, free, floor=2)
        assert a is not None
        assert sorted(a) == [f"train-worker-{i}" for i in range(5)]
        # below the floor: nothing placeable at all
        assert sched._assign_partial(pods, {}, {}, floor=2) is None

    def test_waiting_gang_does_not_head_block_its_namespace(self):
        fc = S.FakeClock()
        cluster, jax_ctl, sched_ctl, kubelet, reg = sched_world(fc)
        cluster.create(new_tpu_node("n0", topology="4x4"))
        cluster.create(new_tpu_node("n1", topology="4x4"))
        # elastic gang partially admitted, remainder waiting to grow
        cluster.create(gang_elastic_job("first"))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        assert job_status(cluster, "first")["activeReplicas"] == 2
        # a later rigid gang on a DIFFERENT pool must admit even though
        # "first" is queued ahead of it and cannot use that pool
        cluster.create(new_tpu_node("other0", topology="2x2"))
        cluster.create(S.gang_job("second", replicas=1, topology="2x2",
                                  chips=4))
        pump([jax_ctl, sched_ctl], fc, kubelet)
        b = bindings(cluster)
        assert b[worker_name("second", 0)] == "other0", b


# -- dist: re-entrant world formation ----------------------------------------


class TestDistReentry:
    @pytest.fixture(autouse=True)
    def _clean_world_state(self):
        dist._ACTIVE = None
        dist._DIST_LIVE = False
        yield
        dist._ACTIVE = None
        dist._DIST_LIVE = False

    def test_idempotent_same_world(self):
        cfg1 = dist.initialize_from_env({})
        cfg2 = dist.initialize_from_env({})
        assert cfg1 == cfg2
        assert dist.active_world() == cfg2

    def test_reinit_distributed_world_tears_down_first(self, monkeypatch):
        calls = []
        monkeypatch.setattr(dist, "_jax_initialize",
                            lambda cfg: calls.append(("init", cfg.num_processes)))
        monkeypatch.setattr(dist, "_jax_shutdown",
                            lambda: calls.append(("shutdown", None)))
        env4 = {dist.ENV_COORD: "c:1", dist.ENV_NPROC: "4",
                dist.ENV_PID: "0"}
        dist.initialize_from_env(env4, wait=False)
        assert calls == [("init", 4)]
        # same world again: idempotent, no re-init
        dist.initialize_from_env(env4, wait=False)
        assert calls == [("init", 4)]
        # shrunken world: teardown THEN re-init
        env2 = {dist.ENV_COORD: "c:1", dist.ENV_NPROC: "2",
                dist.ENV_PID: "0"}
        cfg = dist.initialize_from_env(env2, wait=False)
        assert calls == [("init", 4), ("shutdown", None), ("init", 2)]
        assert cfg.num_processes == 2
        assert dist.active_world().num_processes == 2

    def test_teardown_failure_raises_typed_error(self, monkeypatch):
        monkeypatch.setattr(dist, "_jax_initialize", lambda cfg: None)

        def boom():
            raise RuntimeError("backend wedged")

        monkeypatch.setattr(dist, "_jax_shutdown", boom)
        dist.initialize_from_env(
            {dist.ENV_COORD: "c:1", dist.ENV_NPROC: "4",
             dist.ENV_PID: "1"}, wait=False)
        with pytest.raises(dist.WorldTeardownError):
            dist.shutdown()

    def test_shutdown_clears_state(self, monkeypatch):
        monkeypatch.setattr(dist, "_jax_initialize", lambda cfg: None)
        monkeypatch.setattr(dist, "_jax_shutdown", lambda: None)
        dist.initialize_from_env(
            {dist.ENV_COORD: "c:1", dist.ENV_NPROC: "2",
             dist.ENV_PID: "0"}, wait=False)
        dist.shutdown()
        assert dist.active_world() is None

    def test_bad_env_does_not_tear_down_a_healthy_world(self, monkeypatch):
        monkeypatch.setattr(dist, "_jax_initialize", lambda cfg: None)
        shutdowns = []
        monkeypatch.setattr(dist, "_jax_shutdown",
                            lambda: shutdowns.append(1))
        dist.initialize_from_env(
            {dist.ENV_COORD: "c:1", dist.ENV_NPROC: "2",
             dist.ENV_PID: "0"}, wait=False)
        with pytest.raises(ValueError):
            dist.initialize_from_env({dist.ENV_NPROC: "3"}, wait=False)
        assert shutdowns == []
        assert dist.active_world().num_processes == 2


# -- preemption grace --------------------------------------------------------


class TestPreemptionGrace:
    def test_no_deadline_before_trigger(self):
        notice = PreemptionNotice(grace_s=30.0, clock=lambda: 100.0)
        assert notice.remaining_grace() is None
        assert notice.deadline is None

    def test_trigger_records_the_wall_deadline(self):
        t = {"now": 100.0}
        notice = PreemptionNotice(grace_s=30.0, clock=lambda: t["now"])
        notice.trigger()
        assert notice.deadline == 130.0
        t["now"] = 112.0
        assert notice.remaining_grace() == pytest.approx(18.0)
        t["now"] = 200.0
        assert notice.remaining_grace() == 0.0  # clamped, never negative

    def test_repeat_trigger_keeps_the_first_deadline(self):
        t = {"now": 100.0}
        notice = PreemptionNotice(grace_s=30.0, clock=lambda: t["now"])
        notice.trigger()
        t["now"] = 110.0
        notice.trigger()  # a repeated SIGTERM must not extend the window
        assert notice.deadline == 130.0

    def test_grace_from_env(self, monkeypatch):
        monkeypatch.setenv("JAXJOB_TERMINATION_GRACE_S", "7.5")
        assert PreemptionNotice().grace_s == 7.5
        monkeypatch.setenv("JAXJOB_TERMINATION_GRACE_S", "bogus")
        assert PreemptionNotice().grace_s == 30.0

    def test_signal_handler_records_deadline(self):
        import os
        import signal as sig

        t = {"now": 50.0}
        old = sig.getsignal(sig.SIGUSR2)
        try:
            notice = PreemptionNotice(
                grace_s=10.0, clock=lambda: t["now"]).install(sig.SIGUSR2)
            os.kill(os.getpid(), sig.SIGUSR2)
            assert notice()
            assert notice.deadline == 60.0
            notice.uninstall()
        finally:
            sig.signal(sig.SIGUSR2, old)


# -- batch policy ------------------------------------------------------------


class TestScaleConfig:
    def _cfg(self, **kw):
        from kubeflow_tpu.runtime.trainer import TrainConfig

        base = dict(model="resnet18", global_batch=8)
        base.update(kw)
        return TrainConfig.from_dict(base)

    def test_preserve_keeps_global_batch_scales_accum(self):
        cfg = self._cfg(grad_accum_steps=2)
        out = elastic.scale_config(cfg, full_world=4, world=2,
                                   policy=elastic.BATCH_PRESERVE)
        assert out.global_batch == 8
        assert out.grad_accum_steps == 4  # 4/2 x base accum 2
        out = elastic.scale_config(cfg, full_world=8, world=2,
                                   policy=elastic.BATCH_PRESERVE)
        assert out.grad_accum_steps == 8

    def test_preserve_never_introduces_accumulation(self):
        # a single-shot config stays single-shot: splitting the batch
        # would change BatchNorm statistics and break loss continuity
        cfg = self._cfg()
        out = elastic.scale_config(cfg, full_world=4, world=2,
                                   policy=elastic.BATCH_PRESERVE)
        assert out.grad_accum_steps == 0
        assert out.global_batch == 8

    def test_preserve_full_world_is_identity(self):
        cfg = self._cfg(grad_accum_steps=2)
        assert elastic.scale_config(cfg, 4, 4, elastic.BATCH_PRESERVE) is cfg

    def test_preserve_compounds_existing_accum(self):
        cfg = self._cfg(grad_accum_steps=2)
        out = elastic.scale_config(cfg, 4, 2, elastic.BATCH_PRESERVE)
        assert out.grad_accum_steps == 4
        assert out.global_batch == 8

    def test_preserve_indivisible_falls_back_to_base(self):
        # scaled accum 2x3=6, but global_batch 8 % 6 != 0 -> keep the
        # configured accumulation, same global batch
        cfg = self._cfg(global_batch=8, grad_accum_steps=2)
        out = elastic.scale_config(cfg, 3, 1, elastic.BATCH_PRESERVE)
        assert out.grad_accum_steps == 2 and out.global_batch == 8

    def test_scale_scales_global_batch(self):
        cfg = self._cfg()
        out = elastic.scale_config(cfg, 4, 2, elastic.BATCH_SCALE)
        assert out.global_batch == 4
        out = elastic.scale_config(cfg, 2, 4, elastic.BATCH_SCALE)
        assert out.global_batch == 16

    def test_scale_indivisible_raises(self):
        with pytest.raises(ValueError):
            elastic.scale_config(self._cfg(global_batch=5), 4, 2,
                                 elastic.BATCH_SCALE)

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError):
            elastic.scale_config(self._cfg(), 4, 2, "Halve")


# -- elastic coordinator (scripted worlds, stub trainer) ---------------------


class _ScriptedSource:
    def __init__(self, world):
        self.world = world

    def __call__(self):
        return self.world


class _StubTrainer:
    """fit() runs 'steps' whose only effect is polling stop() — the
    coordinator's control flow under test, not the math."""

    def __init__(self, cfg, on_step=None, steps=5):
        self.cfg = cfg
        self.on_step = on_step
        self.steps = steps

    def fit(self, stop=None, callback=None):
        for i in range(self.steps):
            if stop is not None and stop():
                return None, {"preempted": True, "steps": i}
            if self.on_step:
                self.on_step(i)
            if callback:
                callback(i, {"loss": 0.0})
        return None, {"steps": self.steps}


def _coord(source, **kw):
    kw.setdefault("form_world", lambda w: None)
    kw.setdefault("my_name", "train-worker-0")
    return elastic.ElasticCoordinator(source, **kw)


def _cfg(tmp_path):
    from kubeflow_tpu.runtime.trainer import TrainConfig

    return TrainConfig.from_dict(dict(model="resnet18", global_batch=8,
                                      checkpoint_dir=str(tmp_path)))


W4 = dist.WorldSpec(gen=0, size=4, members=tuple(
    f"train-worker-{i}" for i in range(4)), coordinator="c:1")
W2 = dist.WorldSpec(gen=1, size=2, members=("train-worker-0",
                                            "train-worker-2"),
                    coordinator="c:1")


class TestElasticCoordinator:
    def test_completes_without_resize(self, tmp_path):
        formed = []
        coord = _coord(_ScriptedSource(W4), form_world=formed.append)
        _, summary = coord.run(
            _cfg(tmp_path),
            trainer_factory=lambda c, w: _StubTrainer(c))
        assert summary["elastic"] == {"exit": "completed", "resizes": 0,
                                      "worlds": [4]}
        assert formed == [W4]
        assert "preempted" not in summary

    def test_resize_reforms_and_resumes(self, tmp_path):
        src = _ScriptedSource(W4)
        formed = []

        def on_step(i):
            if i == 2:
                src.world = W2  # the controller re-stamped mid-fit

        coord = _coord(src, form_world=formed.append)
        _, summary = coord.run(
            _cfg(tmp_path),
            trainer_factory=lambda c, w: _StubTrainer(c, on_step=on_step))
        assert summary["elastic"] == {"exit": "completed", "resizes": 1,
                                      "worlds": [4, 2]}
        assert formed == [W4, W2]

    def test_batch_policy_applied_per_world(self, tmp_path):
        import dataclasses as dc

        src = _ScriptedSource(W4)
        seen = []

        def factory(cfg, world):
            seen.append((world, cfg.global_batch, cfg.grad_accum_steps))
            return _StubTrainer(
                cfg, on_step=(lambda i: setattr(src, "world", W2))
                if len(seen) == 1 else None)

        coord = _coord(src)
        coord.run(dc.replace(_cfg(tmp_path), grad_accum_steps=2),
                  trainer_factory=factory)
        assert seen == [(4, 8, 2), (2, 8, 4)]  # batch preserved via accum

    def test_preemption_notice_wins_over_resize(self, tmp_path):
        src = _ScriptedSource(W4)
        notice = PreemptionNotice(grace_s=30.0, clock=lambda: 0.0)

        def on_step(i):
            if i == 1:
                notice.trigger()  # SIGTERM: this pod is going away

        coord = _coord(src, notice=notice)
        _, summary = coord.run(
            _cfg(tmp_path),
            trainer_factory=lambda c, w: _StubTrainer(c, on_step=on_step))
        assert summary["elastic"]["exit"] == "preempted"
        assert summary["preempted"] is True

    def test_notice_plus_resize_exits_for_restart(self, tmp_path):
        """SIGTERM and a resize landing in the same step: the notice
        wins unconditionally — a terminating pod must not burn its
        grace on a re-formation whose stop flag is already set."""
        src = _ScriptedSource(W4)
        notice = PreemptionNotice(grace_s=30.0, clock=lambda: 0.0)

        def on_step(i):
            if i == 1:
                notice.trigger()
                src.world = W2

        coord = _coord(src, notice=notice)
        _, summary = coord.run(
            _cfg(tmp_path),
            trainer_factory=lambda c, w: _StubTrainer(c, on_step=on_step))
        assert summary["elastic"]["exit"] == "preempted"
        assert summary["elastic"]["resizes"] == 0

    def test_stale_initial_world_formation_retries_at_current(
            self, tmp_path):
        """Partial admission race: an admitted worker starts with the
        full-gang stamp and its world formation times out waiting for
        never-admitted peers — meanwhile the controller's
        shrink-to-admitted re-stamp landed. The coordinator must retry
        at the CURRENT world, not crash (a non-75 exit would burn the
        restart budget)."""
        src = _ScriptedSource(W4)
        formed = []

        def form(w):
            formed.append(w.gen)
            if w.gen == 0:
                src.world = W2  # the re-stamp landed while init blocked
                raise RuntimeError("initialize timed out: peers absent")

        coord = _coord(src, form_world=form)
        _, summary = coord.run(
            _cfg(tmp_path), trainer_factory=lambda c, w: _StubTrainer(c))
        assert formed == [0, 1]
        assert summary["elastic"] == {"exit": "completed", "resizes": 1,
                                      "worlds": [4, 2]}

    def test_formation_failure_without_stamp_movement_raises(
            self, tmp_path):
        def form(w):
            raise RuntimeError("coordinator unreachable")

        coord = _coord(_ScriptedSource(W4), form_world=form)
        with pytest.raises(RuntimeError, match="unreachable"):
            coord.run(_cfg(tmp_path),
                      trainer_factory=lambda c, w: _StubTrainer(c))

    def test_join_barrier_waits_for_membership(self, tmp_path):
        src = _ScriptedSource(dist.WorldSpec(
            gen=1, size=2, members=("train-worker-1", "train-worker-2")))
        polls = []

        def sleep(dt):
            polls.append(dt)
            if len(polls) == 3:  # the grow re-stamp admits us
                src.world = dist.WorldSpec(
                    gen=2, size=3,
                    members=("train-worker-0", "train-worker-1",
                             "train-worker-2"))

        coord = _coord(src, sleep=sleep, join_poll_s=0.5,
                       join_timeout_s=60.0, clock=lambda: 0.0)
        _, summary = coord.run(
            _cfg(tmp_path), trainer_factory=lambda c, w: _StubTrainer(c))
        assert len(polls) == 3
        assert summary["elastic"]["worlds"] == [3]

    def test_join_barrier_times_out(self, tmp_path):
        t = {"now": 0.0}

        def sleep(dt):
            t["now"] += 100.0

        src = _ScriptedSource(dist.WorldSpec(
            gen=1, size=1, members=("train-worker-9",)))
        coord = _coord(src, sleep=sleep, clock=lambda: t["now"],
                       join_timeout_s=150.0)
        with pytest.raises(TimeoutError):
            coord.run(_cfg(tmp_path),
                      trainer_factory=lambda c, w: _StubTrainer(c))

    def test_incompatible_resized_world_exits_for_restart(self, tmp_path):
        """A shrink to a world the config cannot run (e.g. global batch
        not divisible by the survivor count) must exit EX_TEMPFAIL
        semantics for a gang restart — crashing would burn the restart
        budget through a crash loop."""
        src = _ScriptedSource(W4)

        def factory(cfg, world):
            if world != 4:
                raise ValueError("microbatch 32 not divisible by dp 3")
            return _StubTrainer(
                cfg, on_step=lambda i: setattr(src, "world", W2))

        coord = _coord(src)
        _, summary = coord.run(_cfg(tmp_path), trainer_factory=factory)
        assert summary["elastic"]["exit"] == "preempted"
        assert summary["preempted"] is True

    def test_scale_policy_indivisible_resized_world_exits_for_restart(
            self, tmp_path):
        """The Scale policy's divisibility error on a RESIZED world
        gets the same exit-for-restart treatment as an unbuildable
        trainer — not a crash that burns the restart budget."""
        import dataclasses as dc

        src = _ScriptedSource(W4)
        w3 = dist.WorldSpec(gen=1, size=3, members=tuple(
            f"train-worker-{i}" for i in range(3)))

        def factory(cfg, world):
            return _StubTrainer(
                cfg, on_step=lambda i: setattr(src, "world", w3))

        coord = _coord(src, batch_policy=elastic.BATCH_SCALE)
        # 10 x 3/4 is not integral -> scale_config raises on the
        # shrunken world only
        _, summary = coord.run(
            dc.replace(_cfg(tmp_path), global_batch=10),
            trainer_factory=factory)
        assert summary["elastic"]["exit"] == "preempted"

    def test_config_error_at_full_size_still_raises(self, tmp_path):
        def factory(cfg, world):
            raise ValueError("genuinely bad config")

        coord = _coord(_ScriptedSource(W4))
        with pytest.raises(ValueError, match="genuinely bad"):
            coord.run(_cfg(tmp_path), trainer_factory=factory)

    def test_unsynced_world_file_waits_instead_of_training_solo(
            self, tmp_path):
        """A None source read at startup means the downward-API file has
        not synced yet — the coordinator must wait in the join barrier,
        never fabricate a size-1 world and train as an independent
        rank 0 against the shared checkpoint dir."""
        src = _ScriptedSource(None)
        polls = []

        def sleep(dt):
            polls.append(dt)
            if len(polls) == 2:
                src.world = W4  # the kubelet synced the projection

        coord = _coord(src, sleep=sleep, clock=lambda: 0.0)
        _, summary = coord.run(
            _cfg(tmp_path), trainer_factory=lambda c, w: _StubTrainer(c))
        assert len(polls) == 2
        assert summary["elastic"]["worlds"] == [4]

    def test_requires_checkpoint_dir(self):
        from kubeflow_tpu.runtime.trainer import TrainConfig

        coord = _coord(_ScriptedSource(W4))
        with pytest.raises(ValueError):
            coord.run(TrainConfig.from_dict(dict(model="resnet18")))

    def test_requires_resume(self, tmp_path):
        # resume=False would retrain from step 0 on every resize
        from kubeflow_tpu.runtime.trainer import TrainConfig

        coord = _coord(_ScriptedSource(W4))
        with pytest.raises(ValueError, match="resume"):
            coord.run(TrainConfig.from_dict(dict(
                model="resnet18", checkpoint_dir=str(tmp_path),
                resume=False)))

    def test_batch_policy_spelling_is_the_wire_contract(self):
        # ONE spelling: jaxjob spec values == dist wire values ==
        # coordinator comparisons
        assert (T.BATCH_PRESERVE, T.BATCH_SCALE) == \
            (dist.BATCH_PRESERVE, dist.BATCH_SCALE) == \
            (elastic.BATCH_PRESERVE, elastic.BATCH_SCALE)

    def test_world_file_source_roundtrip(self, tmp_path):
        path = tmp_path / "world"
        source = elastic.file_world_source(str(path))
        assert source() is None  # not yet projected
        path.write_text(W2.to_json())
        assert source() == W2
        path.write_text("{half a json")
        assert source() is None  # mid-write reads keep the current world

    def test_world_env_names_this_workers_rank(self):
        coord = _coord(_ScriptedSource(W2), my_name="train-worker-2")
        env = coord.world_env(W2, base_env={})
        assert env[dist.ENV_PID] == "1"
        assert env[dist.ENV_NPROC] == "2"
        assert env[dist.ENV_COORD] == "c:1"

    def test_world_env_refuses_nonmember_rank_default(self):
        """A worker whose name is absent from the world it was asked to
        form (the stamp moved under it) must NOT default to rank 0 —
        forming as rank 0 collides with the world's real coordinator."""
        coord = _coord(_ScriptedSource(W2), my_name="train-worker-1")
        with pytest.raises(elastic.WorldMembershipError):
            coord.world_env(W2, base_env={})

    def test_world_env_untracked_membership_is_rank0(self):
        # my_name=None (single-pod/test contract) keeps the rank-0 default
        coord = _coord(_ScriptedSource(W2), my_name=None)
        assert coord.world_env(W2, base_env={})[dist.ENV_PID] == "0"


# -- launcher bootstrap: elastic jobs defer world formation ------------------


class TestLauncherElasticBootstrap:
    def _run_main(self, tmp_path, monkeypatch):
        from kubeflow_tpu.runtime import launcher

        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text("{}")
        calls = []
        monkeypatch.setattr(
            dist, "initialize_from_env",
            lambda *a, **k: calls.append(1) or dist.DistConfig.from_env({}))
        monkeypatch.setattr(launcher, "run_builtin_trainer", lambda cfg: 0)
        assert launcher.main(["--config", str(cfg_path)]) == 0
        return calls

    def test_rigid_job_initializes_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(dist.ENV_WORLD_FILE, raising=False)
        assert len(self._run_main(tmp_path, monkeypatch)) == 1

    def test_elastic_job_defers_formation_to_coordinator(
            self, tmp_path, monkeypatch):
        """With a world file wired, the pod env describes the FULL gang
        while the live membership is the controller's stamp; an eager
        global initialize would block for never-admitted peers under
        partial admission (and for a grow-back replacement joining a
        shrunken world). The launcher must leave the first formation to
        the ElasticCoordinator."""
        monkeypatch.setenv(dist.ENV_WORLD_FILE, str(tmp_path / "world"))
        assert self._run_main(tmp_path, monkeypatch) == []

    def test_user_command_with_world_file_still_initializes(
            self, tmp_path, monkeypatch):
        # only the --config path wires an ElasticCoordinator; a user
        # command keeps the eager env formation (no elastic resize)
        from kubeflow_tpu.runtime import launcher

        calls = []
        monkeypatch.setattr(
            dist, "initialize_from_env",
            lambda *a, **k: calls.append(1) or dist.DistConfig.from_env({}))
        monkeypatch.setattr(launcher, "run_user_command", lambda argv: 0)
        monkeypatch.setenv(dist.ENV_WORLD_FILE, str(tmp_path / "world"))
        assert launcher.main(["--", "true"]) == 0
        assert len(calls) == 1


# -- checkpoint resharding: save at N, restore at M --------------------------


class _CkptState:
    """Minimal TrainState stand-in for Checkpointer (step/params/
    batch_stats/opt_state + .replace)."""

    def __init__(self, **kw):
        self.__dict__.update(kw)

    def replace(self, **kw):
        d = dict(self.__dict__)
        d.update(kw)
        return _CkptState(**d)


def _mesh(n):
    import jax

    from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=1, fsdp=n), jax.devices()[:n])


def _sharded_state(n, step=7, mesh=None):
    """Params + adamw optimizer state laid out over an n-way fsdp mesh
    (or a caller-supplied mesh) via the shared sharding inference
    (parallel/shardings.py)."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.parallel.shardings import infer_shardings

    mesh = mesh if mesh is not None else _mesh(n)
    rng = np.random.RandomState(0)
    host = {
        "dense": {"kernel": rng.randn(128, 256).astype(np.float32),
                  "bias": rng.randn(256).astype(np.float32)},
        "head": {"kernel": rng.randn(256, 64).astype(np.float32)},
    }
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), host)
    shardings = infer_shardings(abstract, mesh)
    params = jax.tree.map(
        lambda a, s: jax.device_put(jnp.asarray(a), s), host, shardings)
    opt_state = optax.adamw(1e-3).init(params)
    return _CkptState(step=jnp.asarray(step, jnp.int32), params=params,
                      batch_stats={}, opt_state=opt_state), host


def _unshard(tree):
    import jax

    return jax.tree.map(lambda a: np.asarray(a), tree)


@pytest.mark.parametrize("save_world,restore_world",
                         [(8, 4), (8, 2), (8, 1), (4, 2), (4, 1),
                          (2, 8), (1, 4), (4, 8)])
def test_checkpoint_reshards_bitwise(tmp_path, devices8,
                                     save_world, restore_world):
    """THE elasticity contract (PAPERS.md: checkpoint-based fault
    tolerance): params and optimizer state saved under one world layout
    restore BITWISE-identical under any other — sharding is a compiler
    input, not checkpoint state."""
    from kubeflow_tpu.runtime.checkpoint import Checkpointer

    state, host = _sharded_state(save_world)
    ck = Checkpointer(str(tmp_path), world_size=save_world)
    assert ck.save(7, state)
    ck.wait()
    ck.close()

    template, _ = _sharded_state(restore_world, step=0)
    ck2 = Checkpointer(str(tmp_path), world_size=restore_world)
    restored = ck2.restore(7, template)
    ck2.close()
    assert int(restored.step) == 7
    got = _unshard(restored.params)
    for key in ("dense", "head"):
        for leaf, a in host[key].items():
            assert np.array_equal(got[key][leaf], a), (key, leaf)
    # optimizer moments reshard bitwise too
    want_opt = _unshard(state.opt_state)
    got_opt = _unshard(restored.opt_state)
    import jax

    for w, g in zip(jax.tree.leaves(want_opt), jax.tree.leaves(got_opt)):
        assert np.array_equal(w, g)


def _slice_mesh(ns):
    """The multi-slice layout a slice shrink/grow actually swaps
    between: dcn outermost over the slice partition, fsdp inside."""
    import jax

    from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(dcn=ns, fsdp=2), jax.devices()[:2 * ns])


@pytest.mark.parametrize("save_slices,restore_slices",
                         [(2, 1), (1, 2), (4, 2), (2, 4), (4, 1)])
def test_checkpoint_reshards_across_slice_counts(tmp_path, devices8,
                                                 save_slices,
                                                 restore_slices):
    """ISSUE 12 multi-slice corollary of the bitwise contract: a
    whole-slice shrink/grow changes the DCN extent of the mesh (and
    with it every array's replication layout), not just the device
    count — params and optimizer moments must still restore bitwise.
    The dcn axis is a compiler input like any other mesh axis."""
    from kubeflow_tpu.runtime.checkpoint import Checkpointer

    save_n, restore_n = 2 * save_slices, 2 * restore_slices
    state, host = _sharded_state(save_n, mesh=_slice_mesh(save_slices))
    ck = Checkpointer(str(tmp_path), world_size=save_n)
    assert ck.save(7, state)
    ck.wait()
    ck.close()

    template, _ = _sharded_state(restore_n, step=0,
                                 mesh=_slice_mesh(restore_slices))
    ck2 = Checkpointer(str(tmp_path), world_size=restore_n)
    restored = ck2.restore(7, template)
    ck2.close()
    assert int(restored.step) == 7
    got = _unshard(restored.params)
    for key in ("dense", "head"):
        for leaf, a in host[key].items():
            assert np.array_equal(got[key][leaf], a), (key, leaf)
    want_opt = _unshard(state.opt_state)
    got_opt = _unshard(restored.opt_state)
    import jax

    for w, g in zip(jax.tree.leaves(want_opt), jax.tree.leaves(got_opt)):
        assert np.array_equal(w, g)


def test_manifest_records_world_sizes(tmp_path, devices8):
    from kubeflow_tpu.runtime.checkpoint import Checkpointer

    state, _ = _sharded_state(4)
    ck = Checkpointer(str(tmp_path), world_size=4)
    ck.save(1, state)
    ck.close()
    # the shrunken incarnation reopens the same directory
    state2, _ = _sharded_state(2, step=2)
    ck2 = Checkpointer(str(tmp_path), world_size=2)
    ck2.save(2, state2)
    ck2.close()
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert manifest["world_sizes"] == {"1": 4, "2": 2}
    assert manifest["latest_step"] == 2


# -- the hermetic e2e: shrink mid-training, grow back, loss continuity ------


def _train_cfg(tmp_path, total_steps=12):
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.trainer import TrainConfig

    return TrainConfig.from_dict(dict(
        model="resnet18", model_kwargs={"num_filters": 8},
        task="classification", global_batch=8, image_size=16,
        num_classes=10, mesh=MeshSpec(data=8), total_steps=total_steps,
        warmup_steps=1, learning_rate=0.01, log_every=10**9,
        checkpoint_dir=str(tmp_path)))


def _device_mesh_fn():
    import jax

    from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh

    return lambda cfg, w: build_mesh(MeshSpec(data=w), jax.devices()[:w])


def test_elastic_e2e_shrink_grow_loss_continuity(tmp_path):
    """The acceptance e2e: a 4-worker elastic JAXJob loses 2 workers
    (spot reclaim) mid-training, shrinks without consuming maxRestarts,
    continues from the last checkpointed step with a CONTINUOUS loss
    curve (no re-warmup from step 0), then grows back to 4 when the
    scheduler readmits capacity — deterministic under the fake
    scheduler clock.

    Runs in a FRESH subprocess (tests/elastic_e2e_driver.py — the
    gang_worker.py pattern): in a long-lived full-suite process this
    image's jaxlib heap-corrupts on subset-mesh compiles (the
    test_checkpoint.py crash family), and elastic resizes are exactly
    subset meshes."""
    import subprocess
    import sys

    driver = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "elastic_e2e_driver.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, driver, str(tmp_path)],
        cwd=repo, env=env, capture_output=True, text=True, timeout=540)
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-3000:])
    lines = [ln for ln in out.stdout.splitlines()
             if ln.startswith("ELASTIC_E2E ")]
    assert lines, out.stdout[-3000:]
    r = json.loads(lines[-1].split(" ", 1)[1])

    # spot preferred at admission: workers 0,1 landed on the spot pool
    assert r["initial_spot_bindings"] == ["spot0", "spot1"]
    # world trajectory: full -> shrunken -> full again, in place
    assert r["elastic"] == {"exit": "completed", "resizes": 2,
                            "worlds": [4, 2, 4]}
    assert r["step"] == 12
    # every global step executed exactly once: NO re-warmup from 0
    assert len(r["losses"]) == 12

    # control plane: shrunk and grew back without touching any budget
    assert r["restarts"] == 0
    assert r["preemptions"] == 0
    assert r["resizes"] == 2
    assert r["active_replicas"] == 4
    assert r["resizing"] == "False"
    assert r["running"] is True

    # loss-curve continuity: the resized run matches an uninterrupted
    # same-global-batch run step for step (Preserve policy) — the PR 5
    # bar was mere reconvergence; this is the stronger contract
    assert len(r["ref_losses"]) == 12
    np.testing.assert_allclose(r["losses"], r["ref_losses"],
                               rtol=1e-3, atol=1e-4)
