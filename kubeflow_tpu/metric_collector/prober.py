"""Availability prober: the `kubeflow_availability` gauge.

Mirrors metric-collector/service-readiness/kubeflow-readiness.py: an
authenticated GET against the platform endpoint sets a binary Prometheus
gauge (:20-22, metric_update :25-37). Auth is pluggable (the reference
used OIDC-through-IAP; header-identity and none are provided here), and
a multi-target mode probes every component the TpuDef deployed.

Results land in BOTH sinks (the PR 4 convention): prometheus_client
for the prober's own scrape port, and the ``MetricsRegistry`` so the
fleet observability plane (``obs/tsdb.ScrapeLoop``) can pull the same
series through a ``RegistryTarget`` or the registry's ``/metrics``
endpoint — catalogued in docs/observability.md.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

import prometheus_client as prom

from kubeflow_tpu.runtime.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("kubeflow_tpu.metric_collector")

_METRICS: dict[str, object] = {}


def availability_gauge():
    if "g" not in _METRICS:
        _METRICS["g"] = prom.Gauge(
            "kubeflow_availability",
            "whether the kubeflow-tpu endpoint answers (1 up / 0 down)",
            ["target"],
        )
    return _METRICS["g"]


def http_check(url: str, headers: dict[str, str] | None = None,
               timeout: float = 10.0) -> bool:
    import requests

    try:
        r = requests.get(url, headers=headers or {}, timeout=timeout)
        return 200 <= r.status_code < 400
    except Exception as e:
        log.debug("probe %s failed: %s", url, e)
        return False


class AvailabilityProber:
    def __init__(
        self,
        targets: dict[str, str],
        checker: Callable[[str], bool] | None = None,
        user_header: str | None = None,
        registry: MetricsRegistry | None = None,
    ):
        headers = {"kubeflow-userid": user_header} if user_header else {}
        self.targets = targets
        self.checker = checker or (lambda url: http_check(url, headers))
        self.registry = registry if registry is not None else REGISTRY

    def probe_once(self) -> dict[str, bool]:
        out = {}
        for name, url in self.targets.items():
            up = self.checker(url)
            availability_gauge().labels(target=name).set(1 if up else 0)
            self.registry.gauge(
                "kubeflow_availability", 1 if up else 0,
                help_="whether the kubeflow-tpu endpoint answers "
                      "(1 up / 0 down)", target=name)
            self.registry.counter_inc(
                "kubeflow_probe_total",
                help_="availability probes by result",
                target=name, result="up" if up else "down")
            out[name] = up
        return out

    def run(self, period_s: float = 30.0) -> None:  # pragma: no cover
        while True:
            results = self.probe_once()
            down = [k for k, v in results.items() if not v]
            if down:
                log.warning("targets down: %s", down)
            time.sleep(period_s)


def main() -> None:  # pragma: no cover - container entry
    import argparse

    p = argparse.ArgumentParser("kubeflow-tpu-metric-collector")
    p.add_argument("--target", action="append", default=[],
                   help="name=url, repeatable")
    p.add_argument("--port", type=int, default=8088)
    p.add_argument("--period-secs", type=float, default=30.0)
    args = p.parse_args()
    targets = dict(t.split("=", 1) for t in args.target) or {
        "dashboard": "http://centraldashboard.kubeflow.svc/healthz"}
    prom.start_http_server(args.port)
    AvailabilityProber(targets).run(args.period_secs)


if __name__ == "__main__":  # pragma: no cover
    main()
