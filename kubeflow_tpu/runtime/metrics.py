"""Hot-loop instrumentation the reference never had.

The reference's observability is Prometheus on the control plane only
(bootstrap/cmd/bootstrap/app/server.go:68-132, notebook-controller
pkg/metrics/metrics.go) — per-step training metrics don't exist. Here
every worker exports step time, throughput, and MFU in Prometheus text
exposition format, scrapeable at :9100/metrics, with zero third-party
dependencies (stdlib http.server on a daemon thread).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# Peak dense bf16 FLOP/s per chip, by jax device_kind. Source: public Cloud
# TPU docs tables (v4: 275T, v5e: 197T, v5p: 459T, v6e "Trillium": 918T).
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}
_DEFAULT_PEAK = 197e12

# Peak HBM bandwidth per chip (bytes/s), same doc tables (v4: 1.2TB/s,
# v5e: 819GB/s, v5p: 2.77TB/s, v6e: 1.64TB/s). Drives the roofline
# fields bench.py reports next to MFU.
PEAK_HBM_BW = {
    "TPU v4": 1.2e12,
    "TPU v5 lite": 819e9,
    "TPU v5": 2.77e12,
    "TPU v5p": 2.77e12,
    "TPU v6 lite": 1.64e12,
    "TPU v6e": 1.64e12,
}
_DEFAULT_BW = 819e9


def _lookup(table: dict, device_kind: str, default: float) -> float:
    for prefix, val in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if device_kind.startswith(prefix):
            return val
    return default


def peak_flops(device_kind: str) -> float:
    return _lookup(PEAK_FLOPS, device_kind, _DEFAULT_PEAK)


def peak_hbm_bw(device_kind: str) -> float:
    return _lookup(PEAK_HBM_BW, device_kind, _DEFAULT_BW)


class StepMeter:
    """Tracks step wall time, examples/sec and MFU over a sliding window."""

    def __init__(self, flops_per_step: float, n_chips: int, device_kind: str = "", window: int = 20):
        self.flops_per_step = float(flops_per_step)
        self.n_chips = max(1, n_chips)
        self.peak = peak_flops(device_kind) * self.n_chips if device_kind else None
        self._times: deque[float] = deque(maxlen=window)
        self._t0: float | None = None
        self.steps = 0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        assert self._t0 is not None, "stop() without start()"
        dt = time.perf_counter() - self._t0
        self._times.append(dt)
        self.steps += 1
        self._t0 = None
        return dt

    @property
    def step_time(self) -> float:
        return sum(self._times) / len(self._times) if self._times else float("nan")

    def throughput(self, examples_per_step: int) -> float:
        return examples_per_step / self.step_time

    @property
    def achieved_flops(self) -> float:
        return self.flops_per_step / self.step_time

    @property
    def mfu(self) -> float:
        if not self.peak:
            return float("nan")
        return self.achieved_flops / self.peak


class MetricsRegistry:
    """Minimal Prometheus registry: gauges and counters, text format 0.0.4."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, tuple[str, str, dict[tuple, float]]] = {}

    def _set(self, kind: str, name: str, help_: str, value: float, labels: dict | None):
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            _, _, series = self._metrics.setdefault(name, (kind, help_, {}))
            series[key] = value

    def gauge(self, name: str, value: float, help_: str = "", **labels) -> None:
        self._set("gauge", name, help_, value, labels)

    def counter_inc(self, name: str, help_: str = "", by: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            _, _, series = self._metrics.setdefault(name, ("counter", help_, {}))
            series[key] = series.get(key, 0.0) + by

    def render(self) -> str:
        out = []
        with self._lock:
            for name, (kind, help_, series) in sorted(self._metrics.items()):
                if help_:
                    out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} {kind}")
                for key, value in sorted(series.items()):
                    if key:
                        lbl = ",".join(f'{k}="{v}"' for k, v in key)
                        out.append(f"{name}{{{lbl}}} {value}")
                    else:
                        out.append(f"{name} {value}")
        return "\n".join(out) + "\n"


REGISTRY = MetricsRegistry()


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = REGISTRY

    def do_GET(self):  # noqa: N802
        if self.path.rstrip("/") in ("", "/metrics"):
            body = self.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/healthz":
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"ok")
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, *a):  # silence per-request lines
        pass


def serve_metrics(port: int = 9100, registry: MetricsRegistry = REGISTRY) -> ThreadingHTTPServer:
    """Start the /metrics endpoint on a daemon thread; returns the server
    (caller may .shutdown()). Port 0 picks a free port (tests)."""
    handler = type("Handler", (_Handler,), {"registry": registry})
    srv = ThreadingHTTPServer(("0.0.0.0", port), handler)
    t = threading.Thread(target=srv.serve_forever, name="metrics", daemon=True)
    t.start()
    return srv
