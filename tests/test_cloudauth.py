"""tpctl cloud-auth plumbing (reference: tokenSource.go:35-75,
gcpUtils.go:60-180, initHandler.go:33; test fidelity of
tokenSource_test.go + gcpUtils_test.go)."""

import threading

import pytest

from kubeflow_tpu.tpctl.cloudauth import (
    IAM_ADMIN_ROLE,
    SET_IAM_POLICY_PERMISSION,
    ProjectLocks,
    RefreshableTokenSource,
    bind_role,
    check_project_access,
    prepare_account,
    update_policy,
)


class FakeCrm:
    def __init__(self, valid_tokens=("good",), fail_times=0):
        self.valid = set(valid_tokens)
        self.fail_times = fail_times
        self.calls = 0
        self.policies: dict[str, dict] = {}
        self.set_calls: list[tuple[str, dict]] = []

    def test_iam_permissions(self, project, token, permissions):
        self.calls += 1
        if self.fail_times > 0:
            self.fail_times -= 1
            raise ConnectionError("transient")
        return list(permissions) if token in self.valid else []

    def get_iam_policy(self, project, token):
        import copy
        return copy.deepcopy(self.policies.setdefault(project, {"bindings": []}))

    def set_iam_policy(self, project, token, policy):
        self.policies[project] = policy
        self.set_calls.append((project, policy))


class TestCheckProjectAccess:
    def test_valid_token(self):
        assert check_project_access("p", "good", FakeCrm()) is True

    def test_insufficient_token_returns_false_immediately(self):
        crm = FakeCrm()
        assert check_project_access("p", "bad", crm) is False
        assert crm.calls == 1  # clean denial: no retries

    def test_transient_errors_retried_with_backoff(self):
        # gcpUtils.go:150-155: exponential backoff on API errors
        crm = FakeCrm(fail_times=2)
        sleeps = []
        assert check_project_access("p", "good", crm,
                                    sleep=sleeps.append) is True
        assert crm.calls == 3
        assert sleeps == [2.0, 4.0]

    def test_backoff_budget_exhausted_raises(self):
        # an exhausted WALL-CLOCK budget re-raises the backend error: a
        # CRM outage must not read as a credentials verdict, and slow
        # backend calls count against the budget (thread-pinning bound)
        crm = FakeCrm(fail_times=1000)
        now = [0.0]
        def clock():
            now[0] += 20.0  # each backend call burns 20s of wall clock
            return now[0]
        calls = []
        with pytest.raises(ConnectionError):
            check_project_access("p", "good", crm, sleep=calls.append,
                                 clock=clock)
        assert crm.calls - 1 <= 4  # budget exhausts after a few calls

    def test_auth_rejection_is_a_verdict_not_an_outage(self):
        # HTTP 401/403 from the backend -> immediate False, no retries
        class DenyCrm:
            def __init__(self):
                self.calls = 0
            def test_iam_permissions(self, project, token, permissions):
                self.calls += 1
                err = ConnectionError("401 unauthorized")
                err.code = 401
                raise err
        crm = DenyCrm()
        assert check_project_access("p", "tok", crm,
                                    sleep=lambda s: None) is False
        assert crm.calls == 1


class TestRefreshableTokenSource:
    def test_requires_project(self):
        with pytest.raises(ValueError):
            RefreshableTokenSource("", FakeCrm())

    def test_refresh_validates_then_swaps(self):
        ts = RefreshableTokenSource("p", FakeCrm())
        assert ts.token() is None
        ts.refresh("good")
        assert ts.token() == "good"

    def test_empty_token_rejected(self):
        # tokenSource.go:53-55
        ts = RefreshableTokenSource("p", FakeCrm())
        with pytest.raises(ValueError):
            ts.refresh("")

    def test_invalid_token_keeps_current(self):
        # tokenSource.go:62-67: failed validation leaves the old token
        ts = RefreshableTokenSource("p", FakeCrm())
        ts.refresh("good")
        with pytest.raises(PermissionError):
            ts.refresh("bad")
        assert ts.token() == "good"


class TestPrepareAccount:
    # gcpUtils.go:60-68
    def test_service_account(self):
        assert prepare_account("x@p.iam.gserviceaccount.com") == \
            "serviceAccount:x@p.iam.gserviceaccount.com"

    def test_support_group(self):
        assert prepare_account("google-kubeflow-support@google.com") == \
            "group:google-kubeflow-support@google.com"

    def test_plain_user(self):
        assert prepare_account("alice@example.com") == "user:alice@example.com"


class TestUpdatePolicy:
    CONF = [{"members": ["set-kubeflow-iap-account"],
             "roles": ["roles/iap.httpsResourceAccessor"]}]

    def test_add_binding_with_placeholder_substitution(self):
        # gcpUtils.go:80-87 placeholder mapping
        policy = {"bindings": [{"role": "roles/viewer",
                                "members": ["user:bob@example.com"]}]}
        out = update_policy(policy, self.CONF, cluster="kf", project="p",
                            email="alice@example.com", action="add")
        roles = {b["role"]: sorted(b["members"]) for b in out["bindings"]}
        assert roles["roles/viewer"] == ["user:bob@example.com"]
        assert roles["roles/iap.httpsResourceAccessor"] == ["user:alice@example.com"]

    def test_add_is_idempotent(self):
        policy = {"bindings": [{"role": "roles/iap.httpsResourceAccessor",
                                "members": ["user:alice@example.com"]}]}
        out = update_policy(policy, self.CONF, cluster="kf", project="p",
                            email="alice@example.com", action="add")
        [b] = [b for b in out["bindings"]
               if b["role"] == "roles/iap.httpsResourceAccessor"]
        assert b["members"] == ["user:alice@example.com"]

    def test_remove_action_deletes_member(self):
        # gcpUtils.go:99-104: action=remove flips the member off
        policy = {"bindings": [{"role": "roles/iap.httpsResourceAccessor",
                                "members": ["user:alice@example.com",
                                            "user:bob@example.com"]}]}
        out = update_policy(policy, self.CONF, cluster="kf", project="p",
                            email="alice@example.com", action="remove")
        [b] = [b for b in out["bindings"]
               if b["role"] == "roles/iap.httpsResourceAccessor"]
        assert b["members"] == ["user:bob@example.com"]

    def test_role_emptied_by_remove_is_dropped(self):
        policy = {"bindings": [{"role": "roles/iap.httpsResourceAccessor",
                                "members": ["user:alice@example.com"]}]}
        out = update_policy(policy, self.CONF, cluster="kf", project="p",
                            email="alice@example.com", action="remove")
        assert out["bindings"] == []

    def test_service_account_placeholders(self):
        conf = [{"members": ["set-kubeflow-admin-service-account",
                             "set-kubeflow-vm-service-account"],
                 "roles": ["roles/editor"]}]
        out = update_policy({"bindings": []}, conf, cluster="kf", project="p",
                            email="e@x.com", action="add")
        [b] = out["bindings"]
        assert sorted(b["members"]) == [
            "serviceAccount:kf-admin@p.iam.gserviceaccount.com",
            "serviceAccount:kf-vm@p.iam.gserviceaccount.com"]


class TestBindRole:
    def test_grants_admin_role(self):
        # initHandler.go:24: <projectNumber>@cloudservices.gserviceaccount.com
        crm = FakeCrm()
        bind_role("p", "good", "123@cloudservices.gserviceaccount.com", crm)
        [b] = crm.policies["p"]["bindings"]
        assert b["role"] == IAM_ADMIN_ROLE
        assert b["members"] == ["serviceAccount:123@cloudservices.gserviceaccount.com"]

    def test_idempotent(self):
        crm = FakeCrm()
        for _ in range(2):
            bind_role("p", "good", "123@cloudservices.gserviceaccount.com", crm)
        assert len(crm.set_calls) == 1

    def test_concurrent_binds_serialize_per_project(self):
        # ksServer.go:44-47: policy read-modify-write races are guarded by
        # the per-project lock; 8 concurrent binds must not lose updates.
        crm = FakeCrm()
        locks = ProjectLocks()
        threads = [threading.Thread(
            target=bind_role,
            args=("p", "good", f"sa{i}@cloudservices.gserviceaccount.com", crm),
            kwargs={"locks": locks}) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        members = {m for b in crm.policies["p"]["bindings"]
                   for m in b["members"]}
        assert len(members) == 8


class TestTpctlCloudGate:
    """The kfctlServer.go:519/:545 validity gate wired into tpctl create."""

    def _req(self, platform="gke-tpu", project="proj-1", token="good"):
        import json as _json

        from kubeflow_tpu.utils.httpd import HttpReq
        body = {"metadata": {"name": "d1"},
                "spec": {"platform": {"kind": platform, "project": project,
                                      "zone": "us-central2-b"}}}
        headers = {"authorization": f"Bearer {token}"} if token else {}
        return HttpReq(method="POST", path="/tpctl/apps/v1/create", params={},
                       query={}, headers=headers,
                       body=_json.dumps(body).encode())

    def _server(self, crm):
        from kubeflow_tpu.control.k8s.fake import FakeCluster
        from kubeflow_tpu.tpctl.apply import Coordinator, ExistingCluster
        from kubeflow_tpu.tpctl.server import TpctlServer
        cluster = FakeCluster()
        # stub platform provider: gate tests must never shell out to a
        # real gcloud (GkeTpuPlatform.apply would)
        factory = lambda: Coordinator(cluster, provider=ExistingCluster())
        return TpctlServer(cluster, crm_backend=crm,
                           coordinator_factory=factory)

    def test_existing_platform_needs_no_token(self):
        srv = self._server(FakeCrm())
        resp = srv.router().dispatch(self._req(platform="existing", token=None))
        assert resp.status == 200

    def test_cloud_platform_without_token_is_401(self):
        srv = self._server(FakeCrm())
        assert srv.router().dispatch(self._req(token=None)).status == 401

    def test_insufficient_token_is_403(self):
        srv = self._server(FakeCrm(valid_tokens=("other",)))
        assert srv.router().dispatch(self._req(token="bad")).status == 403

    def test_valid_token_enqueues_and_caches_source(self):
        crm = FakeCrm()
        srv = self._server(crm)
        resp = srv.router().dispatch(self._req())
        assert resp.status == 200
        assert srv._token_sources["proj-1"].token() == "good"

    def test_missing_project_is_400(self):
        srv = self._server(FakeCrm())
        assert srv.router().dispatch(self._req(project="")).status == 400

    def test_no_backend_means_no_gate(self):
        srv = self._server(None)
        assert srv.router().dispatch(self._req(token=None)).status == 200

    def test_crm_outage_is_503_not_403(self):
        srv = self._server(FakeCrm(fail_times=1000))
        srv_cls = type(srv)
        old = srv_cls.ACCESS_CHECK_BUDGET_S
        srv_cls.ACCESS_CHECK_BUDGET_S = 0.0  # no sleeping in tests
        try:
            assert srv.router().dispatch(self._req()).status == 503
        finally:
            srv_cls.ACCESS_CHECK_BUDGET_S = old
