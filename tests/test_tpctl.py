"""tpctl deployment engine semantics (reference: bootstrap/ —
kfctlServer_test.go, router_test.go, server_test.go shapes; idempotency
contract of testing/kfctl/kfctl_second_apply.py)."""

import json
import time

import pytest
import yaml

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.tpctl import manifests
from kubeflow_tpu.tpctl.apply import Coordinator, GkeTpuPlatform
from kubeflow_tpu.tpctl.server import TpctlServer
from kubeflow_tpu.tpctl.tpudef import (
    COND_AVAILABLE,
    COND_DEGRADED,
    TpuDef,
    example_yaml,
)


@pytest.fixture()
def cfg():
    return TpuDef.from_dict(yaml.safe_load(example_yaml()))


class TestTpuDef:
    def test_example_roundtrip(self, cfg):
        assert cfg.name == "kubeflow-tpu"
        assert cfg.platform == "existing"
        assert "jaxjob-controller" in cfg.applications
        again = TpuDef.from_dict(yaml.safe_load(cfg.dump()))
        assert again.to_object()["spec"] == cfg.to_object()["spec"]

    def test_unknown_application_rejected(self):
        with pytest.raises(ValueError, match="unknown applications"):
            TpuDef.from_dict({"spec": {"applications": ["nope"]}})


class TestManifests:
    def test_render_all(self, cfg):
        objs = manifests.render(cfg)
        kinds = [(o["kind"], ob.meta(o)["name"]) for o in objs]
        assert ("CustomResourceDefinition", "jaxjobs.kubeflow.org") in kinds
        assert ("CustomResourceDefinition", "jaxservices.kubeflow.org") in kinds
        assert ("CustomResourceDefinition", "studyjobs.kubeflow.org") in kinds
        assert ("Namespace", "kubeflow") in kinds
        assert ("Deployment", "jaxjob-controller") in kinds
        assert ("Deployment", "jaxservice-controller") in kinds
        assert ("Deployment", "centraldashboard") in kinds
        assert ("MutatingWebhookConfiguration", "poddefault-webhook") in kinds
        assert ("ClusterRole", "kubeflow-admin") in kinds
        # CRDs render before workloads
        crd_idx = kinds.index(("CustomResourceDefinition", "jaxjobs.kubeflow.org"))
        dep_idx = kinds.index(("Deployment", "jaxjob-controller"))
        assert crd_idx < dep_idx

    def test_overlay_patch(self, cfg):
        cfg.overlays = [{"target": {"kind": "Deployment", "name": "jaxjob-controller"},
                         "patch": {"spec": {"replicas": 3}}}]
        objs = manifests.render(cfg)
        dep = next(o for o in objs if o["kind"] == "Deployment"
                   and ob.meta(o)["name"] == "jaxjob-controller")
        assert dep["spec"]["replicas"] == 3

    def test_subset_applications(self):
        cfg = TpuDef.from_dict(
            {"spec": {"applications": ["crds", "namespace", "jaxjob-controller"]}})
        objs = manifests.render(cfg)
        kinds = {o["kind"] for o in objs}
        assert "MutatingWebhookConfiguration" not in kinds
        assert any(o["kind"] == "Deployment" for o in objs)


class TestCoordinator:
    def test_apply_sets_available(self, cfg):
        cluster = FakeCluster()
        obj = Coordinator(cluster).apply(cfg)
        assert ob.cond_is_true(obj, COND_AVAILABLE)
        assert not ob.cond_is_true(obj, COND_DEGRADED)
        assert cluster.get("apps/v1", "Deployment", "jaxjob-controller", "kubeflow")
        assert cluster.get("v1", "Namespace", "kubeflow")

    def test_second_apply_idempotent(self, cfg):
        """kfctl_second_apply.py contract."""
        cluster = FakeCluster()
        coord = Coordinator(cluster)
        coord.apply(cfg)
        rvs = {(o["kind"], ob.meta(o)["name"]): ob.meta(o)["resourceVersion"]
               for o in cluster.list("apps/v1", "Deployment", namespace="kubeflow")}
        coord.apply(cfg)
        rvs2 = {(o["kind"], ob.meta(o)["name"]): ob.meta(o)["resourceVersion"]
                for o in cluster.list("apps/v1", "Deployment", namespace="kubeflow")}
        assert rvs == rvs2

    def test_apply_failure_sets_degraded(self, cfg):
        cluster = FakeCluster()

        class Boom(Exception):
            pass

        class FailingPlatform:
            def apply(self, cfg):
                raise Boom("dm quota exceeded")

        coord = Coordinator(cluster, provider=FailingPlatform())
        with pytest.raises(Boom):
            coord.apply(cfg)
        obj = coord.status(cfg.name)
        assert ob.cond_is_true(obj, COND_DEGRADED)

    def test_delete_removes_components(self, cfg):
        cluster = FakeCluster()
        coord = Coordinator(cluster)
        coord.apply(cfg)
        coord.delete(cfg)
        assert cluster.list("apps/v1", "Deployment", namespace="kubeflow") == []
        assert coord.status(cfg.name) is None

    def test_gke_platform_command_shape(self):
        cfg = TpuDef.from_dict({
            "metadata": {"name": "kf"},
            "spec": {"platform": {"kind": "gke-tpu", "project": "p", "zone": "us-z",
                                  "accelerator": "tpu-v5-lite-podslice",
                                  "topology": "4x4"}}})
        cmds = GkeTpuPlatform().commands(cfg)
        joined = " ".join(cmds[0])
        assert "--project=p" in joined
        assert "gke-tpu-topology=4x4" in joined


class TestServer:
    def test_create_then_poll(self, cfg):
        import requests

        cluster = FakeCluster()
        srv = TpctlServer(cluster)
        svc = srv.serve(host="127.0.0.1")
        svc.serve_background()
        try:
            base = f"http://127.0.0.1:{svc.port}"
            r = requests.post(f"{base}/tpctl/apps/v1/create",
                              json=yaml.safe_load(example_yaml()), timeout=5)
            assert r.status_code == 200, r.text
            # poll until the worker finishes the apply
            import time as _t

            for _ in range(100):
                g = requests.post(f"{base}/tpctl/apps/v1/get",
                                  json={"name": "kubeflow-tpu"}, timeout=5)
                if g.status_code == 200:
                    conds = {c["type"]: c["status"]
                             for c in g.json()["conditions"]}
                    if conds.get(COND_AVAILABLE) == "True":
                        break
                _t.sleep(0.05)
            else:
                pytest.fail("deployment never became available")
        finally:
            svc.shutdown()

    def test_conflicting_spec_rejected(self, cfg):
        srv = TpctlServer(FakeCluster())
        from kubeflow_tpu.utils.httpd import HttpReq

        body1 = json.dumps(yaml.safe_load(example_yaml())).encode()
        req1 = HttpReq("POST", "/tpctl/apps/v1/create", {}, {}, {}, body1)
        assert srv.router().dispatch(req1).status == 200
        changed = yaml.safe_load(example_yaml())
        changed["spec"]["namespace"] = "other"
        req2 = HttpReq("POST", "/tpctl/apps/v1/create", {}, {}, {},
                       json.dumps(changed).encode())
        assert srv.router().dispatch(req2).status == 409

    def test_gc_reaps_idle_workers(self, cfg):
        srv = TpctlServer(FakeCluster(), ttl_s=0.0)
        from kubeflow_tpu.utils.httpd import HttpReq

        body = json.dumps(yaml.safe_load(example_yaml())).encode()
        srv.router().dispatch(HttpReq("POST", "/tpctl/apps/v1/create", {}, {}, {}, body))
        assert srv.workers
        import time as _t

        # GC only reaps IDLE workers (busy ones keep their identity so a
        # re-submit can't start a second concurrent apply) — wait for the
        # worker thread to drain its apply, bounded (flaked at a fixed
        # 10 ms under CPU contention)
        deadline = _t.monotonic() + 30.0
        while _t.monotonic() < deadline:
            if not any(w.busy for w in srv.workers.values()):
                break
            _t.sleep(0.05)
        assert srv.gc_once() == ["kubeflow-tpu"]
        assert not srv.workers


class TestCli:
    def test_generate_and_dry_run_apply(self, capsys):
        from kubeflow_tpu.tpctl.cli import main

        assert main(["generate"]) == 0
        docs = list(yaml.safe_load_all(capsys.readouterr().out))
        assert any(d["kind"] == "CustomResourceDefinition" for d in docs)
        assert main(["apply", "--dry-run"]) == 0
        out = capsys.readouterr().out
        assert "TpuDefAvailable" in out

    def test_example_subcommand(self, capsys):
        from kubeflow_tpu.tpctl.cli import main

        assert main(["example"]) == 0
        cfg = TpuDef.from_dict(yaml.safe_load(capsys.readouterr().out))
        assert cfg.name == "kubeflow-tpu"


class TestHttpClient:
    """kfctlClient flow: create over HTTP, poll to Available
    (bootstrap/cmd/kfctlClient/main.go:141, run :59)."""

    def test_apply_and_wait_over_http(self, cfg):
        import threading

        from kubeflow_tpu.tpctl.client import TpctlClient

        cluster = FakeCluster()
        srv = TpctlServer(cluster)
        svc = srv.serve(host="127.0.0.1", port=0)
        threading.Thread(target=svc.serve_forever, daemon=True).start()
        client = TpctlClient(f"http://127.0.0.1:{svc.port}")
        assert client.check_access()
        status = client.apply_and_wait(cfg, timeout_s=30, poll_s=0.05)
        conds = {c["type"]: c["status"] for c in status["conditions"]}
        assert conds.get(COND_AVAILABLE) == "True"
        # the worker actually applied manifests to the backing cluster
        assert cluster.list("apps/v1", "Deployment", namespace="kubeflow")

    def test_check_access_false_when_down(self):
        from kubeflow_tpu.tpctl.client import TpctlClient

        client = TpctlClient("http://127.0.0.1:1")  # nothing listening
        assert not client.check_access()

    def test_wait_times_out_cleanly(self):
        # Live server, but the deployment never exists: the poll loop must
        # raise TimeoutError at the fake-clock deadline, not spin or hang.
        import threading

        from kubeflow_tpu.tpctl.client import TpctlClient

        srv = TpctlServer(FakeCluster())
        svc = srv.serve(host="127.0.0.1", port=0)
        threading.Thread(target=svc.serve_forever, daemon=True).start()
        client = TpctlClient(f"http://127.0.0.1:{svc.port}")
        t = [0.0]
        with pytest.raises(TimeoutError):
            client.wait_available("never-created", timeout_s=10, poll_s=1,
                                  clock=lambda: t[0],
                                  sleep=lambda s: t.__setitem__(0, t[0] + s))


class TestDoctor:
    """tpctl doctor — the wait_for_kubeflow/kf_is_ready readiness check
    as a CLI against the live cluster."""

    def test_reports_missing_then_healthy(self, cfg):
        from kubeflow_tpu.tpctl.cli import doctor_report

        cluster = FakeCluster()
        rows, healthy = doctor_report(cluster, cfg)
        assert not healthy
        assert all(r["status"] == "missing" for r in rows)

        Coordinator(cluster).apply(cfg)
        rows, healthy = doctor_report(cluster, cfg)
        missing = [r for r in rows if r["status"] == "missing"]
        assert not missing
        # deployments exist but report 0 ready replicas -> not healthy yet
        notready = [r for r in rows if r["status"] == "not-ready"]
        assert notready and not healthy
        # a controller "starts": readyReplicas catches up
        for r in notready:
            d = cluster.get("apps/v1", "Deployment", r["name"], cfg.namespace)
            d.setdefault("status", {})["readyReplicas"] = \
                (d.get("spec") or {}).get("replicas", 1)
            cluster.update_status(d)
        rows, healthy = doctor_report(cluster, cfg)
        assert healthy, [r for r in rows if not r["ok"]]

    def test_cli_exit_codes(self, cfg, tmp_path, capsys):
        from kubeflow_tpu.tpctl import cli

        # dry-run applies to a fresh in-memory cluster; deployments have
        # no kubelet to become ready -> doctor says unhealthy (rc 1)
        f = tmp_path / "tpudef.yaml"
        f.write_text(cfg.dump())
        rc = cli.main(["doctor", "-f", str(f), "--dry-run"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "platform NOT healthy" in out


def test_ha_controllers_render_leader_election(cfg):
    cfg.ha_controllers = True
    objs = manifests.render(cfg)
    ctl = next(o for o in objs if o.get("kind") == "Deployment"
               and ob.meta(o)["name"] == "jaxjob-controller")
    assert ctl["spec"]["replicas"] == 2
    env = {e["name"]: e["value"]
           for e in ctl["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["ENABLE_LEADER_ELECTION"] == "true"
    assert env["POD_NAMESPACE"] == cfg.namespace
    # web apps stay single-replica (stateless; scale separately)
    dash = next(o for o in objs if o.get("kind") == "Deployment"
                and ob.meta(o)["name"] == "centraldashboard")
    assert dash["spec"].get("replicas", 1) == 1
    # default: no HA knobs
    cfg.ha_controllers = False
    objs = manifests.render(cfg)
    ctl = next(o for o in objs if o.get("kind") == "Deployment"
               and ob.meta(o)["name"] == "jaxjob-controller")
    env = {e["name"]: e["value"]
           for e in ctl["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert "ENABLE_LEADER_ELECTION" not in env


class TestSubprocessIsolation:
    """router.go:275-357 parity: per-deployment OS-process isolation —
    a poisoned apply kills one child, never the REST plane."""

    def test_subprocess_worker_applies_through_child_process(self):
        import requests as rq

        from kubeflow_tpu.control.k8s.apiserver import ApiServer, client_for
        from kubeflow_tpu.tpctl.server import TpctlServer

        api = ApiServer().serve_background()
        try:
            srv = TpctlServer(client_for(api), isolation="subprocess",
                              apiserver_url=api.url)
            svc = srv.serve(host="127.0.0.1", port=0).serve_background()
            body = {"metadata": {"name": "iso-dep"},
                    "spec": {"applications": ["crds"]}}
            r = rq.post(f"http://127.0.0.1:{svc.port}/tpctl/apps/v1/create",
                        json=body, timeout=10)
            assert r.status_code == 200, r.text
            deadline = time.monotonic() + 60
            w = srv.workers["iso-dep"]
            while time.monotonic() < deadline:
                g = rq.post(f"http://127.0.0.1:{svc.port}/tpctl/apps/v1/get",
                            json={"name": "iso-dep"}, timeout=10)
                if w.error or (g.status_code == 200
                               and (g.json().get("conditions")
                                    or g.json().get("status"))):
                    break
                time.sleep(0.5)
            assert w.error is None, w.error
            assert w.last_pid is not None  # a real child process ran
            # the child's apply landed in the shared apiserver
            from kubeflow_tpu.tpctl.tpudef import API_VERSION as TAV
            tpu = api.cluster.get(TAV, "TpuDef",
                                  "iso-dep")
            assert tpu is not None
            svc.shutdown()
        finally:
            api.shutdown()

    def test_poisoned_apply_kills_child_not_server(self):
        import requests as rq

        from kubeflow_tpu.control.k8s.apiserver import ApiServer, client_for
        from kubeflow_tpu.tpctl.server import TpctlServer, _SubprocessWorker

        api = ApiServer().serve_background()
        try:
            srv = TpctlServer(client_for(api), isolation="subprocess",
                              apiserver_url="http://127.0.0.1:1")  # dead
            svc = srv.serve(host="127.0.0.1", port=0).serve_background()
            r = rq.post(f"http://127.0.0.1:{svc.port}/tpctl/apps/v1/create",
                        json={"metadata": {"name": "doomed"},
                              "spec": {"applications": ["crds"]}}, timeout=10)
            assert r.status_code == 200
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if srv.workers["doomed"].error:
                    break
                time.sleep(0.5)
            assert srv.workers["doomed"].error, "child failure not surfaced"
            # the REST plane survived and serves other deployments
            r2 = rq.post(f"http://127.0.0.1:{svc.port}/tpctl/apps/v1/get",
                         json={"name": "doomed"}, timeout=10)
            assert r2.status_code == 200
            assert "exited" in r2.json().get("error", "") or \
                r2.json().get("error")
            svc.shutdown()
        finally:
            api.shutdown()

    def test_subprocess_isolation_requires_apiserver_url(self):
        from kubeflow_tpu.control.k8s.fake import FakeCluster
        from kubeflow_tpu.tpctl.server import TpctlServer

        with pytest.raises(ValueError):
            TpctlServer(FakeCluster(), isolation="subprocess")


def test_full_worker_queue_is_429_not_deadlock():
    """submit() runs under the server lock: a full queue must answer 429
    immediately, never block the REST plane for an apply duration."""
    import threading as _t

    from kubeflow_tpu.tpctl.server import _Worker
    from kubeflow_tpu.utils.httpd import ApiHttpError

    gate = _t.Event()

    class _Blocked:
        def apply(self, cfg):
            gate.wait(30)

    w = _Worker("jam", _Blocked())
    cfg = TpuDef(name="jam", applications=("crds",))
    with pytest.raises(ApiHttpError) as ei:
        for _ in range(12):  # queue cap 10 + the in-flight one
            w.submit(cfg)
    assert ei.value.status == 429
    gate.set()


def test_gc_never_reaps_a_busy_worker():
    """A worker with queued or in-flight applies keeps its identity past
    TTL: reaping it would let a re-submit run a second concurrent apply
    for the same deployment."""
    import threading as _t

    from kubeflow_tpu.tpctl.server import TpctlServer

    gate = _t.Event()
    started = _t.Event()

    class _Slow:
        def apply(self, cfg):
            started.set()
            gate.wait(30)

    srv = TpctlServer(FakeCluster(), ttl_s=0.01,
                      coordinator_factory=lambda: _Slow())
    from kubeflow_tpu.utils.httpd import HttpReq

    body = json.dumps({"metadata": {"name": "busy"},
                       "spec": {"applications": ["crds"]}}).encode()
    req = HttpReq(method="POST", path="/tpctl/apps/v1/create", params={},
                  query={}, headers={}, body=body)
    srv.create(req)
    assert started.wait(10)
    time.sleep(0.05)  # past the ttl while the apply is in flight
    assert srv.gc_once() == []  # busy: NOT reaped
    w = srv.workers["busy"]
    gate.set()
    for _ in range(100):
        if not w.busy:
            break
        time.sleep(0.05)
    assert srv.gc_once() == ["busy"]  # idle now: reaped
