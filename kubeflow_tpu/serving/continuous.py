"""Continuous batching for LM serving: slot-based lockstep decode.

The MicroBatcher coalesces concurrent requests into one `generate()`
call — but then the whole group decodes together: a request arriving one
step later waits for the ENTIRE previous generation, and every request
in a group pays the longest member's latency. Continuous batching is the
transformer-serving answer (beyond anything the reference's TF-Serving
story had): a fixed pool of S slots decodes in lockstep, requests JOIN
at any step boundary (prefilled off to the side, then scattered into a
free slot's cache rows) and LEAVE independently when their token budget
is done. Throughput stays at batched-decode levels while p50 latency
drops to ~arrival + own-length.

TPU-shaped by construction: the decode step is ONE compiled program of
static shape [S, 1] forever — no per-arrival recompiles — with per-slot
positions (models/transformer.py vector `decode_index`), one-hot cache
scatters instead of dynamic shapes, and masked sampling for idle slots.

Single-host scheduler; the decode/prefill programs themselves run under
whatever mesh the variables are sharded over.
"""

from __future__ import annotations

import queue
import threading
from typing import Any

log = __import__("logging").getLogger("kubeflow_tpu.serving.continuous")


class SlotDecoder:
    """S-slot continuous decoder over a KV-cache LM.

    Host API: ``submit(tokens) -> list[int]`` blocks the calling thread
    until that request's continuation is done; many threads may submit
    concurrently. A background loop admits pending requests into free
    slots at step boundaries and advances all active slots one token per
    tick.
    """

    def __init__(self, model, variables, *, slots: int = 8,
                 prompt_len: int = 128, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 mesh=None):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.runtime.generate import (
            check_decode_geometry, init_cache, prefill_scan)

        check_decode_geometry(model, prompt_len, max_new_tokens)
        self.model = model
        self.variables = variables
        self.S = slots
        self.P = prompt_len
        self.N = max_new_tokens
        self.mesh = mesh
        self._jnp = jnp
        self._jax = jax
        cfg_vocab = model.cfg.vocab_size

        # Params are jit ARGUMENTS everywhere below, never closure
        # captures: a closed-over weight tree is serialized into the
        # program as inline constants — a gpt-350m continuous decoder
        # ships ~700MB of MLIR, which remote-compile tunnels reject
        # outright (r5 ledger: HTTP 413 "length limit exceeded") and
        # which turns every weight swap into a full retrace. server.py's
        # predict path (fwd(params, x)) always did it right; this
        # decoder now matches.
        self._params = {"params": variables["params"]}

        # -- compiled: batch-K prefill (the ONE prefill implementation,
        #    shared with generate(): runtime/generate.py prefill_scan).
        #    K is a static batch size — one compile per size in
        #    _PREFILL_SIZES, so an idle-decoder burst prefills together
        #    instead of paying burst_size serial scans. ------------------
        def _prefill(params, prompts_kp, pad_lens_k):
            cache_k = init_cache(model, prompts_kp.shape[0])
            return prefill_scan(model, params, cache_k, prompts_kp,
                                pad_lens_k)

        self._prefill = jax.jit(_prefill)

        # -- compiled: install K prefilled rows into K slots in ONE
        #    program (K static, unrolled; slot ids traced) --------------
        def _install(state, cache_k, logits_k, slots_k, pads_k):
            cache, last, pos, remaining, out, pads, rng = state
            k = logits_k.shape[0]
            for i in range(k):  # static unroll: K is a compile-time size
                si = slots_k[i]
                cache = jax.tree.map(
                    lambda big, kk, i=i, si=si: jax.lax.dynamic_update_slice(
                        big, kk[i:i + 1].astype(big.dtype),
                        (si,) + (0,) * (big.ndim - 1)),
                    cache, cache_k)
                last = jax.lax.dynamic_update_slice(
                    last, logits_k[i][None], (si, 0))
                pos = _set1(jnp, pos, si, self.P)
                remaining = _set1(jnp, remaining, si, self.N)
                out = jax.lax.dynamic_update_slice(
                    out, jnp.zeros((1, self.N), jnp.int32), (si, 0))
                pads = _set1(jnp, pads, si, pads_k[i])
            return (cache, last, pos, remaining, out, pads, rng)

        self._install = jax.jit(_install, donate_argnums=(0,))

        # -- compiled: deactivate slots (dummy prefill targets) ----------
        def _clear_slots(state, slots_k):
            cache, last, pos, remaining, out, pads, rng = state
            clear = (jnp.arange(self.S)[:, None]
                     == slots_k[None, :]).any(axis=1)
            remaining = jnp.where(clear, 0, remaining)
            return (cache, last, pos, remaining, out, pads, rng)

        self._clear_slots = jax.jit(_clear_slots, donate_argnums=(0,))

        # -- compiled: one lockstep decode tick for all S slots ----------
        def _tick(params, state):
            cache, last, pos, remaining, out, pads, rng = state
            from kubeflow_tpu.runtime.generate import _sample

            active = remaining > 0
            rng, sub = jax.random.split(rng)
            tok = _sample(last, temperature, top_k, sub)
            # record the sampled token at each active slot's next column
            # (column index = tokens generated so far = N - remaining)
            ncol = self.N - remaining
            hot = (jnp.arange(self.N)[None, :] == ncol[:, None]) \
                & active[:, None]
            out = jnp.where(hot, tok[:, None], out)
            # advance the model one position for every slot (idle slots
            # compute too — lockstep static shape — but their state is
            # frozen by the masks below and their cache rows are fully
            # overwritten at the next install)
            logits_next, mut = model.apply(
                params | {"cache": cache}, tok[:, None], train=False,
                decode_index=pos, mutable=["cache"], pad_len=pads)
            pos = jnp.where(active, pos + 1, pos)
            remaining = jnp.where(active, remaining - 1, remaining)
            last = jnp.where(active[:, None], logits_next[:, 0], last)
            return (mut["cache"], last, pos, remaining, out, pads, rng)

        self._step = jax.jit(_tick, donate_argnums=(1,))

        # -- compiled: FUSE ticks in one dispatched program. Each
        #    dispatch costs a host round-trip; through a remote tunnel
        #    that round-trip can exceed the tick's own compute (r5
        #    serving ledger: ~235 ms/tick on gpt-350m through the axon
        #    remote-compile tunnel), so decode becomes latency-bound.
        #    Fusing amortizes the dispatch FUSE-fold. Correctness is
        #    unchanged — the tick body masks on remaining>0, so a slot
        #    finishing mid-window just idles until the window ends; the
        #    cost is admission/completion latency bounded at FUSE ticks,
        #    which is why the loop only fuses when nothing is waiting
        #    and every active slot has >= FUSE tokens to go. ------------
        FUSE = 8

        def _step_fused(params, state):
            def body(st, _):
                return _tick(params, st), None

            st, _ = jax.lax.scan(body, state, None, length=FUSE)
            return st

        self._step_fused = jax.jit(_step_fused, donate_argnums=(1,))
        self._fuse = FUSE

        # -- device state (rebuildable: a failed donated call leaves the
        #    old buffers dead, so recovery re-creates from scratch) ------
        def _fresh_state():
            return (
                init_cache(model, self.S),
                jnp.zeros((self.S, cfg_vocab), jnp.float32),
                jnp.zeros((self.S,), jnp.int32),            # pos
                jnp.zeros((self.S,), jnp.int32),            # remaining
                jnp.zeros((self.S, self.N), jnp.int32),     # out
                jnp.zeros((self.S,), jnp.int32),            # pad_len
                jax.random.PRNGKey(seed),
            )

        self._fresh_state = _fresh_state
        self.state = _fresh_state()
        # prefill batch sizes we're willing to compile (smallest >= the
        # waiting count is used; idle bursts prefill together)
        self._PREFILL_SIZES = tuple(sorted(
            {n for n in (1, 2, 4, 8, 16, 32) if n < self.S} | {self.S}))
        self._free: list[int] = list(range(self.S))
        self._pending: "queue.Queue[tuple]" = queue.Queue()
        # guards the _stop flag vs submit(): an enqueue must strictly
        # precede the shutdown drain or the caller waits forever
        self._lock = threading.Lock()
        self._active = 0  # host-side mirror (device state is donated)
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="slot-decoder")
        self._thread.start()

    # -- host API ----------------------------------------------------------

    def submit(self, tokens: list[int]) -> list[int]:
        """Block until the continuation for this prompt is decoded."""
        row = [int(t) for t in tokens][-self.P:]
        pad = self.P - len(row)
        return self.submit_padded([0] * pad + row, pad)

    def submit_padded(self, padded_row, pad: int) -> list[int]:
        """Pre-padded variant for callers that already align rows."""
        import numpy as np

        prompt = np.asarray(padded_row, dtype=np.int32)
        ev = threading.Event()
        sink: list = []
        with self._lock:  # enqueue-before-drain or fail fast, atomically
            if self._stop:
                raise RuntimeError("decoder shut down")
            self._pending.put((prompt, pad, ev, sink))
        self._wake.set()
        ev.wait()
        if sink and isinstance(sink[0], Exception):
            raise sink[0]
        return sink

    def close(self) -> None:
        with self._lock:
            self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    @property
    def active_slots(self) -> int:
        # host-side mirror: reading self.state from another thread races
        # the loop's buffer donation (donate_argnums)
        return self._active

    # -- scheduler loop ----------------------------------------------------

    def _loop(self) -> None:
        import contextlib

        import numpy as np

        jnp = self._jnp
        owners: dict[int, tuple[threading.Event, list]] = {}
        ctx = self.mesh if self.mesh is not None else None

        def fail_all(err, batch=()):
            """Poison every waiter and REBUILD device state: after a
            failed donated call the old buffers are dead — continuing on
            them would turn the decoder into a zombie that errors every
            future request while still accepting submits."""
            for _p, _pad, ev, sink in batch:
                sink.append(err)
                ev.set()
            for s_, (ev, sink) in list(owners.items()):
                sink.append(err)
                ev.set()
            owners.clear()
            self._free = list(range(self.S))
            self.state = self._fresh_state()

        last_rem = np.zeros(self.S, np.int64)  # host mirror of remaining
        while not self._stop:
            try:
                # admit pending requests into free slots (step boundary).
                # Idle decoder: take a BATCH of waiting prompts (padded
                # up to the next supported prefill size) so an idle
                # burst prefills together. Anything mid-generation:
                # admit at most ONE per tick — a burst must not stall
                # in-flight decodes.
                if self._free and not self._pending.empty():
                    want = 1 if owners else len(self._free)
                    batch = []
                    while len(batch) < want and not self._pending.empty():
                        batch.append(self._pending.get_nowait())
                    # validate rows FIRST; a wrong-length row (the
                    # submit_padded caller's bug) fails THAT caller only
                    # and never enters the batch, so row indices below
                    # stay aligned with the prefill outputs
                    valid = []
                    for prompt, pad, ev, sink in batch:
                        if prompt.shape != (self.P,):
                            sink.append(ValueError(
                                f"padded row must have length {self.P}, "
                                f"got {prompt.shape}"))
                            ev.set()
                        else:
                            valid.append((prompt, pad, ev, sink))
                    batch = valid
                    if batch:
                        k = next(n for n in self._PREFILL_SIZES
                                 if n >= len(batch))
                        prompts = np.zeros((k, self.P), np.int32)
                        pads = np.zeros((k,), np.int32)
                        for i, (prompt, pad, _ev, _sink) in enumerate(batch):
                            prompts[i] = prompt
                            pads[i] = pad
                        slots = [self._free.pop()
                                 for _ in range(len(batch))]
                        # dummy rows (k > len(batch)) target REMAINING
                        # free slots: they hold no generation, and any
                        # future real install fully overwrites the row.
                        # Idle admission guarantees enough free slots
                        # (batch <= free == S >= k); active admission is
                        # always k == batch == 1.
                        dummies = self._free[:k - len(slots)]
                        pad_slots = slots + dummies
                        assert len(pad_slots) == k, (k, slots, dummies)
                        try:
                            with (ctx or contextlib.nullcontext()):
                                cache_k, logits_k = self._prefill(
                                    self._params,
                                    jnp.asarray(prompts), jnp.asarray(pads))
                                new_state = self._install(
                                    self.state, cache_k, logits_k,
                                    jnp.asarray(pad_slots, jnp.int32),
                                    jnp.asarray(pads))
                        except Exception as e:
                            self._free.extend(slots)
                            fail_all(e, batch)
                        else:
                            self.state = new_state
                            # dummy installs left remaining>0 on their
                            # free slots: zero them so the step loop
                            # never decodes an unowned slot
                            if dummies:
                                self.state = self._clear_slots(
                                    self.state,
                                    jnp.asarray(dummies, jnp.int32))
                            last_rem = np.array(last_rem)  # writable copy
                            for s_, (prompt, pad, ev, sink) in zip(
                                    slots, batch):
                                owners[s_] = (ev, sink)
                                last_rem[s_] = self.N
                self._active = len(owners)
                if not owners:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                # fuse ticks when every active slot has a full window of
                # tokens left AND no waiter could be admitted any sooner
                # by single-stepping: with all remaining >= FUSE no slot
                # can complete inside the window, so when the decoder is
                # SATURATED (no free slot) a queued request loses zero
                # ticks to fusion — that saturated case is exactly the
                # latency-bound regime the fusion exists for (host-side
                # remaining mirror: last readback, N for fresh installs)
                fuse = ((self._pending.empty() or not self._free)
                        and all(int(last_rem[s_]) >= self._fuse
                                for s_ in owners))
                with (ctx or contextlib.nullcontext()):
                    self.state = (self._step_fused if fuse else
                                  self._step)(self._params, self.state)
                remaining = np.asarray(self.state[3])
                last_rem = remaining
                out = None
                for s_ in list(owners):
                    if remaining[s_] <= 0:
                        if out is None:  # one readback per tick, lazily
                            out = np.asarray(self.state[4])
                        ev, sink = owners.pop(s_)
                        sink.extend(int(t) for t in out[s_])
                        ev.set()
                        self._free.append(s_)
                self._active = len(owners)
            except Exception as e:  # a broken step: poison + rebuild
                log.exception("slot-decoder loop failed")
                fail_all(e)
                self._active = 0
        # shutdown: fail any stragglers
        for ev, sink in list(owners.values()):
            sink.append(RuntimeError("decoder shut down"))
            ev.set()
        while not self._pending.empty():
            _p, _pad, ev, sink = self._pending.get_nowait()
            sink.append(RuntimeError("decoder shut down"))
            ev.set()


def _set1(jnp, vec, i, val):
    """vec[i] = val with a dynamic index (static-shape scatter)."""
    return jnp.where(jnp.arange(vec.shape[0]) == i,
                     jnp.asarray(val, vec.dtype), vec)
