"""Parallelism primitives: meshes, shardings, distributed bootstrap.

TPU-native replacement for the reference's parallelism matrix (SURVEY.md
§2.5): parameter-server data parallelism and MPI/NCCL allreduce become XLA
collectives over ICI, compiled into the step function by GSPMD.
"""

from kubeflow_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_PIPELINE,
    AXIS_SEQ,
    MeshSpec,
    build_mesh,
)
from kubeflow_tpu.parallel.dist import (
    DistConfig,
    initialize_from_env,
    is_coordinator,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_FSDP",
    "AXIS_MODEL",
    "AXIS_PIPELINE",
    "AXIS_SEQ",
    "MeshSpec",
    "build_mesh",
    "DistConfig",
    "initialize_from_env",
    "is_coordinator",
]
