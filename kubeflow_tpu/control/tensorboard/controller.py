"""Tensorboard controller: CR -> Deployment + Service + VirtualService.

Reference: tensorboard-controller/controllers/tensorboard_controller.go
(:53 Reconcile, generateDeployment :129, generateService :208,
generateVirtualService :228, isCloudPath :277). TPU twist: the image
serves TensorBoard with the JAX profiler plugin (xprof traces written by
the jaxrt runtime land in logdir/plugins/profile), so the same CR fronts
both scalars and TPU profiles. Non-cloud logdir paths mount a PVC, cloud
paths (gs://, s3://) are passed straight to tensorboard --logdir.
"""

from __future__ import annotations

import os

from kubeflow_tpu.control import reconcilehelper as rh
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.runtime import Controller, Reconciler, Request, Result

GROUP = "tensorboard.kubeflow.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "Tensorboard"

DEFAULT_IMAGE = "kubeflow-tpu/tensorboard:latest"


def new_tensorboard(name: str, namespace: str = "default", logspath: str = "") -> dict:
    return ob.new_object(API_VERSION, KIND, name, namespace, spec={"logspath": logspath})


def is_cloud_path(path: str) -> bool:
    """isCloudPath (:277): gs://, s3://, or /cns/ (legacy)."""
    return path.startswith(("gs://", "s3://", "/cns/"))


def crd_manifest() -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"tensorboards.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {"kind": KIND, "listKind": "TensorboardList",
                      "plural": "tensorboards", "singular": "tensorboard"},
            "scope": "Namespaced",
            "versions": [{
                "name": VERSION, "served": True, "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True}},
            }],
        },
    }


class TensorboardReconciler(Reconciler):
    def generate_deployment(self, tb: dict) -> dict:
        m = ob.meta(tb)
        logspath = (tb.get("spec") or {}).get("logspath", "")
        container = {
            "name": "tensorboard",
            "image": (tb.get("spec") or {}).get("image", DEFAULT_IMAGE),
            "command": ["tensorboard", f"--logdir={logspath}", "--bind_all",
                        "--port=6006"],
            "ports": [{"containerPort": 6006, "name": "http"}],
        }
        pod_spec: dict = {"containers": [container]}
        if logspath and not is_cloud_path(logspath):
            # local/NFS path -> PVC mount (:184-206)
            container["volumeMounts"] = [{"name": "logs", "mountPath": logspath}]
            pod_spec["volumes"] = [{
                "name": "logs",
                "persistentVolumeClaim": {"claimName": f"{m['name']}-logs"},
            }]
        return ob.new_object(
            "apps/v1", "Deployment", m["name"], m["namespace"],
            spec={
                "replicas": 1,
                "selector": {"matchLabels": {"app": m["name"]}},
                "template": {
                    "metadata": {"labels": {"app": m["name"]}},
                    "spec": pod_spec,
                },
            },
        )

    def generate_service(self, tb: dict) -> dict:
        m = ob.meta(tb)
        return ob.new_object(
            "v1", "Service", m["name"], m["namespace"],
            spec={
                "selector": {"app": m["name"]},
                "ports": [{"name": f"http-{m['name']}", "port": 80,
                           "targetPort": 6006}],
            },
        )

    def generate_virtual_service(self, tb: dict) -> dict:
        m = ob.meta(tb)
        prefix = f"/tensorboard/{m['namespace']}/{m['name']}/"
        return ob.new_object(
            "networking.istio.io/v1alpha3", "VirtualService",
            f"tensorboard-{m['namespace']}-{m['name']}", m["namespace"],
            spec={
                "hosts": ["*"],
                "gateways": [os.environ.get("ISTIO_GATEWAY", "kubeflow/kubeflow-gateway")],
                "http": [{
                    "match": [{"uri": {"prefix": prefix}}],
                    "rewrite": {"uri": "/"},
                    "route": [{"destination": {
                        "host": f"{m['name']}.{m['namespace']}.svc.cluster.local",
                        "port": {"number": 80}}}],
                }],
            },
        )

    def reconcile(self, client, req: Request) -> Result | None:
        tb = client.get_or_none(API_VERSION, KIND, req.name, req.namespace)
        if tb is None or ob.meta(tb).get("deletionTimestamp"):
            return None
        rh.reconcile_child(client, tb, self.generate_deployment(tb))
        rh.reconcile_child(client, tb, self.generate_service(tb))
        if os.environ.get("USE_ISTIO", "false").lower() == "true":
            rh.reconcile_child(client, tb, self.generate_virtual_service(tb))
        dep = client.get_or_none("apps/v1", "Deployment", req.name, req.namespace)
        ready = bool(dep and (dep.get("status") or {}).get("readyReplicas"))
        ob.cond_set(tb, "Ready", "True" if ready else "False",
                    "DeploymentReady" if ready else "DeploymentNotReady")
        client.update_status(tb)
        return None


def build_controller(client) -> Controller:
    ctl = Controller("tensorboard", client, TensorboardReconciler())
    ctl.watches_primary(API_VERSION, KIND).owns("apps/v1", "Deployment").owns("v1", "Service")
    return ctl
