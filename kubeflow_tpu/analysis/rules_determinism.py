"""tpulint virtual-time determinism rules (DET6xx) for replay-critical
modules.

The ROADMAP's macro-bench composes every virtual-time bench into one
simulated world whose value rests on byte-identical decision-fingerprint
replay. That property dies silently: one ambient ``time.time()`` or
unseeded ``random.Random()`` in a decision path and two runs of the
same scenario diverge, with nothing failing until someone diffs
fingerprints. The DET6xx family makes "this module is replayable" a
static property of the modules the benches actually replay:

- **DET601** wall-clock reads (``time.time/monotonic/perf_counter``,
  ``datetime.now``) in a replay-critical module. The injectable idiom —
  ``def __init__(self, clock=time.monotonic)`` then ``self.clock()`` —
  is naturally clean because the rule fires on *calls*, not references.
  The analysis is call-graph propagated: a call into a helper that
  *returns* a wall-clock value (``ob.now_iso()``, or any program
  function whose return expression reaches a wall read and that has no
  clock-ish injection parameter) fires at the call site in the
  replay-critical module, where a fix or an audited suppression
  belongs.
- **DET602** unseeded / default-constructed RNGs (``random.Random()``
  with no seed, ``random.SystemRandom``) and ambient module-level
  ``random.*`` / ``numpy.random.*`` calls, which draw from process
  state no replay controls.
- **DET603** raw ``time.sleep`` not routed through an injectable
  sleeper (``self._sleep = time.sleep`` + ``self._sleep(...)`` is
  clean; a literal ``time.sleep(...)`` call is not replayable).
- **DET604** fingerprint-poisoning identity sources: ``uuid.uuid4``,
  ``os.urandom``, ``secrets.*``, and ``id()``-keyed ordering
  (``sorted(xs, key=id)``) — values that differ across processes and
  therefore across replays.

Scope is the module list the bench harnesses replay under virtual
clocks (see docs/scale.md "Determinism contract"); everything else in
the tree may read wall clocks freely. Suppressions carry the usual
audited justification and are held non-stale by HYG004.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from kubeflow_tpu.analysis.core import (
    Finding, Module, ProgramRule, call_name, register,
)

# The modules the virtual-time benches replay: decisions made here must
# be a pure function of injected inputs (clock, rng, sleeper, events).
_SCOPES = (
    "control/scheduler/",
    "control/cache",
    "serving/router",
    "serving/continuous",
    "obs/",
    "control/jaxservice",
    "control/jaxjob",
)

# Direct wall-clock sources, canonicalized through the import table.
_WALL_CALLS = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# Helpers known (by name) to return a wall-clock-derived value even
# when the defining module is outside the scanned program — keeps a
# per-file scan and a whole-tree scan agreeing on the same finding at
# the same call site, so suppressions stay HYG004-coherent.
_WALL_HELPERS = {"now_iso"}

# Parameters that mark a function as an injection seam: its callers can
# substitute a virtual clock, so its internal wall read is the seam's
# default, not an ambient read at the call site.
_CLOCKISH_PARAM = re.compile(
    r"^(clock|now|perf|timer|time_fn|time_source|sleep|sleeper)$"
    r"|_(clock|now|perf|sleep)$")

# Ambient module-level RNG draws (process-global state).
_RANDOM_AMBIENT = {
    "random", "uniform", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "expovariate",
    "betavariate", "triangular", "getrandbits", "randbytes", "seed",
}
_NP_AMBIENT = {
    "random", "rand", "randn", "randint", "uniform", "choice",
    "shuffle", "permutation", "normal", "seed",
}

# Identity sources whose values differ per-process (DET604).
_IDENTITY_CALLS = {"uuid.uuid4", "uuid.uuid1", "os.urandom"}

_FIXPOINT_CAP = 32


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(s in p for s in _SCOPES)


def _canon(name: str, imports: dict[str, tuple]) -> str:
    """Canonicalize a dotted call through the module's import aliases:
    ``_time.sleep`` -> ``time.sleep``, ``from datetime import datetime``
    + ``datetime.now`` -> ``datetime.datetime.now``. An unimported head
    passes through unchanged, so corpus fragments work verbatim."""
    parts = name.split(".")
    got = imports.get(parts[0])
    if got is not None:
        if got[0] == "mod":
            parts = got[1].split(".") + parts[1:]
        else:  # ("sym", base_module, symbol)
            parts = got[1].split(".") + [got[2]] + parts[1:]
    return ".".join(parts)


def _scope_modules(program) -> list[tuple[str, Module, dict]]:
    out = []
    for modname, module in program.modules.items():
        if _in_scope(module.path):
            out.append((modname, module,
                        program.imports.get(modname, {})))
    return out


def _clockish_seam(fn: ast.FunctionDef) -> bool:
    args = fn.args
    params = args.posonlyargs + args.args + args.kwonlyargs
    return any(_CLOCKISH_PARAM.search(a.arg) for a in params)


def _wall_returning(program) -> set[str]:
    """Function quals whose *return value* reaches a wall-clock read —
    the call-graph propagation behind DET601. A function with a
    clock-ish parameter is an injection seam and never taints callers.
    Bounded union fixpoint (like ``Program.may_held``)."""
    tainted: set[str] = set()
    returns: dict[str, list[ast.Call]] = {}
    for qual, fi in program.functions.items():
        if _clockish_seam(fi.node):
            continue
        imports = program.imports.get(fi.modname, {})
        calls: list[ast.Call] = []
        direct = False
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            for sub in ast.walk(node.value):
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                if name is None:
                    continue
                if _canon(name, imports) in _WALL_CALLS:
                    direct = True
                else:
                    calls.append(sub)
        if direct:
            tainted.add(qual)
        elif calls:
            returns[qual] = calls
    for _ in range(_FIXPOINT_CAP):
        changed = False
        for qual, calls in returns.items():
            if qual in tainted:
                continue
            fi = program.functions[qual]
            for sub in calls:
                if program._resolve_call(sub, fi) in tainted:
                    tainted.add(qual)
                    changed = True
                    break
        if not changed:
            break
    return tainted


@register
class WallClockInReplayPath(ProgramRule):
    """DET601: an ambient wall-clock read in a replay-critical module.
    Two bench runs of the same scenario read different values here, so
    the decision fingerprint diverges with no test failing."""

    id = "DET601"
    name = "wall-clock-in-replay-path"
    short = "ambient wall-clock read in a replay-critical module"

    def check_program(self, program) -> Iterator[Finding]:
        mods = _scope_modules(program)
        if not mods:
            return
        tainted = _wall_returning(program) if len(program.modules) > 1 else set()
        for modname, module, imports in mods:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                canon = _canon(name, imports)
                if canon in _WALL_CALLS:
                    yield self.finding(
                        module, node,
                        f"{canon}() read in a replay-critical module: "
                        "inject a clock (param or attribute defaulting "
                        "to the real one) so the bench can substitute "
                        "virtual time")
                elif name.rsplit(".", 1)[-1] in _WALL_HELPERS:
                    yield self.finding(
                        module, node,
                        f"{name}() returns a wall-clock value in a "
                        "replay-critical module: thread an injectable "
                        "now=/clock= instead (or suppress as a metadata "
                        "timestamp that never enters a decision)")
                else:
                    # resolve through the caller-agnostic symbol table:
                    # module-level and function-level call sites alike
                    callee = program.resolve_symbol(modname, name)
                    if callee is not None and callee in tainted:
                        yield self.finding(
                            module, node,
                            f"{name}() reaches a wall-clock read "
                            "(call-graph): give the helper a clock-ish "
                            "injection parameter or inject at this "
                            "call site")


@register
class UnseededRngInReplayPath(ProgramRule):
    """DET602: RNG state no replay controls — unseeded constructors and
    ambient module-level draws from the process-global generator."""

    id = "DET602"
    name = "unseeded-rng-in-replay-path"
    short = "unseeded / ambient RNG in a replay-critical module"

    def check_program(self, program) -> Iterator[Finding]:
        for modname, module, imports in _scope_modules(program):
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                canon = _canon(name, imports)
                if canon == "random.Random" and not node.args \
                        and not node.keywords:
                    yield self.finding(
                        module, node,
                        "unseeded random.Random() in a replay-critical "
                        "module: default-construct with a seed "
                        "(random.Random(0)) and let callers inject")
                elif canon == "random.SystemRandom":
                    yield self.finding(
                        module, node,
                        "random.SystemRandom draws from the OS entropy "
                        "pool — unreplayable by construction; use a "
                        "seeded Random injected by the caller")
                elif canon == "numpy.random.default_rng" \
                        and not node.args and not node.keywords:
                    yield self.finding(
                        module, node,
                        "unseeded numpy default_rng() in a "
                        "replay-critical module: pass an explicit seed")
                elif "." in canon:
                    head, leaf = canon.rsplit(".", 1)
                    if head == "random" and leaf in _RANDOM_AMBIENT:
                        yield self.finding(
                            module, node,
                            f"ambient random.{leaf}() uses the "
                            "process-global RNG: draw from an injected "
                            "seeded random.Random instead")
                    elif head == "numpy.random" and leaf in _NP_AMBIENT:
                        yield self.finding(
                            module, node,
                            f"ambient numpy.random.{leaf}() uses global "
                            "RNG state: use a seeded Generator")


@register
class RawSleepInReplayPath(ProgramRule):
    """DET603: a literal ``time.sleep`` pins the module to real time.
    The virtual-time benches advance a simulated clock; a raw sleep
    both slows the bench wall-clock and decouples the module from the
    simulated timeline."""

    id = "DET603"
    name = "raw-sleep-in-replay-path"
    short = "raw time.sleep in a replay-critical module"

    def check_program(self, program) -> Iterator[Finding]:
        for modname, module, imports in _scope_modules(program):
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                if _canon(name, imports) == "time.sleep":
                    yield self.finding(
                        module, node,
                        "raw time.sleep() in a replay-critical module: "
                        "route through an injectable sleeper "
                        "(self._sleep = time.sleep; self._sleep(...)) "
                        "so benches can substitute virtual time")


@register
class FingerprintPoisonInReplayPath(ProgramRule):
    """DET604: identity sources whose values differ per process. A
    uuid4 or os.urandom value that leaks into a decision fingerprint —
    or ``id()``-keyed ordering that leaks allocation addresses into
    iteration order — makes byte-identical replay impossible."""

    id = "DET604"
    name = "fingerprint-poison-in-replay-path"
    short = "per-process identity source in a replay-critical module"

    def check_program(self, program) -> Iterator[Finding]:
        for modname, module, imports in _scope_modules(program):
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name is None:
                    continue
                canon = _canon(name, imports)
                if canon in _IDENTITY_CALLS or canon.startswith("secrets."):
                    yield self.finding(
                        module, node,
                        f"{canon}() is a per-process identity source in "
                        "a replay-critical module: derive ids from "
                        "injected seeds, or suppress with the audit "
                        "that the value never enters a decision "
                        "fingerprint")
                elif self._id_keyed(node):
                    yield self.finding(
                        module, node,
                        "id()-keyed ordering leaks allocation addresses "
                        "into iteration order — unreplayable across "
                        "processes; key on a stable field instead")

    @staticmethod
    def _id_keyed(node: ast.Call) -> bool:
        orderer = (isinstance(node.func, ast.Name)
                   and node.func.id in ("sorted", "min", "max")) or (
                   isinstance(node.func, ast.Attribute)
                   and node.func.attr == "sort")
        if not orderer:
            return False
        return any(kw.arg == "key" and isinstance(kw.value, ast.Name)
                   and kw.value.id == "id" for kw in node.keywords)
