"""Collectives backend protocol (ISSUE 12 tentpole, parallel/backends.py).

Four contract surfaces, all hermetic on the conftest 8-device CPU mesh:

- **selection**: the JAXJOB_COLLECTIVES_BACKEND registry — default
  ``single`` (byte-compatible), explicit name > caller env > process
  env, unknown names rejected loudly;
- **level-mapped meshes**: axes mapped to LEVEL_DCN lay outermost on
  slice boundaries; the degenerate map reproduces ``mesh.build_mesh``
  exactly; JAXJOB_MESH_DCN_AXES rides extra axes (``pipe``) over DCN;
- **loopback formation**: the TCP join barrier forms/blocks/tears down
  real multi-process worlds with sockets only (no multiprocess jax —
  this image's CPU backend cannot run it), and in-process slice
  partitioning drives the dcn axis;
- **reduction equivalence**: the hierarchical reduce-scatter →
  all-reduce → all-gather shape is numerically the flat psum, and a
  model trained under Single vs Loopback(1 slice) lands on IDENTICAL
  params (the backend-equivalence property the elastic plane leans on).
"""

import socket
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel import backends as B
from kubeflow_tpu.parallel import dist as D
from kubeflow_tpu.parallel import mesh as M


@pytest.fixture(autouse=True)
def clean_world(monkeypatch):
    """Backend selection rides env vars and dist holds module world
    state — isolate both so tests compose in any order."""
    monkeypatch.delenv(B.ENV_BACKEND, raising=False)
    monkeypatch.delenv(B.ENV_DCN_AXES, raising=False)
    yield
    D.shutdown()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- selection ---------------------------------------------------------------


class TestSelection:
    def test_default_is_single_and_a_singleton(self):
        bk = B.get_backend()
        assert isinstance(bk, B.SingleBackend)
        assert bk.name == B.BACKEND_SINGLE
        assert B.get_backend() is bk

    def test_every_contract_name_resolves(self):
        for name in (B.BACKEND_SINGLE, B.BACKEND_LOOPBACK, B.BACKEND_TPU):
            assert B.get_backend(name).name == name

    def test_process_env_selects(self, monkeypatch):
        monkeypatch.setenv(B.ENV_BACKEND, B.BACKEND_LOOPBACK)
        assert isinstance(B.get_backend(), B.LoopbackBackend)

    def test_caller_env_beats_process_env(self, monkeypatch):
        monkeypatch.setenv(B.ENV_BACKEND, B.BACKEND_LOOPBACK)
        bk = B.get_backend(env={B.ENV_BACKEND: B.BACKEND_TPU})
        assert isinstance(bk, B.TpuIciDcnBackend)

    def test_explicit_name_beats_everything(self, monkeypatch):
        monkeypatch.setenv(B.ENV_BACKEND, B.BACKEND_TPU)
        bk = B.get_backend(B.BACKEND_SINGLE,
                           env={B.ENV_BACKEND: B.BACKEND_LOOPBACK})
        assert isinstance(bk, B.SingleBackend)

    def test_unknown_backend_rejected_loudly(self):
        with pytest.raises(ValueError, match="known"):
            B.get_backend("nccl")


# -- the mesh-axes→levels map ------------------------------------------------


class TestLevelMap:
    def test_default_map_is_dcn_only(self):
        assert B.get_backend().level_map(env={}) == {M.AXIS_DCN: B.LEVEL_DCN}

    def test_env_rides_extra_axes_over_dcn(self):
        lv = B.get_backend().level_map(env={B.ENV_DCN_AXES: "pipe, seq"})
        assert lv[M.AXIS_PIPELINE] == B.LEVEL_DCN
        assert lv[M.AXIS_SEQ] == B.LEVEL_DCN
        assert lv[M.AXIS_DCN] == B.LEVEL_DCN

    def test_dcn_axes_parsing(self):
        assert B.dcn_axes_from_env({}) == ()
        assert B.dcn_axes_from_env({B.ENV_DCN_AXES: ""}) == ()
        assert B.dcn_axes_from_env({B.ENV_DCN_AXES: " pipe ,expert"}) == \
            ("pipe", "expert")


class TestLevelMesh:
    def test_degenerate_map_is_byte_compatible(self, devices8):
        """The default map must reproduce mesh.build_mesh EXACTLY —
        same device ids in the same positions (the single-slice
        byte-compat guarantee)."""
        spec = M.MeshSpec(dcn=2, data=4)
        got = B.build_level_mesh(spec, devices8)
        want = M.build_mesh(spec, devices8)
        np.testing.assert_array_equal(
            np.vectorize(lambda d: d.id)(got.devices),
            np.vectorize(lambda d: d.id)(want.devices))

    def test_pipe_over_dcn_falls_on_slice_boundaries(self, devices8):
        """pipe mapped to LEVEL_DCN lays pipeline stages OUTERMOST: with
        contiguous-rank slices, stage 0 is slice {0..3} and stage 1 is
        slice {4..7} — the pipe-axis-over-dcn placement the pipeline
        runtime selects for cross-slice stages."""
        mesh = B.build_level_mesh(
            M.MeshSpec(data=2, pipe=2, model=2), devices8,
            levels={M.AXIS_PIPELINE: B.LEVEL_DCN})
        assert mesh.shape[M.AXIS_PIPELINE] == 2
        devs = mesh.devices  # (dcn, data, fsdp, pipe, expert, seq, model)
        stage0 = {d.id for d in devs[:, :, :, 0].flat}
        stage1 = {d.id for d in devs[:, :, :, 1].flat}
        assert stage0 == {0, 1, 2, 3} and stage1 == {4, 5, 6, 7}

    def test_dcn_stays_outermost_of_the_dcn_level(self, devices8):
        """With dcn AND pipe both at LEVEL_DCN, dcn is still the
        outermost: slice = dcn group, stages split inside it."""
        mesh = B.build_level_mesh(
            M.MeshSpec(dcn=2, data=2, pipe=2), devices8,
            levels={M.AXIS_PIPELINE: B.LEVEL_DCN})
        devs = mesh.devices
        dcn0 = {d.id for d in devs[0].flat}
        assert dcn0 == {0, 1, 2, 3}
        stage0_in_dcn0 = {d.id for d in devs[0, :, :, 0].flat}
        assert stage0_in_dcn0 == {0, 1}

    def test_backend_mesh_honors_dcn_axes_env(self, monkeypatch, devices8):
        """End to end through the backend: JAXJOB_MESH_DCN_AXES=pipe
        changes placement without touching any call site."""
        monkeypatch.setenv(B.ENV_DCN_AXES, "pipe")
        mesh = B.SingleBackend().mesh(
            M.MeshSpec(data=4, pipe=2), devices8)
        stage0 = {d.id for d in mesh.devices[:, :, :, 0].flat}
        assert stage0 == {0, 1, 2, 3}


# -- loopback formation ------------------------------------------------------


class TestLoopbackFormation:
    def test_slice_groups_partition(self, devices8):
        groups = B.LoopbackBackend.slice_groups(devices8, 2)
        assert [len(g) for g in groups] == [4, 4]
        assert [d.id for d in groups[0]] == [0, 1, 2, 3]
        with pytest.raises(ValueError, match="partition"):
            B.LoopbackBackend.slice_groups(devices8, 3)

    def test_tcp_barrier_forms_a_three_rank_world(self, monkeypatch):
        """Rank 0 binds the coordinator port and releases nobody until
        every peer checked in — real gang-formation semantics over plain
        sockets. All three joins return live state; leave() is
        idempotent."""
        monkeypatch.setenv(B.ENV_LOOPBACK_JOIN_TIMEOUT, "10")
        port = _free_port()
        backends = [B.LoopbackBackend() for _ in range(3)]
        cfgs = [D.DistConfig(coordinator_address=f"127.0.0.1:{port}",
                             num_processes=3, process_id=i)
                for i in range(3)]
        results: dict[int, bool] = {}
        errors: list[BaseException] = []

        def join(rank):
            try:
                results[rank] = backends[rank].join(cfgs[rank])
            except BaseException as e:  # surfaced below, not swallowed
                errors.append(e)

        threads = [threading.Thread(target=join, args=(r,), daemon=True)
                   for r in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        assert not errors, errors
        assert results == {0: True, 1: True, 2: True}
        for bk in backends:
            bk.leave()
            bk.leave()  # idempotent

    def test_barrier_blocks_until_timeout_without_peers(self, monkeypatch):
        """A missing peer blocks the gang — rank 0 must NOT release a
        partial world."""
        monkeypatch.setenv(B.ENV_LOOPBACK_JOIN_TIMEOUT, "0.6")
        cfg = D.DistConfig(
            coordinator_address=f"127.0.0.1:{_free_port()}",
            num_processes=2, process_id=0)
        bk = B.LoopbackBackend()
        with pytest.raises(TimeoutError, match="peers"):
            bk.join(cfg)

    def test_multislice_world_needs_no_sockets(self):
        """num_slices>1 in ONE process is the in-process slice world:
        join holds live state (teardown must run) but opens nothing."""
        bk = B.LoopbackBackend()
        cfg = D.DistConfig(coordinator_address=None, num_processes=1,
                           process_id=0, num_slices=2, slice_id=0)
        assert bk.join(cfg) is True
        bk.leave()

    def test_form_reshape_teardown_lifecycle(self, devices8):
        """The full protocol surface the elastic coordinator drives:
        form a 2-slice world (dcn=2 mesh on the slice partition),
        reshape to 1 slice through the same code path, tear down."""
        lb = B.get_backend(B.BACKEND_LOOPBACK)
        env = {B.ENV_BACKEND: B.BACKEND_LOOPBACK, D.ENV_NPROC: "1",
               D.ENV_NUM_SLICES: "2", D.ENV_SLICE_ID: "0"}
        mesh = lb.form(env)
        assert mesh.shape[M.AXIS_DCN] == 2
        assert D.active_world().num_slices == 2
        assert D.active_backend() is lb
        mesh1 = lb.reshape({B.ENV_BACKEND: B.BACKEND_LOOPBACK,
                            D.ENV_NPROC: "1"})
        assert mesh1.shape[M.AXIS_DCN] == 1
        assert D.active_world().num_slices == 1
        lb.teardown()
        assert D.active_world() is None

    def test_dist_routes_through_selected_backend(self):
        """dist.initialize_from_env hands world formation to the env's
        backend — the ONE seam COLL401 funnels every caller through."""
        env = {B.ENV_BACKEND: B.BACKEND_LOOPBACK, D.ENV_NPROC: "1",
               D.ENV_NUM_SLICES: "2", D.ENV_SLICE_ID: "1"}
        cfg = D.initialize_from_env(env)
        assert cfg.multislice and cfg.slice_id == 1
        assert isinstance(D.active_backend(), B.LoopbackBackend)


# -- reduction equivalence ---------------------------------------------------


def _reduce_under(bk, mesh, x):
    """Run bk.hierarchical_reduce over a (dcn, data)-sharded tree inside
    shard_map; the result is replicated (it is a global sum)."""
    def body(xl):
        return bk.hierarchical_reduce({"g": xl})["g"]

    # check_rep=False: the psum_scatter→psum→all_gather chain IS fully
    # replicated, but shard_map's static rep-checker can't prove it
    return shard_map(body, mesh=mesh,
                     in_specs=P((M.AXIS_DCN, M.AXIS_DATA)),
                     out_specs=P(), check_rep=False)(x)


class TestHierarchicalReduce:
    """reduce-scatter(ici) → all-reduce(dcn) → all-gather(ici) must be
    numerically the flat psum — integer-valued floats make both exact,
    so equality is bitwise, not allclose."""

    @pytest.fixture()
    def mesh2x4(self, devices8):
        bk = B.TpuIciDcnBackend()
        return bk, bk.mesh(M.MeshSpec(dcn=2, data=4), devices8)

    def test_scatter_path_matches_flat_sum(self, mesh2x4):
        bk, mesh = mesh2x4
        # local leading dim 4 tiles over the data extent 4 → the
        # reduce-scatter path runs (not the fallback)
        x = jnp.arange(32.0 * 3).reshape(32, 3)
        got = _reduce_under(bk, mesh, x)
        ref = np.asarray(x).reshape(8, 4, 3).sum(0)
        np.testing.assert_array_equal(np.asarray(got), ref)

    def test_untileable_shape_falls_back_flat(self, mesh2x4):
        bk, mesh = mesh2x4
        x = jnp.arange(16.0 * 3).reshape(16, 3)  # local dim 2, ici 4
        got = _reduce_under(bk, mesh, x)
        ref = np.asarray(x).reshape(8, 2, 3).sum(0)
        np.testing.assert_array_equal(np.asarray(got), ref)

    @pytest.mark.parametrize("maker", [B.SingleBackend, B.LoopbackBackend],
                             ids=["single", "loopback"])
    def test_every_backend_agrees_with_the_sum(self, maker, mesh2x4):
        _, mesh = mesh2x4
        bk = maker()
        bk._mesh = mesh
        x = jnp.arange(32.0 * 3).reshape(32, 3)
        got = _reduce_under(bk, mesh, x)
        ref = np.asarray(x).reshape(8, 4, 3).sum(0)
        np.testing.assert_array_equal(np.asarray(got), ref)


class TestBackendEquivalence:
    """The property the hermetic e2e leans on: training under
    LoopbackBackend is the SAME computation as under SingleBackend."""

    @staticmethod
    def _train(bk, mesh, seed, steps=6):
        rng = np.random.RandomState(seed)
        X = jnp.asarray(rng.randn(16, 4).astype(np.float32))
        Y = jnp.asarray(rng.randn(16).astype(np.float32))

        def local_loss(w, xl, yl):
            return 0.5 * jnp.sum((xl @ w - yl) ** 2)

        grad = shard_map(
            lambda w, xl, yl: bk.hierarchical_reduce(
                jax.grad(local_loss)(w, xl, yl)),
            mesh=mesh,
            in_specs=(P(), P((M.AXIS_DCN, M.AXIS_DATA)),
                      P((M.AXIS_DCN, M.AXIS_DATA))),
            out_specs=P(), check_rep=False)

        @jax.jit
        def step(w, X, Y):
            return w - 0.05 * grad(w, X, Y) / X.shape[0]

        w = jnp.zeros((4,))
        for _ in range(steps):
            w = step(w, X, Y)
        return np.asarray(w)

    @pytest.mark.parametrize("seed", [0, 1])
    def test_single_vs_loopback_one_slice_identical_params(
            self, seed, devices8):
        """One-slice loopback defaults to the SAME mesh as single — the
        trained params must be bit-identical, not just close."""
        single = B.SingleBackend()
        loop = B.LoopbackBackend()
        w_s = self._train(single, single.mesh(devices=devices8), seed)
        w_l = self._train(loop, loop.mesh(devices=devices8), seed)
        assert np.array_equal(w_s, w_l), (w_s, w_l)

    def test_two_slice_loopback_matches_single_math(self, devices8):
        """A formed 2-slice in-process world (dcn=2 on the partition
        boundary) trains to the single-backend answer — the cross-slice
        reduce is a real dcn-axis collective, same math."""
        env = {B.ENV_BACKEND: B.BACKEND_LOOPBACK, D.ENV_NPROC: "1",
               D.ENV_NUM_SLICES: "2", D.ENV_SLICE_ID: "0"}
        D.initialize_from_env(env)
        loop = B.get_backend(B.BACKEND_LOOPBACK)
        mesh2 = loop.mesh(devices=devices8)
        assert mesh2.shape[M.AXIS_DCN] == 2
        w_2slice = self._train(loop, mesh2, seed=3)
        single = B.SingleBackend()
        w_ref = self._train(single, single.mesh(devices=devices8), seed=3)
        np.testing.assert_allclose(w_2slice, w_ref, rtol=1e-6)
