"""ChaosClient — seeded, policy-driven fault injection for any Client.

The reference platform's failure story is per-replica ``restartPolicy``
plus real-GKE E2E (SURVEY.md §4-5); nothing in its test tiers can
*inject* an apiserver error, a dropped watch, or a node dying under a
running gang. This module is the missing chaos engine: it wraps any
Client (FakeCluster or RestClient — the two share one verb surface) and
injects deterministic faults per verb/kind at a configured rate:

- ``Conflict`` storms on mutating verbs (the optimistic-concurrency
  loser path every controller must treat as benign);
- transient 429/500/503 ``ApiError`` (with a ``retry_after`` attribute,
  the Retry-After header analogue RestClient's backoff honors);
- injected latency (slow-apiserver simulation);
- mid-stream watch termination with resubscribe — exercising the
  resume-from-resourceVersion path and, when the resume point has
  fallen out of the watch cache, the 410-Expired relist path;
- cluster-level primitives to mark nodes NotReady, heal them, and kill
  bound pods mid-run (the preemptible-TPU steady state).

Everything is driven by one ``random.Random(seed)``, so a failure
sequence replays exactly: same seed + same call order = same faults.
``TPU_CHAOS_SEED`` / ``TPU_CHAOS_RATE`` configure the default policy
(the knob convention TPU_RACE_* established for the race tier). With
rate 0 the wrapper is a strict pass-through and every existing suite
runs unchanged through it.

Events are NEVER fault-injected here: Kubernetes event recording is
fire-and-forget (client-go's recorder drops on overflow rather than
failing the reconcile), so event loss is modeled by watch drops and the
EventRecorder's own best-effort contract, not by raising into a
controller that must not care.

Arming: by default every eligible call can fault (``always_on=True``).
Test harnesses that share one client between the controller under test
and the assertions pass ``always_on=False`` and arm chaos only around
reconciles (``arm_controller``) — faults then hit exactly the code that
must survive them, never the test's own setup/assert calls.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import logging
import os
import random
import threading
import time
from collections import deque

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import WatchEvent

log = logging.getLogger("kubeflow_tpu.chaos")

ENV_SEED = "TPU_CHAOS_SEED"
ENV_RATE = "TPU_CHAOS_RATE"

# Verbs a conflict can be injected on (409 only makes sense for writes).
MUTATING_VERBS = frozenset(
    {"create", "update", "update_status", "patch", "apply", "delete"})
READ_VERBS = frozenset({"get", "list"})
DATA_VERBS = MUTATING_VERBS | READ_VERBS

# Ambient "faults may fire now" flag. A contextvar, not a client field:
# each thread (controller worker, watch thread, test main) gets its own
# context, so arming a reconcile in one worker never opens the window
# for the test thread's assertion calls.
_ARMED: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "kftpu_chaos_armed", default=False)


@dataclasses.dataclass(frozen=True)
class ChaosPolicy:
    """What to inject, where, how often. Frozen: a policy is config."""

    seed: int = 0
    rate: float = 0.0            # per-eligible-call fault probability
    conflict_weight: float = 1.0  # vs error_weight, mutating verbs only
    error_weight: float = 1.0
    error_codes: tuple = (429, 500, 503)
    retry_after: float = 0.05    # attached to 429/503 (Retry-After)
    latency: float = 0.0         # >0: some faults are delays, not errors
    latency_weight: float = 1.0
    verbs: frozenset | None = None   # None = all DATA_VERBS
    kinds: frozenset | None = None   # None = every kind
    watch_drop_every: int = 0    # ~every N delivered events; 0 = never

    @classmethod
    def from_env(cls, environ=None, **overrides) -> "ChaosPolicy":
        """Policy from TPU_CHAOS_SEED / TPU_CHAOS_RATE (overridable)."""
        env = os.environ if environ is None else environ
        fields = {
            "seed": int(env.get(ENV_SEED, "0")),
            "rate": float(env.get(ENV_RATE, "0")),
        }
        fields.update(overrides)
        return cls(**fields)


@dataclasses.dataclass(frozen=True)
class Fault:
    """One injected fault — the replayable record of a chaos decision."""

    call: int       # 1-based index among eligible calls
    verb: str
    kind: str
    fault: str      # "conflict" | "error:<code>" | "latency"


class ChaosClient:
    """Wrap ``inner`` (any Client) with seeded fault injection.

    Unknown attributes delegate to the inner client, so backend-specific
    surface (``dump``, ``add_admission_hook``, ``list_page``, ...) keeps
    working through the wrapper.
    """

    def __init__(self, inner, policy: ChaosPolicy | None = None,
                 always_on: bool = True, sleeper=None):
        self.inner = inner
        self.policy = policy if policy is not None else ChaosPolicy.from_env()
        self.always_on = always_on
        self._sleeper = sleeper if sleeper is not None else time.sleep
        self._lock = threading.Lock()
        self._rng = random.Random(self.policy.seed)
        self._calls = 0
        self._faults: list[Fault] = []

    # -- arming --------------------------------------------------------------

    @contextlib.contextmanager
    def armed(self):
        """Faults may fire inside this context (for always_on=False)."""
        token = _ARMED.set(True)
        try:
            yield self
        finally:
            _ARMED.reset(token)

    def _active(self) -> bool:
        return self.always_on or _ARMED.get()

    # -- the dice ------------------------------------------------------------

    def fault_log(self) -> list[Fault]:
        """Injected faults so far — equal across same-seed replays."""
        with self._lock:
            return list(self._faults)

    def _randint(self, a: int, b: int) -> int:
        with self._lock:
            return self._rng.randint(a, b)

    def _maybe_fault(self, verb: str, kind: str) -> None:
        p = self.policy
        if p.rate <= 0 or not self._active():
            return
        if p.verbs is not None and verb not in p.verbs:
            return
        if p.kinds is not None and kind not in p.kinds:
            return
        with self._lock:
            # every *eligible* call consumes exactly one uniform draw, so
            # the fault sequence is a pure function of (seed, call order)
            self._calls += 1
            n = self._calls
            if self._rng.random() >= p.rate:
                return
            menu: list[tuple[str, float]] = [("error", p.error_weight)]
            if verb in MUTATING_VERBS:
                menu.append(("conflict", p.conflict_weight))
            if p.latency > 0:
                menu.append(("latency", p.latency_weight))
            menu = [(name, w) for name, w in menu if w > 0]
            if not menu:  # e.g. conflict-only policy on a read verb
                return
            total = sum(w for _, w in menu)
            pick = self._rng.random() * total
            fault = menu[-1][0]
            for name, w in menu:
                if pick < w:
                    fault = name
                    break
                pick -= w
            if fault == "error":
                code = p.error_codes[self._rng.randrange(len(p.error_codes))]
                fault = f"error:{code}"
            self._faults.append(Fault(n, verb, kind, fault))
        self._raise_or_delay(fault, verb, kind)

    def _raise_or_delay(self, fault: str, verb: str, kind: str) -> None:
        if fault == "latency":
            self._sleeper(self.policy.latency)
            return
        if fault == "conflict":
            raise ob.Conflict(f"chaos: injected conflict on {verb} {kind}")
        code = int(fault.split(":", 1)[1])
        err = ob.ApiError(
            f"chaos: injected HTTP {code} on {verb} {kind}")
        err.code = code
        if code in (429, 503):
            err.retry_after = self.policy.retry_after
        raise err

    # -- Client verbs (faulted) ---------------------------------------------

    def create(self, obj: dict) -> dict:
        self._maybe_fault("create", obj.get("kind", ""))
        return self.inner.create(obj)

    def get(self, api_version, kind, name, namespace=None) -> dict:
        self._maybe_fault("get", kind)
        return self.inner.get(api_version, kind, name, namespace)

    def get_or_none(self, api_version, kind, name, namespace=None):
        self._maybe_fault("get", kind)
        return self.inner.get_or_none(api_version, kind, name, namespace)

    def list(self, api_version, kind, namespace=None,
             label_selector=None, field_selector=None) -> list[dict]:
        self._maybe_fault("list", kind)
        return self.inner.list(api_version, kind, namespace,
                               label_selector, field_selector)

    def update(self, obj: dict) -> dict:
        self._maybe_fault("update", obj.get("kind", ""))
        return self.inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        self._maybe_fault("update_status", obj.get("kind", ""))
        return self.inner.update_status(obj)

    def patch(self, api_version, kind, name, patch, namespace=None) -> dict:
        self._maybe_fault("patch", kind)
        return self.inner.patch(api_version, kind, name, patch, namespace)

    def apply(self, obj: dict, *, field_manager: str, force: bool = False):
        self._maybe_fault("apply", obj.get("kind", ""))
        return self.inner.apply(obj, field_manager=field_manager, force=force)

    def delete(self, api_version, kind, name, namespace=None) -> None:
        self._maybe_fault("delete", kind)
        return self.inner.delete(api_version, kind, name, namespace)

    def record_event(self, involved, reason, message, etype="Normal",
                     component="kubeflow-tpu") -> dict:
        # fire-and-forget channel: never faulted (see module docstring)
        return self.inner.record_event(involved, reason, message, etype,
                                       component=component)

    def watch(self, api_version, kind, namespace=None, **kw):
        stream = self.inner.watch(api_version, kind, namespace, **kw)
        if self.policy.watch_drop_every <= 0:
            return stream
        return ChaosWatchStream(self, (api_version, kind, namespace), stream)

    def __getattr__(self, name):
        # backend-specific surface passes through unfaulted
        return getattr(self.inner, name)

    # -- cluster-level chaos primitives (always direct, never faulted) ------

    def fail_node(self, name: str) -> None:
        """Mark a Node NotReady — the TPU-maintenance / host-death drill.
        The scheduler's health pass and the JAXJob slice-health check
        both key off this condition."""
        self._set_node_ready(name, False)

    def heal_node(self, name: str) -> None:
        self._set_node_ready(name, True)

    def _set_node_ready(self, name: str, ready: bool) -> None:
        node = self.inner.get("v1", "Node", name)
        status = node.setdefault("status", {})
        conds = [c for c in status.get("conditions") or []
                 if c.get("type") != "Ready"]
        conds.append({"type": "Ready",
                      "status": "True" if ready else "False"})
        status["conditions"] = conds
        self.inner.update_status(node)  # tpulint: disable=CTL502  chaos drill, not a reconcile: fail/heal_node mutate on purpose every invocation
        log.info("chaos: node %s -> Ready=%s", name, ready)

    def delete_node(self, name: str) -> None:
        self.inner.delete("v1", "Node", name)
        log.info("chaos: node %s deleted", name)

    def evict_pod(self, name: str, namespace: str = "default",
                  message: str = "chaos: node-pressure eviction") -> None:
        """Kubelet-eviction shape (phase Failed, reason Evicted, no
        containerStatuses) — classified as preemption, not crash, by
        JAXJobReconciler._pod_preempted."""
        from kubeflow_tpu.control.scheduler.nodes import eviction_status

        pod = self.inner.get_or_none("v1", "Pod", name, namespace)
        if pod is None:
            return
        pod.setdefault("status", {})
        pod["status"].update(eviction_status(message))
        self.inner.update_status(pod)
        log.info("chaos: evicted pod %s/%s", namespace, name)

    def kill_pod(self, name: str, namespace: str = "default") -> None:
        """Hard kill: the pod object vanishes (a node dying takes its
        pods' apiserver records with it once the GC runs)."""
        try:
            self.inner.delete("v1", "Pod", name, namespace)
        except ob.NotFound:
            pass
        log.info("chaos: killed pod %s/%s", namespace, name)


class ChaosWatchStream:
    """Wrap a watch stream; every ~``watch_drop_every`` delivered events
    the underlying stream is torn down mid-flight and resubscribed —
    resume-from-resourceVersion when the backend retained the history,
    else (410 Expired, or a backend without resume) a full relist that
    re-yields every live object as MODIFIED and synthesizes DELETED for
    objects this stream had seen that vanished during the gap (the
    informer relist contract ``_RestWatchStream`` implements for real
    apiservers, exercised here hermetically)."""

    def __init__(self, client: ChaosClient, args: tuple, stream):
        self._client = client
        self._args = args
        self._stream = stream
        self._closed = False
        self._served = 0
        self._budget = self._draw_budget()
        self._drops = 0
        self._last_rv = ""
        self._known: dict[tuple[str, str], dict] = {}
        self._replay: deque[WatchEvent] = deque()
        if hasattr(stream, "poll"):
            # only expose poll when the wrapped stream has it (the
            # hermetic FakeWatchStream); runtime._drain_streams keys off
            # hasattr to tell test-mode streams from production ones
            self.poll = self._poll

    @property
    def drops(self) -> int:
        return self._drops

    def _draw_budget(self) -> int:
        n = self._client.policy.watch_drop_every
        return self._client._randint(max(1, n // 2), max(1, 2 * n))

    @staticmethod
    def _key(obj: dict) -> tuple[str, str]:
        m = ob.meta(obj)
        return (m.get("namespace") or "", m.get("name") or "")

    def _note(self, ev: WatchEvent) -> None:
        self._last_rv = ob.meta(ev.object).get(
            "resourceVersion", self._last_rv)
        if ev.type == "DELETED":
            self._known.pop(self._key(ev.object), None)
        else:
            self._known[self._key(ev.object)] = ev.object

    def _drop_and_resubscribe(self) -> None:
        self._drops += 1
        self._served = 0
        self._budget = self._draw_budget()
        try:
            self._stream.stop()
        except Exception:
            pass
        api_version, kind, namespace = self._args
        inner = self._client.inner
        stream = None
        if self._last_rv:
            try:
                stream = inner.watch(api_version, kind, namespace,
                                     since_rv=self._last_rv)
                log.info("chaos: watch %s dropped, resumed from rv=%s",
                         kind, self._last_rv)
            except ob.Expired:
                log.info("chaos: watch %s dropped, resume rv=%s expired "
                         "(410) -> relist", kind, self._last_rv)
            except TypeError:
                pass  # backend without watch-cache resume: relist below
        if stream is None:
            # subscribe FIRST, then relist: changes landing between the
            # two are replayed by the fresh stream, never lost in a gap
            stream = inner.watch(api_version, kind, namespace)
            live: dict[tuple[str, str], dict] = {}
            for obj in inner.list(api_version, kind, namespace):
                live[self._key(obj)] = obj
                self._last_rv = ob.meta(obj).get(
                    "resourceVersion", self._last_rv)
                self._replay.append(WatchEvent("MODIFIED", obj))
            for key, last_state in self._known.items():
                if key not in live:
                    self._replay.append(WatchEvent("DELETED", last_state))
            self._known = live
        self._stream = stream

    def _poll(self, timeout: float = 0.0):
        if self._replay:
            return self._replay.popleft()
        if self._served >= self._budget:
            self._drop_and_resubscribe()
            if self._replay:
                return self._replay.popleft()
        ev = self._stream.poll(timeout)
        if ev is None:
            return None
        self._served += 1
        self._note(ev)
        return ev

    def __iter__(self):
        while not self._closed:
            while self._replay:
                yield self._replay.popleft()
            if self._served >= self._budget:
                self._drop_and_resubscribe()
                continue
            delivered = False
            for ev in self._stream:
                if self._closed:
                    return
                self._served += 1
                self._note(ev)
                delivered = True
                yield ev
                if self._served >= self._budget or self._replay:
                    break
            if not delivered and not self._replay \
                    and self._served < self._budget:
                return  # inner stream ended for good (closed)

    def stop(self) -> None:
        self._closed = True
        self._stream.stop()


class ArmedReconciler:
    """Duck-typed Reconciler wrapper: faults fire only while the wrapped
    reconcile runs (pair with ``ChaosClient(always_on=False)``)."""

    def __init__(self, inner, chaos: ChaosClient):
        self.inner = inner
        self.chaos = chaos

    def reconcile(self, client, req):
        with self.chaos.armed():
            return self.inner.reconcile(client, req)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def arm_controller(ctl, chaos: ChaosClient):
    """Route a Controller's reconciles through ``chaos.armed()`` so only
    the code under test sees faults, never the harness around it."""
    ctl.reconciler = ArmedReconciler(ctl.reconciler, chaos)
    return ctl
