"""static-config-server: serves a platform config document.

Mirrors components/static-config-server (Go): a single config payload
(platform endpoints, links, build info) served at /config for the
dashboard and CLIs to consume. Config comes from a JSON/YAML file or an
inline dict; reloaded on mtime change so a ConfigMap update propagates
without a restart.
"""

from __future__ import annotations

import json
import os

from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import HttpReq, Router


class StaticConfigServer:
    def __init__(self, config: dict | None = None, path: str | None = None):
        if (config is None) == (path is None):
            raise ValueError("exactly one of config / path required")
        self._config = config
        self._path = path
        self._mtime = 0.0
        if path:
            self._load()

    def _load(self) -> None:
        with open(self._path) as f:
            text = f.read()
        try:
            self._config = json.loads(text)
        except json.JSONDecodeError:
            from kubeflow_tpu.utils import yaml_lite

            self._config = yaml_lite.loads(text)
        self._mtime = os.path.getmtime(self._path)

    def get_config(self, req: HttpReq):
        if self._path and os.path.getmtime(self._path) != self._mtime:
            self._load()
        return self._config

    def router(self) -> Router:
        r = Router("static-config")
        r.route("GET", "/config", self.get_config)
        r.route("GET", "/", self.get_config)
        httpd.add_health_routes(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 8080) -> httpd.HttpService:
        return httpd.HttpService(self.router(), host, port)
