"""Worker payload for the multi-process gang e2e test.

What a real JAXJob training container does (the launcher contract,
reference tf-cnn/launcher.py:59-93): join the distributed world from
JAXJOB_* env, build a mesh, train with checkpointing, exit 0. Run by
LocalPodExecutor as an actual subprocess.

Under JAXJOB_COLLECTIVES_BACKEND=loopback (the tier-1 mode) the gang
forms over the LoopbackBackend's TCP join barrier — real membership,
coordinator, and teardown semantics, hermetic on CPU — and each rank
then trains an identical replica on its own local devices with a
per-rank checkpoint dir (this image's multi-process jax.distributed CPU
worlds crash in flax init, so the real-backend path is the @slow
variant). Without the env the worker keeps the real jax.distributed
contract: one process-spanning mesh, shared checkpoints.

Env knobs (set by the test through the pod spec / env_hook):
  GANG_CKPT_DIR     orbax checkpoint root (per-rank subdir on loopback)
  GANG_TOTAL_STEPS  global step target
  GANG_STEP_DELAY_S per-step sleep so the test can kill a worker mid-run
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# sitecustomize may have pre-registered a TPU backend; force cpu the same
# way tests/conftest.py does.
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.parallel import backends as B  # noqa: E402
from kubeflow_tpu.parallel import dist as D  # noqa: E402
from kubeflow_tpu.parallel.dist import initialize_from_env  # noqa: E402


def main() -> int:
    dist = initialize_from_env()
    loopback = isinstance(D.active_backend(), B.LoopbackBackend)
    if loopback:
        # the TCP barrier released us: the whole gang is live — the
        # membership proof the device-count assertion gives on the
        # real backend
        world = D.active_world()
        assert world is not None \
            and world.num_processes == dist.num_processes, world
        mesh_extent = jax.local_device_count()
        ckpt_dir = os.path.join(os.environ["GANG_CKPT_DIR"],
                                f"r{dist.process_id}")
    else:
        assert jax.device_count() == dist.num_processes, \
            (jax.device_count(), dist.num_processes)
        mesh_extent = dist.num_processes
        ckpt_dir = os.environ["GANG_CKPT_DIR"]

    import time

    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    delay = float(os.environ.get("GANG_STEP_DELAY_S", "0"))
    # resnet classification, not the LM: this image's flax crashes in
    # transformer init (the known test_bench_lm_pipeline failure
    # family), and the contract under test is the gang, not the model
    cfg = TrainConfig.from_dict(dict(
        model="resnet18",
        model_kwargs={"num_filters": 8},
        task="classification",
        global_batch=2 * dist.num_processes,
        image_size=16,
        num_classes=10,
        mesh=MeshSpec(data=mesh_extent),
        optimizer="adamw",
        learning_rate=1e-3,
        total_steps=int(os.environ["GANG_TOTAL_STEPS"]),
        warmup_steps=1,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=1,
        log_every=10**9,
    ))
    trainer = Trainer(cfg)
    cb = (lambda i, m: time.sleep(delay)) if delay else None
    # Same SIGTERM contract as the launcher: checkpoint + EX_TEMPFAIL.
    # The trainer turns the per-worker notice into a gang-agreed stop
    # (all ranks break at the same step) when num_processes > 1.
    from kubeflow_tpu.runtime.preemption import EX_TEMPFAIL, PreemptionNotice

    notice = PreemptionNotice().install()
    state, summary = trainer.fit(callback=cb, stop=notice)
    line = json.dumps({"rank": dist.process_id,
                       "start_step": summary["start_step"],
                       "final_step": int(state.step),
                       "preempted": bool(summary.get("preempted", False)),
                       "loss": summary["final"].get("loss")})
    print(line, flush=True)
    # Also append to a shared log so the test can assert per-run
    # start_steps (stdout is swallowed by the executor on success).
    log_path = os.environ.get("GANG_LOG")
    if log_path:
        with open(log_path, "a") as f:
            f.write(line + "\n")
    return EX_TEMPFAIL if summary.get("preempted") else 0


if __name__ == "__main__":
    sys.exit(main())
