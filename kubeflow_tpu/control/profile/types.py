"""Profile CRD types (reference: profile-controller/api/v1/profile_types.go:38)."""

from __future__ import annotations

from kubeflow_tpu.control.k8s import objects as ob

GROUP = "kubeflow.org"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "Profile"

FINALIZER = "profile-finalizer"  # profile_controller.go:48
# ClusterRoles bound in the namespace (profile_controller.go:58-62)
ADMIN_CLUSTER_ROLE = "kubeflow-admin"
EDIT_CLUSTER_ROLE = "kubeflow-edit"
VIEW_CLUSTER_ROLE = "kubeflow-view"
SA_EDITOR = "default-editor"
SA_VIEWER = "default-viewer"
QUOTA_NAME = "kf-resource-quota"  # profile_controller.go:47
RESOURCE_TPU = "google.com/tpu"
# annotation consumed by KFAM bindings (kfam/bindings.go)
ANNO_USER = "user"
ANNO_ROLE = "role"


def owner_name(profile: dict) -> str | None:
    """The owning user of a Profile. Canonical spec.owner is a Subject
    dict ({kind, name}, profile_types.go:38); a bare string is accepted
    for convenience."""
    owner = (profile.get("spec") or {}).get("owner")
    if isinstance(owner, dict):
        return owner.get("name")
    return owner


def new_profile(
    name: str,
    owner: str,
    *,
    tpu_chip_quota: int | None = None,
    cpu_quota: str | None = None,
    memory_quota: str | None = None,
    plugins: list[dict] | None = None,
) -> dict:
    spec: dict = {"owner": {"kind": "User", "name": owner}}
    hard: dict = {}
    if tpu_chip_quota is not None:
        hard[f"requests.{RESOURCE_TPU}"] = tpu_chip_quota
    if cpu_quota:
        hard["requests.cpu"] = cpu_quota
    if memory_quota:
        hard["requests.memory"] = memory_quota
    if hard:
        spec["resourceQuotaSpec"] = {"hard": hard}
    if plugins:
        spec["plugins"] = plugins
    prof = ob.new_object(API_VERSION, KIND, name, namespace=None, spec=spec)
    ob.meta(prof)["finalizers"] = [FINALIZER]
    return prof


def crd_manifest() -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"profiles.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {"kind": KIND, "listKind": "ProfileList",
                      "plural": "profiles", "singular": "profile"},
            "scope": "Cluster",
            "versions": [{
                "name": VERSION, "served": True, "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True}},
            }],
        },
    }
