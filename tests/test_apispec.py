"""tpctl OpenAPI spec (reference contract: bootstrap/api/swagger.yaml)."""

import json

from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.tpctl.apispec import BASE, openapi
from kubeflow_tpu.tpctl.server import TpctlServer
from kubeflow_tpu.utils.httpd import HttpReq


def _get(server, path):
    return server.router().dispatch(
        HttpReq(method="GET", path=path, params={}, query={}, headers={},
                body=b""))


class TestOpenApiSpec:
    def test_document_shape(self):
        doc = openapi()
        assert doc["openapi"].startswith("3.0")
        assert doc["info"]["title"]
        assert "TpuDef" in doc["components"]["schemas"]
        # JSON-serializable end to end
        json.dumps(doc)

    def test_every_server_route_is_documented(self):
        """The spec is generated, but routes are registered by hand — this
        pins them together."""
        doc = openapi()
        server = TpctlServer(FakeCluster())
        router = server.router()
        documented = {
            (m.upper(), p)
            for p, ops in doc["paths"].items()
            for m in ops
            if m in ("get", "post", "put", "delete", "patch")
        }
        for method, rx, _fn in router._routes:
            # reconstruct the literal path from the compiled pattern
            for doc_method, path in documented:
                if doc_method == method and (
                        rx.fullmatch(path.lstrip("/")) or rx.fullmatch(path)):
                    break
            else:
                raise AssertionError(
                    f"route {method} {rx.pattern} not in the OpenAPI spec")

    def test_served_by_the_server(self):
        server = TpctlServer(FakeCluster())
        resp = _get(server, f"{BASE}/openapi.json")
        assert resp.status == 200
        doc = json.loads(resp.body)
        assert f"{BASE}/create" in doc["paths"]

    def test_invalid_create_returns_documented_400(self):
        """The spec advertises 400 for bad input; the server must match
        (not leak a 500 from TpuDef validation)."""
        server = TpctlServer(FakeCluster())
        req = HttpReq(method="POST", path=f"{BASE}/create", params={},
                      query={}, headers={},
                      body=json.dumps({"spec": {"applications": ["nope"]}}).encode())
        assert server.router().dispatch(req).status == 400
        bad_json = HttpReq(method="POST", path=f"{BASE}/create", params={},
                           query={}, headers={}, body=b"{not json")
        assert server.router().dispatch(bad_json).status == 400
        non_object = HttpReq(method="POST", path=f"{BASE}/create", params={},
                             query={}, headers={}, body=b'"hello"')
        assert server.router().dispatch(non_object).status == 400

    def test_tpudef_schema_platforms_in_sync(self):
        """Valid platform enum mirrors apply.PROVIDERS."""
        from kubeflow_tpu.tpctl.apply import PROVIDERS

        doc = openapi()
        enum = doc["components"]["schemas"]["TpuDef"]["properties"]["spec"][
            "properties"]["platform"]["properties"]["kind"]["enum"]
        assert sorted(enum) == sorted(PROVIDERS)
