"""Shared utilities (config loading, logging, small helpers)."""
