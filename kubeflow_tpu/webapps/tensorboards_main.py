"""Entry: python -m kubeflow_tpu.webapps.tensorboards_main."""
import argparse

import os

from kubeflow_tpu.control.k8s.rest import RestClient
from kubeflow_tpu.webapps.crud_backend import Authorizer
from kubeflow_tpu.webapps.tensorboards import TensorboardsApp

p = argparse.ArgumentParser("tensorboards")
p.add_argument("--port", type=int, default=5005)
p.add_argument("--apiserver", default="")
args = p.parse_args()
client = RestClient(base_url=args.apiserver or None)
# authz always on in the deployed service: profile owner/contributor
# roles gate every verb (tests construct the app the same way)
authz = Authorizer(client, cluster_admin=os.environ.get("CLUSTER_ADMIN") or None)
svc = TensorboardsApp(client, authz).serve(port=args.port)
print(f"tensorboards on :{svc.port}")
svc.serve_forever()
