"""tpulint lockset/concurrency rules (LOCK201/202) for the control plane.

LOCK201 is an Eraser-style lockset checker specialized to the idiom
this tree actually uses (SURVEY.md §5: hand-rolled mutexes): each class
declares ``self._lock = threading.Lock()`` and guards state with
``with self._lock:`` blocks. The rule learns, per class, which
attributes are mutated under which lock, then flags mutations of those
same attributes outside any lock.

Since PR 2 the rule runs on the whole-program call graph
(analysis/callgraph.py) instead of one class at a time:

- private helpers whose every call site holds the lock — in the same
  class (``LeaderElector._became`` under ``try_acquire``), in another
  class, or in another *module* — are recognized via the program-wide
  locked-entry fixpoint, so a lock taken in ``control/runtime.py``
  still vouches for a helper reached through ``control/leases.py``;
- writes through parameters of a known class (``def seed(c:
  Controller): c._queue[k] = v``, or ``self`` passed along) are
  attributed to that class and checked against its guarded map.

Mutator calls (``.append``/``.update``/...) count as writes only for
attributes with container evidence, so ``self.client.update(obj)`` (a
k8s API call) never registers as a mutation of ``self.client``.

LOCK202 keeps reconcile bodies non-blocking: a sleeping reconcile stalls
the shared workqueue worker for every object behind it — the correct
spelling is ``Result(requeue_after=...)``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from kubeflow_tpu.analysis.callgraph import Program
from kubeflow_tpu.analysis.core import (
    Finding, Module, ProgramRule, Rule, call_name, register,
)


@register
class UnguardedAttribute(ProgramRule):
    """LOCK201: attribute mutated under a lock in one place and without
    it in another — the torn-state/lost-update class the race tier
    (tests/test_race.py) probes dynamically, caught statically."""

    id = "LOCK201"
    name = "unguarded-attribute"
    short = "lock-guarded attribute mutated without the lock"

    def check_program(self, program: Program) -> Iterator[Finding]:
        guarded = program.guarded_map()
        for w in program.writes():
            per = guarded.get(w.class_qual)
            if per is None or w.attr not in per:
                continue
            if w.tokens or w.func.name == "__init__":
                continue
            cls_name = w.class_qual.split(":")[-1]
            where = (f"{cls_name}.{w.func.name}" if w.func.owner is not None
                     else w.func.name)
            locked_path, locked_line, _ = per[w.attr]
            at = (f"line {locked_line}" if locked_path == w.module.path
                  else f"{locked_path}:{locked_line}")
            yield self.finding(
                w.module, w.node,
                f"'{w.recv}.{w.attr}' is mutated under a lock at {at} "
                f"but mutated here (in '{where}') without it")


@register
class BlockingInReconcile(Rule):
    """LOCK202: blocking call inside a reconcile body. Reconciles share
    workqueue workers; one sleep or raw network wait head-of-line
    blocks every queued object. Requeue with Result(requeue_after=...)
    or inject a waiter."""

    id = "LOCK202"
    name = "blocking-in-reconcile"
    short = "blocking call (sleep / raw I/O) inside a reconcile body"

    _EXACT = {"time.sleep", "urllib.request.urlopen", "urlopen"}
    _PREFIX = ("socket.", "requests.", "subprocess.")

    def check(self, module: Module) -> Iterator[Finding]:
        for fn in ast.walk(module.tree):
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name.startswith("reconcile")):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name and (name in self._EXACT
                             or name.startswith(self._PREFIX)):
                    yield self.finding(
                        module, node,
                        f"{name}() blocks inside '{fn.name}'; reconciles "
                        "share workqueue workers — return "
                        "Result(requeue_after=...) instead of waiting "
                        "in-line")
