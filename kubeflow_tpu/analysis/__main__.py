"""tpulint CLI: ``python -m kubeflow_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage error. ``--hygiene`` adds the
stdlib hygiene gates (parse/debugger/conflict-marker, yaml manifests)
on top of the tpulint rules, so tools/lint_all.sh is one process.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from kubeflow_tpu.analysis import core, hygiene, report


def _parse_rules(text: str | None) -> set[str] | None:
    if not text:
        return None
    return {r.strip() for r in text.split(",") if r.strip()}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description="JAX/TPU-aware static analysis (tpulint)")
    parser.add_argument("paths", nargs="*", default=["kubeflow_tpu"],
                        help="files or directories to scan "
                             "(default: kubeflow_tpu)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--select", metavar="RULES",
                        help="comma-separated rule ids to run exclusively")
    parser.add_argument("--ignore", metavar="RULES",
                        help="comma-separated rule ids to skip")
    parser.add_argument("--hygiene", action="store_true",
                        help="also run the stdlib hygiene gates "
                             "(parse/debugger/conflict markers, yaml)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rules and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in core.all_rules():
            print(f"{rule.id}  {rule.name}: {rule.short}")
        for rid, short in sorted(hygiene.HYGIENE_RULES.items()):
            print(f"{rid}  hygiene: {short}")
        return 0

    for raw in args.paths:
        if not pathlib.Path(raw).exists():
            # a typo'd path must not exit 0 "clean" while scanning nothing
            print(f"no such path: {raw}", file=sys.stderr)
            return 2

    select, ignore = _parse_rules(args.select), _parse_rules(args.ignore)
    known = {r.id for r in core.all_rules()} | {core.PARSE_RULE}
    known |= set(hygiene.HYGIENE_RULES)
    for wanted in (select or set()) | (ignore or set()):
        if wanted not in known:
            print(f"unknown rule id: {wanted}", file=sys.stderr)
            return 2
    if select and select & set(hygiene.HYGIENE_RULES):
        # selecting a HYG id implies the hygiene pass — otherwise the
        # selection would silently scan nothing and exit 0
        args.hygiene = True

    findings = core.scan_paths(args.paths, select=select, ignore=ignore)
    if args.hygiene:
        hyg = hygiene.run_hygiene(args.paths)
        if select:
            hyg = [f for f in hyg if f.rule in select]
        if ignore:
            hyg = [f for f in hyg if f.rule not in ignore]
        findings = sorted(findings + hyg,
                          key=lambda f: (f.path, f.line, f.col, f.rule))

    print(report.render_json(findings) if args.json
          else report.render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
