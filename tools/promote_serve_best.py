"""Promote the serving sweep's best measured operating point.

Same promotion discipline as promote_best.py, for the decode side
(VERDICT r3 #4: serving numbers as a first-class ledger): parse files of
serve_bench.py JSON lines, keep the best CONTINUOUS-mode point per
(model, max_new_tokens, slots, param_dtype, kv_cache_dtype) config in
tools/serve_table.json (the A/B ledger), and write the best
DEFAULT-GEOMETRY (gpt-350m) point to tools/serve_best.json — bench.py
attaches it (and, budget permitting, re-measures) so the driver-recorded
BENCH json carries a serving field. Only measured numbers are promoted;
a failed sweep changes nothing; non-default geometries never compete for
(or raise the floor of) the headline slot.

Usage: python tools/promote_serve_best.py LOG [LOG...]
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))


def candidates(paths):
    for path in paths:
        if not os.path.exists(path):
            continue
        for line in open(path):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if doc.get("mode") == "continuous" and \
                    isinstance(doc.get("tokens_per_sec"), (int, float)) and \
                    doc["tokens_per_sec"] > 0:
                yield doc


def _config_key(doc) -> str:
    return "|".join(str(doc.get(k)) for k in (
        "model", "max_new_tokens", "slots", "param_dtype",
        "kv_cache_dtype", "attention_window", "rolling_kv_cache"))


def main() -> int:
    paths = sys.argv[1:] or [os.path.join(HERE, "serve_sweep.log")]
    best_path = os.path.join(HERE, "serve_best.json")
    table_path = os.path.join(HERE, "serve_table.json")
    floor = 0.0
    if os.path.exists(best_path):
        try:
            floor = json.load(open(best_path)).get("tokens_per_sec", 0.0)
        except (ValueError, OSError):
            pass
    # per-config bests (every measured geometry/dtype keeps its own row —
    # the A/B ledger for BASELINE.md)
    table: dict = {}
    if os.path.exists(table_path):
        try:
            table = json.load(open(table_path))
        except (ValueError, OSError):
            table = {}
    best = None
    for doc in candidates(paths):
        key = _config_key(doc)
        if doc["tokens_per_sec"] > table.get(key, {}).get(
                "tokens_per_sec", 0.0):
            table[key] = doc
        # serve_best.json pins ONLY the default headline geometry —
        # cross-config competition (e.g. a llama-1b long-prompt point)
        # must neither win the slot nor raise the floor against future
        # default-geometry measurements
        if doc.get("model") != "gpt-350m":
            continue
        if doc["tokens_per_sec"] > floor and (
                best is None
                or doc["tokens_per_sec"] > best["tokens_per_sec"]):
            best = doc
    if table:
        tmp = table_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1)
        os.replace(tmp, table_path)
        print(f"serving table: {len(table)} config(s) -> {table_path}")
    if best is None:
        print(f"no default-geometry point beat {floor:.1f} tok/s; "
              "serve_best.json unchanged")
        return 0
    best["promoted_at"] = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())
    tmp = best_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(best, f, indent=1)
    os.replace(tmp, best_path)
    print(f"promoted serving point {best['model']} "
          f"{best['param_dtype']}/{best.get('kv_cache_dtype', 'native')} "
          f"{best['tokens_per_sec']} tok/s -> {best_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
