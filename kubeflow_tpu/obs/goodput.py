"""Goodput accounting: what fraction of chip-seconds were productive?

"Scale MLPerf-0.6 models on Google TPU-v3 Pods" (PAPERS.md) frames
pod-scale efficiency as THE metric; at fleet scale the question is not
"is the job running" but "of the wall-clock the gang held chips, how
much advanced the model?". This module answers it from telemetry the
platform already emits — the PR 4 span stream — with no new
instrumentation contract:

- ``train.step``                 -> ``productive_step`` (or ``compile``
                                    when the span carries the trainer's
                                    ``compile=True`` attr — step 0 pays
                                    XLA compilation)
- ``train.checkpoint``           -> ``checkpoint`` (Checkpointer.save's
                                    device->host + queue window)
- ``elastic.rebuild``            -> ``resize_rebuild`` (teardown,
                                    re-formation, trainer rebuild and
                                    restore across an elastic resize)
- ``jaxjob.provision`` after the
  first                          -> ``restart_rebuild`` (gang restarts
                                    re-provisioning the world)
- window start -> first activity -> ``blocked_on_admission`` (queue
                                    wait + scheduling + image pull +
                                    process start: everything before
                                    the first classified span)
- everything else                -> ``other`` (data stalls, eval,
                                    Python overhead — visible on
                                    purpose: a growing ``other`` is a
                                    profiling signal, not a rounding
                                    error)

Accounting is a single SPMD timeline: overlapping spans are resolved
by bucket priority on an interval sweep, so a checkpoint inside a step
window never double-counts — **conservation** (buckets sum exactly to
the wall-clock window) is checked, not assumed (``GoodputReport.check``
raises on violation; the chaos soak and the elastic resize drill
assert it). Chip-seconds-lost = bucket seconds x gang chips.

``ServingSLO`` is the serving-side counterpart: a latency target +
error budget evaluated from the router's native histograms (either a
registry's cumulative counts or rate()s over the fleet TSDB), the
numbers ``GET /api/goodput`` serves and the SLO-burn alert watches.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from kubeflow_tpu.obs.trace import Span

# Bucket names, priority order (earlier wins where spans overlap).
PRODUCTIVE = "productive_step"
COMPILE = "compile"
CHECKPOINT = "checkpoint"
RESIZE = "resize_rebuild"
RESTART = "restart_rebuild"
ADMISSION = "blocked_on_admission"
OTHER = "other"
BUCKETS = (PRODUCTIVE, COMPILE, CHECKPOINT, RESIZE, RESTART, ADMISSION,
           OTHER)

# span name -> bucket. jaxjob.provision is special-cased (first one is
# startup, later ones are restarts) in classify().
SPAN_BUCKETS = {
    "train.step": PRODUCTIVE,
    "train.checkpoint": CHECKPOINT,
    "elastic.rebuild": RESIZE,
    "jaxjob.provision": RESTART,
}


@dataclass
class GoodputReport:
    """One window's accounting. ``buckets`` are seconds; they sum to
    ``wall_s`` (conservation — ``check()`` proves it)."""

    wall_s: float
    chips: int
    buckets: dict = field(default_factory=dict)

    @property
    def goodput(self) -> float:
        """Fraction of wall time in productive steps (0..1)."""
        if self.wall_s <= 0:
            return 0.0
        return self.buckets.get(PRODUCTIVE, 0.0) / self.wall_s

    def chip_seconds_lost(self) -> dict:
        """Chip-seconds by non-productive cause — the fleet-level cost
        of each failure mode, the number capacity planning wants."""
        return {name: round(self.buckets.get(name, 0.0) * self.chips, 6)
                for name in BUCKETS if name != PRODUCTIVE}

    def check(self, tolerance: float = 1e-6) -> "GoodputReport":
        """Conservation: bucket seconds sum to the wall window. A
        violation means double-counted or dropped time — raise, never
        publish a goodput number that doesn't add up."""
        total = sum(self.buckets.values())
        if not math.isclose(total, self.wall_s, abs_tol=tolerance,
                            rel_tol=1e-9):
            raise AssertionError(
                f"goodput buckets sum to {total:.9f}s != wall "
                f"{self.wall_s:.9f}s (delta {total - self.wall_s:+.9f})")
        return self

    def to_dict(self) -> dict:
        return {
            "wall_s": round(self.wall_s, 6),
            "chips": self.chips,
            "goodput_pct": round(self.goodput * 100.0, 3),
            "buckets_s": {k: round(v, 6)
                          for k, v in sorted(self.buckets.items())},
            "chip_seconds_lost": self.chip_seconds_lost(),
        }


def classify(spans: list[Span]) -> list[tuple[int, float, float]]:
    """Spans -> (priority, start, end) intervals. Priority is the
    bucket's index in BUCKETS (lower wins). Open spans are skipped —
    an unfinished step cannot be credited yet."""
    provisions = sorted(
        (s for s in spans if s.name == "jaxjob.provision"
         and s.end is not None),
        key=lambda s: s.start)
    first_provision = provisions[0] if provisions else None
    out: list[tuple[int, float, float]] = []
    for s in spans:
        if s.end is None or s.end <= s.start:
            continue
        bucket = SPAN_BUCKETS.get(s.name)
        if bucket is None:
            continue
        if s.name == "train.step" and s.attrs.get("compile"):
            bucket = COMPILE
        if s.name == "jaxjob.provision" and s is first_provision:
            # the FIRST provision is cold start: it precedes the first
            # worker activity and lands in blocked_on_admission with
            # the rest of the startup gap
            bucket = ADMISSION
        out.append((BUCKETS.index(bucket), s.start, s.end))
    return out


def account(spans: list[Span], window_start: float, window_end: float,
            chips: int = 1) -> GoodputReport:
    """Sweep-line accounting of ``spans`` over ``[window_start,
    window_end]``: each elementary segment goes to the highest-priority
    covering interval; the prefix before the first classified activity
    is ``blocked_on_admission``; the uncovered remainder is ``other``.
    Conservation holds by construction — and is re-checked in
    ``GoodputReport.check`` because "by construction" has been wrong
    before."""
    wall = max(window_end - window_start, 0.0)
    report = GoodputReport(wall_s=wall, chips=max(int(chips), 1),
                           buckets={name: 0.0 for name in BUCKETS})
    if wall <= 0:
        return report
    intervals = []
    for prio, s, e in classify(spans):
        s = max(s, window_start)
        e = min(e, window_end)
        if e > s:
            intervals.append((prio, s, e))
    # the admission prefix: window start up to the first NON-admission
    # activity (worker spans or a restart/resize rebuild) — the first
    # provision and any gap around it are all "waiting to start"
    admission_prio = BUCKETS.index(ADMISSION)
    first_activity = min((s for prio, s, _ in intervals
                          if prio < admission_prio),
                         default=window_end)
    if first_activity > window_start:
        intervals.append((BUCKETS.index(ADMISSION), window_start,
                          first_activity))
    # sweep the elementary segments between all boundaries; a per-
    # priority active count makes the whole pass O(n log n) — the soak
    # hands this thousands of spans
    deltas: dict[float, list[int]] = {}
    for prio, s, e in intervals:
        deltas.setdefault(s, [0] * len(BUCKETS))[prio] += 1
        deltas.setdefault(e, [0] * len(BUCKETS))[prio] -= 1
    cuts = sorted({window_start, window_end, *deltas})
    active = [0] * len(BUCKETS)
    for lo, hi in zip(cuts, cuts[1:]):
        if lo in deltas:
            for prio, d in enumerate(deltas[lo]):
                active[prio] += d
        if hi <= window_start or lo >= window_end:
            continue
        best = next((p for p, n in enumerate(active) if n > 0), None)
        name = BUCKETS[best] if best is not None else OTHER
        report.buckets[name] += hi - lo
    return report


def job_report(spans: list[Span], chips: int = 1,
               window_start: float | None = None,
               window_end: float | None = None) -> GoodputReport:
    """Convenience: account a job's trace over its own observed extent
    (root span start -> latest span end) unless the caller pins the
    window (the drills pin it to the drill clock)."""
    closed = [s for s in spans if s.end is not None]
    if not closed and window_start is None:
        return GoodputReport(wall_s=0.0, chips=max(int(chips), 1),
                             buckets={name: 0.0 for name in BUCKETS})
    start = window_start if window_start is not None \
        else min(s.start for s in closed)
    # a pinned start with nothing closed yet: an all-admission window,
    # not a max()-over-empty crash
    end = window_end if window_end is not None \
        else max((s.end for s in closed), default=start)
    return account(spans, start, end, chips=chips)


# -- tenant attribution (the chargeback ledger cut) ---------------------------

# span attrs consulted for the billing tenant, in precedence order: an
# explicit tenant attr (the router stamps one per dispatch) wins over
# the emitting controller's namespace.
TENANT_ATTRS = ("tenant", "namespace")
DEFAULT_TENANT = "default"


def span_tenant(span: Span) -> str:
    """The tenant a span bills to — its ``tenant`` attr, else its
    ``namespace``, else the default tenant (fleet-global spans like
    scheduler passes land there on purpose: unattributable time must
    stay visible, not vanish)."""
    for key in TENANT_ATTRS:
        value = span.attrs.get(key)
        if value:
            return str(value)
    return DEFAULT_TENANT


@dataclass
class TenantLedger:
    """The per-tenant cut of the goodput ledger over one window.

    Each tenant gets its own sweep-line ``GoodputReport`` over ITS
    spans (tenants are independent SPMD timelines — one tenant's
    checkpoint must never mask another's productive step), weighted by
    that tenant's chips. ``check()`` proves conservation twice: every
    per-tenant report conserves to the wall window, AND the chip-second
    buckets summed across tenants equal the fleet total
    (``wall x total chips``) exactly — a chargeback invoice that does
    not add up to the fleet bill is raised, never published."""

    wall_s: float
    reports: dict = field(default_factory=dict)   # tenant -> GoodputReport

    @property
    def chips(self) -> int:
        return sum(r.chips for r in self.reports.values())

    def chip_seconds_by_tenant(self) -> dict:
        """tenant -> {cause: chip_seconds} over EVERY bucket (including
        productive time — the invoice bills held chips, not just lost
        ones)."""
        return {
            tenant: {name: r.buckets.get(name, 0.0) * r.chips
                     for name in BUCKETS}
            for tenant, r in sorted(self.reports.items())
        }

    def check(self, tolerance: float = 1e-6) -> "TenantLedger":
        """Conservation, raised not warned (the fleet ledger's
        discipline): per-tenant bucket seconds sum to the wall window,
        and summed chip-seconds across tenants equal the fleet
        ledger."""
        total = 0.0
        for tenant, r in self.reports.items():
            try:
                r.check(tolerance)
            except AssertionError as e:
                raise AssertionError(f"tenant {tenant!r}: {e}") from None
            total += sum(r.buckets.values()) * r.chips
        fleet = self.wall_s * self.chips
        if not math.isclose(total, fleet, abs_tol=tolerance,
                            rel_tol=1e-9):
            raise AssertionError(
                f"tenant chip-seconds sum to {total:.9f} != fleet "
                f"ledger {fleet:.9f} (delta {total - fleet:+.9f})")
        return self

    def to_dict(self) -> dict:
        return {
            "wall_s": round(self.wall_s, 6),
            "chips": self.chips,
            "tenants": {tenant: r.to_dict()
                        for tenant, r in sorted(self.reports.items())},
        }


def tenant_report(spans: list[Span], window_start: float,
                  window_end: float,
                  chips_by_tenant: dict | None = None,
                  default_chips: int = 1) -> TenantLedger:
    """Cut the span stream by billing tenant and account each tenant's
    timeline over the SAME window. ``chips_by_tenant`` sets each
    tenant's chip weight (missing tenants get ``default_chips``);
    tenants listed there with no spans still get a report — an
    all-admission window, the honest bill for chips held idle."""
    by_tenant: dict[str, list[Span]] = {}
    for s in spans:
        by_tenant.setdefault(span_tenant(s), []).append(s)
    for tenant in (chips_by_tenant or {}):
        by_tenant.setdefault(tenant, [])
    ledger = TenantLedger(wall_s=max(window_end - window_start, 0.0))
    for tenant, tenant_spans in sorted(by_tenant.items()):
        chips = (chips_by_tenant or {}).get(tenant, default_chips)
        ledger.reports[tenant] = account(
            tenant_spans, window_start, window_end, chips=chips)
    return ledger


# -- serving SLOs ------------------------------------------------------------


@dataclass
class ServingSLO:
    """A latency objective over the router histogram: ``objective`` of
    requests complete within ``latency_target_s``. The target must sit
    on a REQUEST_BUCKETS bound (serving/router.py) — attainment is read
    straight off the cumulative ``le`` counts, no interpolation, so the
    SLO is exact rather than estimated."""

    name: str = "router-latency"
    latency_target_s: float = 0.5
    objective: float = 0.99

    @property
    def le(self) -> str:
        """The bucket label the target matches, normalized through
        float(): the registry renders ``le`` bounds as ``str(float)``
        ("1.0", never "1"), so an int-valued target must not silently
        match zero fast samples."""
        return str(float(self.latency_target_s))

    def _status(self, fast: float, total: float) -> dict:
        budget = max(1.0 - self.objective, 1e-9)
        attainment = (fast / total) if total > 0 else 1.0
        burn = (1.0 - attainment) / budget
        return {
            "slo": self.name,
            "latency_target_s": self.latency_target_s,
            "objective": self.objective,
            "requests": total,
            "attainment": round(attainment, 6),
            # 1.0 = burning the whole budget over the period measured
            "budget_burn": round(burn, 6),
            "budget_remaining": round(1.0 - burn, 6),
            "met": attainment >= self.objective,
        }

    def from_registry(self, registry, namespace: str,
                      service: str, tenant: str | None = None) -> dict:
        """Cumulative-since-start attainment from a MetricsRegistry's
        router histogram (the in-process shape). ``tenant`` narrows to
        one billing tenant's series (the chargeback cut)."""
        fast = total = 0.0
        # the native histogram renders per-le series; read via the text
        # exposition through the ONE parser
        from kubeflow_tpu.obs import expofmt

        for s in expofmt.parse(registry.render()):
            labels = s.labels_dict()
            if labels.get("namespace") != namespace or \
                    labels.get("service") != service:
                continue
            if tenant is not None and labels.get("tenant") != tenant:
                continue
            if s.name == "router_request_seconds_bucket" and \
                    labels.get("le") == self.le:
                fast += s.value
            elif s.name == "router_request_seconds_count":
                total += s.value
        return self._status(fast, total)

    def from_store(self, store, at: float, window_s: float = 300.0,
                   service: str | None = None,
                   tenant: str | None = None) -> dict:
        """Windowed attainment from the fleet TSDB: increase() of the
        fast bucket vs the count over the last ``window_s``. ``tenant``
        narrows to one billing tenant's series (the chargeback cut)."""
        from kubeflow_tpu.obs.rules import Evaluator

        ev = Evaluator(store)
        sel = []
        if service:
            sel.append(f'service="{service}"')
        if tenant:
            sel.append(f'tenant="{tenant}"')
        le_sel = 'le="%s"' % self.le
        match = f"{{{','.join(sel)}}}" if sel else ""
        lematch = f"{{{','.join([le_sel] + sel)}}}"
        # rounded, floored at 1s: bare int() truncation turned a
        # fractional window into "[0s]" — an empty window that reported
        # a burning service as trivially meeting its SLO
        win = f"[{max(1, round(window_s))}s]"
        fast = sum(v for _, v in ev.query(
            f"increase(router_request_seconds_bucket{lematch}{win})", at))
        total = sum(v for _, v in ev.query(
            f"increase(router_request_seconds_count{match}{win})", at))
        return self._status(fast, total)


# -- the goodput exporter -----------------------------------------------------

# chip count multiplying chip-seconds-lost in exported series; 0/unset
# disables the export loop entirely (controller managers read this)
ENV_GOODPUT_CHIPS = "TPU_GOODPUT_CHIPS"


class GoodputExporter:
    """Publish the goodput ledger as ``goodput_*`` series.

    The PR 10 ledger could be *queried* (``GET /api/goodput``) but no
    production process ever exported it — fleet dashboards had nothing
    to scrape. This exporter closes that open: each ``export_once``
    recomputes the report from the process's span stream and publishes

    - ``goodput_ratio``                      (0..1)
    - ``goodput_wall_seconds``               (accounted window)
    - ``goodput_bucket_seconds{bucket=}``    (per-cause time)
    - ``goodput_chip_seconds_lost{cause=}``  (per-cause chip cost)

    into the MetricsRegistry, so the scrape plane picks them up like
    any other series. ``run_controller`` mains start one; harnesses
    call ``export_once(at=...)`` on virtual time."""

    def __init__(self, registry=None, collector=None, chips: int = 1,
                 interval_s: float = 30.0):
        from kubeflow_tpu.obs import trace as obs_trace
        from kubeflow_tpu.runtime.metrics import REGISTRY

        self.registry = registry if registry is not None else REGISTRY
        self.collector = collector if collector is not None \
            else obs_trace.COLLECTOR
        self.chips = max(int(chips), 1)
        self.interval_s = interval_s
        self._thread = None
        self._stop = None

    def export_once(self, at: float | None = None) -> GoodputReport:
        spans = self.collector.spans()
        report = job_report(spans, chips=self.chips, window_end=at)
        self.registry.gauge("goodput_ratio", report.goodput,
                            help_="fraction of wall time in productive "
                                  "steps (0..1)")
        self.registry.gauge("goodput_wall_seconds", report.wall_s,
                            help_="wall-clock window the ledger "
                                  "accounted")
        for name, secs in sorted(report.buckets.items()):
            self.registry.gauge("goodput_bucket_seconds", secs,
                                help_="accounted seconds by cause",
                                bucket=name)
        for cause, cost in sorted(report.chip_seconds_lost().items()):
            self.registry.gauge("goodput_chip_seconds_lost", cost,
                                help_="chip-seconds lost by "
                                      "non-productive cause", cause=cause)
        return report

    def start(self) -> "GoodputExporter":  # pragma: no cover - thread
        import threading

        if self._thread is None:
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._run, name="goodput-export", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:  # pragma: no cover - thread shell
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:  # pragma: no cover - thread shell
        import logging

        while not self._stop.wait(self.interval_s):
            try:
                self.export_once()
            except Exception:  # telemetry must never kill the process
                logging.getLogger("kubeflow_tpu.obs.goodput").exception(
                    "goodput export failed")
