"""Image release workflows.

The reference's releaser is a set of Argo workflow jsonnets
(image-releaser/components/tf-{serving,notebook}-workflow.libsonnet,
releasing/releaser/components/workflows.libsonnet) that check out the
repo, run `docker build` per component with a registry/tag parameter
matrix, push, and emit a release manifest. This module provides that
capability natively:

- `IMAGES`: the component image inventory (context dir + Dockerfile +
  build-arg matrix, e.g. the notebook's cpu/tpu variant pair — the
  versions/{x.y.z}{,gpu} analogue).
- `build_commands(spec, registry, tag)`: the exact container-tool
  command lines (pure function: unit-testable, auditable).
- `release_workflow(...)`: a testing.Workflow DAG — build all images in
  parallel, then push, then write a release manifest artifact — with a
  pluggable runner so CI can dry-run it hermetically.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import time
from typing import Callable

from kubeflow_tpu.testing.workflow import Workflow


@dataclasses.dataclass(frozen=True)
class ImageSpec:
    name: str                      # image repo basename
    context: str                   # build context, repo-relative
    dockerfile: str = "Dockerfile"  # relative to context
    build_args: tuple = ()          # ((key, value), ...)


IMAGES: tuple[ImageSpec, ...] = (
    ImageSpec("jaxrt", ".", "images/jaxrt/Dockerfile"),
    ImageSpec("jax-notebook", ".", "images/notebook/Dockerfile",
              (("JAX_EXTRA", "cpu"),)),
    ImageSpec("jax-notebook-tpu", ".", "images/notebook/Dockerfile",
              (("JAX_EXTRA", "tpu"),)),
    ImageSpec("platform", ".", "images/platform/Dockerfile"),
    # utility images (reference: ingress-setup-image, private-utils)
    ImageSpec("ingress-setup", ".", "images/ingress-setup/Dockerfile"),
    ImageSpec("private-utils", ".", "images/private-utils/Dockerfile"),
)


def image_ref(spec: ImageSpec, registry: str, tag: str) -> str:
    return f"{registry}/{spec.name}:{tag}"


def _build_args(spec: ImageSpec, tags: list[str],
                cache_from: str | None = None) -> list[str]:
    """Shared docker-build argv assembly (build_commands +
    cloudbuild_manifest must never diverge)."""
    args = ["build"]
    for t in tags:
        args += ["-t", t]
    args += ["-f", spec.dockerfile]
    if cache_from:
        args += ["--cache-from", cache_from]
    for k, v in spec.build_args:
        args += ["--build-arg", f"{k}={v}"]
    args.append(spec.context)
    return args


def build_commands(spec: ImageSpec, registry: str, tag: str,
                   tool: str = "docker") -> list[list[str]]:
    """The build command line(s) for one image (push is separate)."""
    return [[tool] + _build_args(spec, [image_ref(spec, registry, tag)])]


def push_commands(spec: ImageSpec, registry: str, tag: str,
                  tool: str = "docker") -> list[list[str]]:
    return [[tool, "push", image_ref(spec, registry, tag)]]


def cloudbuild_manifest(
    images: tuple[ImageSpec, ...],
    registry: str,
    tag: str,
    *,
    use_image_cache: bool = False,
    latest_tag: str = "latest",
) -> dict:
    """Cloud Build config for the image set — tools/gcb/template.libsonnet
    rebuilt as data. Per image: optional cache pull (waitFor: ['-'] so
    pulls start immediately, subGraphTemplate's pullStep), a build step
    (--cache-from when caching), and a push list via `images`.
    """
    steps = []
    out_images = []
    for spec in images:
        ref = image_ref(spec, registry, tag)
        latest = image_ref(spec, registry, latest_tag)
        out_images += [ref, latest]
        if use_image_cache:
            steps.append({
                "id": f"pull-{spec.name}",
                "name": "gcr.io/cloud-builders/docker",
                "entrypoint": "bash",  # tolerate a missing cache image
                "args": ["-c", f"docker pull {latest} || exit 0"],
                "waitFor": ["-"],
            })
        steps.append({
            "id": f"build-{spec.name}",
            "name": "gcr.io/cloud-builders/docker",
            "args": _build_args(spec, [ref, latest],
                                cache_from=latest if use_image_cache else None),
            # a step with no waitFor waits for ALL previous steps; images
            # are independent, so builds must parallelize in both modes
            "waitFor": [f"pull-{spec.name}"] if use_image_cache else ["-"],
        })
    return {"steps": steps, "images": out_images,
            "timeout": "3600s"}


def git_tag(repo_dir: str = ".") -> str:
    """vYYYYMMDD-<shortsha>: the reference's image tag shape."""
    sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                         cwd=repo_dir, capture_output=True, text=True,
                         check=True).stdout.strip()
    return f"v{time.strftime('%Y%m%d')}-{sha}"


def release_workflow(registry: str, tag: str, *,
                     images: tuple[ImageSpec, ...] = IMAGES,
                     runner: Callable[[list[str]], None] | None = None,
                     artifacts_dir: str | None = None,
                     push: bool = True,
                     tool: str = "docker") -> Workflow:
    """Build-all -> push-all -> manifest DAG. `runner` executes one
    command line; default is subprocess (check=True)."""

    def default_runner(cmd: list[str]) -> None:
        subprocess.run(cmd, check=True)

    run = runner or default_runner
    wf = Workflow(f"release-{tag}", artifacts_dir=artifacts_dir)

    def mk_build(spec: ImageSpec):
        def fn(ctx):
            for cmd in build_commands(spec, registry, tag, tool):
                run(cmd)
            return image_ref(spec, registry, tag)
        return fn

    def mk_push(spec: ImageSpec):
        def fn(ctx):
            for cmd in push_commands(spec, registry, tag, tool):
                run(cmd)
        return fn

    push_steps = []
    for spec in images:
        wf.step(f"build-{spec.name}", mk_build(spec))
        if push:
            wf.step(f"push-{spec.name}", mk_push(spec),
                    deps=[f"build-{spec.name}"])
            push_steps.append(f"push-{spec.name}")

    def manifest(ctx):
        doc = {
            "tag": tag,
            "registry": registry,
            "images": [image_ref(s, registry, tag) for s in images],
        }
        ctx.put("manifest", doc)
        if ctx.artifacts_dir:
            os.makedirs(ctx.artifacts_dir, exist_ok=True)
            path = os.path.join(ctx.artifacts_dir, f"release-{tag}.json")
            with open(path, "w") as f:
                json.dump(doc, f, indent=2)
            return path
        return doc

    wf.step("release-manifest", manifest,
            deps=push_steps or [f"build-{s.name}" for s in images])
    return wf
