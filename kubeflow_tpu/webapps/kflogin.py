"""kflogin: the login page paired with the gatekeeper authservice.

Mirrors components/kflogin (React app, src/login.js + src/App.js): a
browser form that POSTs {username, password} to the gatekeeper and, on
success, forwards the Set-Cookie and bounces the user back to the
original destination. Here the page is served directly (no node build
step) and the credential POST is proxied server-side to the gatekeeper's
/login endpoint so the cookie lands on the platform domain.
"""

from __future__ import annotations

import json
import logging
import urllib.request

from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import HttpReq, HttpResp, Router

log = logging.getLogger("kubeflow_tpu.kflogin")

_PAGE = b"""<!doctype html>
<html><head><title>kubeflow-tpu login</title></head>
<body>
<h2>Log in</h2>
<form id="f">
  <label>Username <input name="username" autocomplete="username"></label><br>
  <label>Password <input name="password" type="password"
         autocomplete="current-password"></label><br>
  <button type="submit">Login</button>
</form>
<p id="msg"></p>
<script>
document.getElementById('f').addEventListener('submit', async (e) => {
  e.preventDefault();
  const data = Object.fromEntries(new FormData(e.target).entries());
  const r = await fetch('apikflogin', {
    method: 'POST',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify(data),
  });
  if (r.ok) {
    const to = new URLSearchParams(location.search).get('rd') || '/';
    location.assign(to);
  } else {
    document.getElementById('msg').textContent = 'login failed';
  }
});
</script>
</body></html>
"""


class KfLogin:
    def __init__(self, gatekeeper_url: str = "http://127.0.0.1:8085",
                 auth_server=None):
        """auth_server: in-process gatekeeper AuthServer (tests / bundled
        deployments); otherwise credentials are proxied to gatekeeper_url."""
        self.gatekeeper_url = gatekeeper_url.rstrip("/")
        self.auth_server = auth_server

    def page(self, req: HttpReq):
        return HttpResp(200, _PAGE, "text/html")

    def do_login(self, req: HttpReq):
        if self.auth_server is not None:
            return self.auth_server.login(req)
        r = urllib.request.Request(
            self.gatekeeper_url + "/login",
            data=req.body or json.dumps({}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(r, timeout=10) as resp:
                cookie = resp.headers.get("Set-Cookie", "")
                return HttpResp(200, resp.read(),
                                headers={"Set-Cookie": cookie} if cookie else {})
        except urllib.error.HTTPError as e:
            return HttpResp(e.code, e.read())

    def router(self) -> Router:
        r = Router("kflogin")
        r.route("GET", "/kflogin", self.page)
        r.route("GET", "/", self.page)
        r.route("POST", "/apikflogin", self.do_login)
        httpd.add_health_routes(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 8084) -> httpd.HttpService:
        return httpd.HttpService(self.router(), host, port)
