"""CI/E2E harness tests: junit emission, the Argo-style DAG runner, and a
hermetic end-to-end workflow mirroring the reference's tier-4 DAG shape
(checkout -> deploy -> kf-is-ready -> second-apply -> workload -> teardown,
testing/workflows/components/kfctl_go_test.jsonnet; SURVEY.md §4)."""

import os
import xml.etree.ElementTree as ET

import pytest
import yaml

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.testing import (
    Step,
    TestSuite,
    Workflow,
    wait_for,
    wait_for_condition,
    wait_for_deployments_ready,
)
from kubeflow_tpu.testing.waiters import WaitTimeout


class TestJunit:
    def test_xml_schema(self, tmp_path):
        s = TestSuite("e2e")
        with s.case("ok"):
            pass
        with pytest.raises(RuntimeError):
            with s.case("boom"):
                raise RuntimeError("exploded")
        p = s.write(str(tmp_path / "junit_e2e.xml"))
        root = ET.parse(p).getroot()
        assert root.tag == "testsuite"
        assert root.get("tests") == "2" and root.get("failures") == "1"
        fail = root.findall("testcase")[1].find("failure")
        assert "exploded" in fail.text


class TestWaiters:
    def test_wait_for_timeout_is_fast_with_fake_clock(self):
        t = [0.0]

        def clock():
            return t[0]

        def sleep(s):
            t[0] += s

        with pytest.raises(WaitTimeout):
            wait_for(lambda: False, timeout_s=10, poll_s=1,
                     clock=clock, sleep=sleep)

    def test_wait_for_deployments_ready(self):
        c = FakeCluster()
        dep = ob.new_object("apps/v1", "Deployment", "web", namespace="kf",
                            spec={"replicas": 2})
        c.create(dep)
        calls = [0]

        def sleep(_):
            calls[0] += 1
            got = c.get("apps/v1", "Deployment", "web", "kf")
            got.setdefault("status", {})["readyReplicas"] = 2
            c.update_status(got)

        wait_for_deployments_ready(c, "kf", ["web"], timeout_s=10,
                                   poll_s=1, sleep=sleep)
        assert calls[0] == 1

    def test_wait_for_condition(self):
        c = FakeCluster()
        job = ob.new_object("kubeflow.org/v1", "StudyJob", "s", namespace="kf")
        c.create(job)

        def sleep(_):
            got = c.get("kubeflow.org/v1", "StudyJob", "s", "kf")
            got.setdefault("status", {})["conditions"] = [
                {"type": "Running", "status": "True"}]
            c.update_status(got)

        obj = wait_for_condition(c, "kubeflow.org/v1", "StudyJob", "s", "kf",
                                 ("Running",), timeout_s=10, poll_s=1,
                                 sleep=sleep)
        assert obj["status"]["conditions"][0]["type"] == "Running"


class TestWorkflow:
    def test_dag_order_skip_and_exit_handler(self, tmp_path):
        order = []

        def mk(name, fail=False):
            def fn(ctx):
                order.append(name)
                if fail:
                    raise RuntimeError(f"{name} failed")
                return name
            return fn

        wf = Workflow("dag", artifacts_dir=str(tmp_path))
        wf.step("a", mk("a"))
        wf.step("b", mk("b", fail=True), deps=["a"])
        wf.step("c", mk("c"), deps=["b"])          # must be skipped
        wf.step("d", mk("d"), deps=["a"])          # independent of b
        wf.exit_handler("teardown", mk("teardown"))
        res = wf.run()
        assert not res.succeeded
        assert res.steps["a"].status == "Succeeded"
        assert res.steps["b"].status == "Failed"
        assert res.steps["c"].status == "Skipped"
        assert res.steps["d"].status == "Succeeded"
        assert order[-1] == "teardown"  # exit handler always runs

        p = res.write_junit(str(tmp_path / "junit_dag.xml"))
        root = ET.parse(p).getroot()
        assert root.get("tests") == "5"
        assert root.get("failures") == "1" and root.get("skipped") == "1"

    def test_step_deadline(self):
        import time

        wf = Workflow("slow")
        wf.step("sleepy", lambda ctx: time.sleep(2), deadline_s=0.2)
        res = wf.run()
        assert res.steps["sleepy"].status == "Failed"
        assert "deadline" in res.steps["sleepy"].error

    def test_parallel_steps_overlap(self):
        import threading

        barrier = threading.Barrier(2, timeout=10)

        def rendezvous(ctx):
            barrier.wait()  # deadlocks unless both run concurrently

        wf = Workflow("par")
        wf.step("x", rendezvous)
        wf.step("y", rendezvous)
        assert wf.run().succeeded


class TestHermeticE2E:
    """The kfctl_go_test DAG shape against the fake cluster: deploy the
    platform via tpctl, wait ready, re-apply (idempotency —
    kfctl_second_apply.py), run a JAXJob workload, teardown."""

    def test_full_dag(self, tmp_path):
        from kubeflow_tpu.control.jaxjob import types as JT
        from kubeflow_tpu.control.jaxjob.controller import build_controller
        from kubeflow_tpu.control.runtime import seed_controller
        from kubeflow_tpu.tpctl.apply import Coordinator
        from kubeflow_tpu.tpctl.tpudef import TpuDef, example_yaml

        cluster = FakeCluster()
        wf = Workflow("kfctl-go-test-equivalent", artifacts_dir=str(tmp_path))

        def deploy(ctx):
            cfg = TpuDef.from_dict(yaml.safe_load(example_yaml()))
            coord = Coordinator(cluster)
            status = coord.apply(cfg)
            ctx.put("tpudef", cfg)
            ctx.put("n_objects", len(cluster.dump()))
            return status

        def kf_is_ready(ctx):
            deps = cluster.list("apps/v1", "Deployment", namespace="kubeflow")
            assert deps, "no deployments applied"
            for d in deps:  # fake cluster: mark ready, then assert the waiter
                d.setdefault("status", {})["readyReplicas"] = (
                    d.get("spec", {}).get("replicas", 1))
                cluster.update_status(d)
            wait_for_deployments_ready(cluster, "kubeflow", timeout_s=5,
                                       poll_s=0.01)

        def second_apply(ctx):
            coord = Coordinator(cluster)
            coord.apply(ctx.get("tpudef"))
            assert len(cluster.dump()) == ctx.get("n_objects"), \
                "second apply must be a no-op (idempotency)"

        def workload(ctx):
            ctl = seed_controller(build_controller(cluster))
            job = JT.new_jaxjob("smoke", "kubeflow", replicas=2,
                                image="kubeflow-tpu/jaxrt:latest")
            cluster.create(job)
            for _ in range(6):
                ctl.run_until_idle(advance_delayed=True)
            pods = cluster.list("v1", "Pod", namespace="kubeflow",
                                label_selector={JT.LABEL_JOB_NAME: "smoke"})
            assert len(pods) == 2

        def teardown(ctx):
            cfg = ctx.get("tpudef")
            if cfg is not None:
                Coordinator(cluster).delete(cfg)

        wf.step("deploy-kubeflow", deploy)
        wf.step("kf-is-ready", kf_is_ready, deps=["deploy-kubeflow"])
        # second-apply's whole-cluster no-op assertion must not race the
        # workload step's object creation; the reference DAG serializes
        # these too (deploy -> test steps in sequence, :251-303).
        wf.step("second-apply", second_apply, deps=["kf-is-ready"])
        wf.step("run-jaxjob", workload, deps=["second-apply"])
        wf.exit_handler("teardown", teardown)
        res = wf.run()
        assert res.succeeded, {k: (s.status, s.error)
                               for k, s in res.steps.items()}
        p = res.write_junit(os.path.join(str(tmp_path), "junit_01.xml"))
        assert os.path.exists(p)


def test_junit_quotes_in_names(tmp_path):
    s = TestSuite('suite "q"')
    with s.case('deploy "prod"'):
        pass
    root = ET.parse(s.write(str(tmp_path / "q.xml"))).getroot()
    assert root.get("name") == 'suite "q"'
    assert root.find("testcase").get("name") == 'deploy "prod"'


def test_workflow_hung_step_does_not_hang_dag():
    import threading
    import time

    release = threading.Event()

    def hung(ctx):
        release.wait(30)  # simulates a truly stuck subprocess

    wf = Workflow("hung")
    wf.step("stuck", hung, deadline_s=0.3)
    t0 = time.monotonic()
    res = wf.run()
    elapsed = time.monotonic() - t0
    release.set()
    assert res.steps["stuck"].status == "Failed"
    assert "deadline" in res.steps["stuck"].error
    assert elapsed < 5, f"run() blocked {elapsed:.1f}s past the deadline"
