#!/usr/bin/env bash
# LM perf sweep queue (runs when the TPU tunnel is up). Each line is one
# operating point; results append as JSON lines to tools/lm_sweep.log.
# See BASELINE.md "Measurement interruption note" for why this exists.
set -u
cd "$(dirname "$0")/.."
LOG=tools/lm_sweep.log
run() {
  echo "### $* $(date -u +%H:%M:%S)" >> "$LOG"
  timeout 900 python bench.py --workload lm "$@" 2>/dev/null | tail -1 >> "$LOG"
}
# gpt-350m adafactor: larger batch; dots-remat A/B
run --lm-model gpt-350m --lm-optimizer adafactor --lm-batch 16
run --lm-model gpt-350m --lm-optimizer adafactor --lm-batch 8 --lm-remat --lm-remat-policy dots
# adamw + dots remat (fits now?)
run --lm-model gpt-350m --lm-optimizer adamw --lm-batch 8 --lm-remat --lm-remat-policy dots
# bigger models (higher arithmetic intensity = the path past 20% MFU;
# adafactor frees the optimizer-state HBM that blocks them under adamw)
run --lm-model gpt-760m --lm-optimizer adafactor --lm-batch 8
run --lm-model gpt-760m --lm-optimizer adafactor --lm-batch 16
run --lm-model gpt-760m --lm-optimizer adafactor --lm-batch 8 --lm-remat --lm-remat-policy dots
run --lm-model llama-1b --lm-optimizer adafactor --lm-batch 4 --lm-remat --lm-remat-policy dots
run --lm-model llama-1b --lm-optimizer adafactor --lm-batch 8 --lm-remat --lm-remat-policy dots
run --lm-model llama-1b --lm-optimizer adafactor --lm-batch 8 --lm-remat --lm-remat-policy full
# flash block-size sweep on the current best config
for bq in 128 256 512; do
  for bk in 128 256; do
    echo "### blocks q=$bq k=$bk" >> "$LOG"
    KFTPU_FLASH_BLOCK_Q=$bq KFTPU_FLASH_BLOCK_K=$bk \
      timeout 900 python bench.py --workload lm --lm-model gpt-350m \
      --lm-optimizer adafactor 2>/dev/null | tail -1 >> "$LOG"
  done
done
echo "### sweep done $(date -u +%H:%M:%S)" >> "$LOG"
# promote the best measured point to the bench default (bench.py
# --lm-best auto reads tools/lm_best.json); only beats-the-floor
# measured numbers are ever promoted
python tools/promote_best.py "$LOG" >> "$LOG" 2>&1
