"""tpulint sharding-consistency rules (TPU105/TPU106) — whole-program.

A mesh-axis typo is the cheapest way to ship a silently wrong sharding:
``jax.jit(..., in_shardings=NamedSharding(mesh, P("modle")))`` raises
only at run time on a real slice (or, worse, replicates where it should
shard). Both rules resolve the mesh-axis vocabulary *statically* from
the program slice being scanned:

- every ``Mesh(devices, (...axes...))`` constructor whose axis-name
  tuple resolves through module-level constants (including constants
  imported from other scanned modules, e.g. ``_AXIS_ORDER`` in
  ``parallel/mesh.py``), and
- the canonical axis vocabulary of ``kubeflow_tpu/parallel/mesh.py``
  whenever a module imports from it (so per-file scans of modules built
  on the shared helpers are still checked).

TPU105 flags ``jax.jit``/``pjit`` ``in_shardings``/``out_shardings``
whose PartitionSpec axis names are not in that vocabulary; TPU106 flags
any other ``NamedSharding(mesh, P(...))`` construction that drifts from
it. With no resolvable Mesh and no mesh-helper import the rules stay
silent, a module whose own Mesh constructor does not resolve is
skipped (its true vocabulary is unknowable), and unresolvable axis
expressions inside specs are skipped — the rules never guess.

The canonical tuple below mirrors ``parallel/mesh.py:_AXIS_ORDER``;
tests/test_tpulint.py pins the two in sync by parsing the source (this
package must not import jax).
"""

from __future__ import annotations

import ast
from typing import Iterator

from kubeflow_tpu.analysis.callgraph import Program
from kubeflow_tpu.analysis.core import (
    Finding, ProgramRule, call_name, dotted, register,
)
from kubeflow_tpu.analysis.rules_jax import _JITS

# mirror of kubeflow_tpu/parallel/mesh.py axis vocabulary (AST-pinned in
# tests; analysis must stay importable without jax)
CANONICAL_AXES = ("dcn", "data", "fsdp", "pipe", "expert", "seq", "model")
_MESH_HELPER_MODULE = "kubeflow_tpu.parallel.mesh"

_MESH_CTORS = {"Mesh", "jax.sharding.Mesh", "sharding.Mesh",
               "maps.Mesh", "jax.experimental.maps.Mesh"}
_SPEC_CTORS = {"P", "PartitionSpec", "jax.sharding.PartitionSpec",
               "sharding.PartitionSpec"}
_NAMED_SHARDING = {"NamedSharding", "jax.sharding.NamedSharding",
                   "sharding.NamedSharding"}
_SHARDING_KWARGS = ("in_shardings", "out_shardings")


def _module_consts(module) -> dict[str, ast.expr]:
    """Top-level simple-name assignments (the constant table)."""
    out: dict[str, ast.expr] = {}
    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                out[node.target.id] = node.value
    return out


class _AxisResolver:
    """Resolve axis-name expressions to strings through module-level
    constants, following from-imports into other scanned modules."""

    def __init__(self, program: Program):
        self.program = program
        self._consts = {name: _module_consts(m)
                        for name, m in program.modules.items()}

    def resolve(self, modname: str, expr: ast.expr,
                depth: int = 4) -> tuple[list[str], bool]:
        """(axis names, fully_resolved). Nested tuples flatten; None
        entries (replicated dims) are fine and contribute nothing."""
        if depth <= 0:
            return [], False
        if isinstance(expr, ast.Constant):
            if expr.value is None:
                return [], True
            if isinstance(expr.value, str):
                return [expr.value], True
            return [], False
        if isinstance(expr, (ast.Tuple, ast.List)):
            axes: list[str] = []
            complete = True
            for e in expr.elts:
                got, ok = self.resolve(modname, e, depth)
                axes.extend(got)
                complete &= ok
            return axes, complete
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return self._resolve_name(modname, expr, depth)
        return [], False

    def _resolve_name(self, modname: str, expr: ast.expr,
                      depth: int) -> tuple[list[str], bool]:
        name = dotted(expr)
        if not name:
            return [], False
        # local constant
        if name in self._consts.get(modname, {}):
            return self.resolve(modname, self._consts[modname][name],
                                depth - 1)
        table = self.program.imports.get(modname, {})
        head, _, rest = name.partition(".")
        got = table.get(name) or table.get(head)
        if got is None:
            return [], False
        if got[0] == "sym" and not rest:
            _, target, sym = got
            if target in self.program.modules:
                if sym in self._consts.get(target, {}):
                    return self.resolve(target, self._consts[target][sym],
                                        depth - 1)
            return [], False
        if got[0] == "mod" and rest and "." not in rest:
            target = got[1]
            if target in self.program.modules and \
                    rest in self._consts.get(target, {}):
                return self.resolve(target, self._consts[target][rest],
                                    depth - 1)
        return [], False


def _mesh_vocabulary(program: Program,
                     resolver: _AxisResolver) -> tuple[set[str], set[str]]:
    """(axis vocabulary, unreliable modules).

    The vocabulary is the union of every *resolved* Mesh constructor's
    axes across the program. A module whose own Mesh constructor does
    NOT fully resolve (runtime-built axis names) is listed unreliable:
    flagging specs in *that* module against the partial vocabulary
    would invent false positives, so it is skipped — but a fully
    resolved module elsewhere in the program is still checked."""
    vocab: set[str] = set()
    unreliable: set[str] = set()
    found: set[str] = set()
    for modname, module in program.modules.items():
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in _MESH_CTORS:
                continue
            axes_expr = None
            if len(node.args) >= 2:
                axes_expr = node.args[1]
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    axes_expr = kw.value
            if axes_expr is None:
                continue
            found.add(modname)
            axes, ok = resolver.resolve(modname, axes_expr)
            vocab.update(axes)
            if not ok:
                unreliable.add(modname)
        # modules built on the shared mesh helpers get the canonical
        # vocabulary even when parallel/mesh.py isn't in this scan
        for target in program.imports.get(modname, {}).values():
            if target[1] == _MESH_HELPER_MODULE or (
                    target[0] == "sym"
                    and target[1].endswith("parallel.mesh")):
                vocab.update(CANONICAL_AXES)
                found.add(modname)
    if not found:
        return set(), set()  # no mesh evidence anywhere: never guess
    return vocab, unreliable


def _axis_strings(resolver: _AxisResolver, modname: str,
                  call: ast.Call) -> Iterator[tuple[str, ast.expr]]:
    """Axis-name strings mentioned in a P(...)/PartitionSpec(...) call
    (literals and fully-resolved constants only)."""
    for arg in call.args:
        axes, ok = resolver.resolve(modname, arg)
        if ok:
            for a in axes:
                yield a, arg


class _ShardingRule(ProgramRule):
    """Shared machinery: walk spec constructions, compare to vocab."""

    def _spec_calls(self, expr: ast.expr) -> Iterator[ast.Call]:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and call_name(node) in _SPEC_CTORS:
                yield node

    def _jit_sharding_kwargs(self, module) -> Iterator[ast.expr]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and call_name(node) in _JITS:
                for kw in node.keywords:
                    if kw.arg in _SHARDING_KWARGS:
                        yield kw.value


@register
class JitShardingAxisDrift(_ShardingRule):
    """TPU105: in_shardings/out_shardings name a mesh axis the program
    slice's Mesh does not define."""

    id = "TPU105"
    name = "jit-sharding-axis-drift"
    short = "jit in_/out_shardings reference an axis missing from the mesh"

    def check_program(self, program: Program) -> Iterator[Finding]:
        resolver = _AxisResolver(program)
        vocab, unreliable = _mesh_vocabulary(program, resolver)
        if not vocab:
            return
        for modname, module in program.modules.items():
            if modname in unreliable:
                continue  # this module's own mesh didn't resolve
            for kwval in self._jit_sharding_kwargs(module):
                for spec in self._spec_calls(kwval):
                    for axis, node in _axis_strings(resolver, modname, spec):
                        if axis not in vocab:
                            yield Finding(
                                self.id, module.path, node.lineno,
                                node.col_offset,
                                f"sharding axis '{axis}' is not an axis of "
                                "any Mesh in this program slice "
                                f"(known: {', '.join(sorted(vocab))}) — "
                                "the jit will fail at call time or "
                                "silently replicate")


@register
class NamedShardingAxisDrift(_ShardingRule):
    """TPU106: a NamedSharding built from a PartitionSpec whose axis
    names drift from the mesh vocabulary (parallel/mesh.py helpers)."""

    id = "TPU106"
    name = "namedsharding-axis-drift"
    short = "NamedSharding spec names an axis missing from the mesh"

    def check_program(self, program: Program) -> Iterator[Finding]:
        resolver = _AxisResolver(program)
        vocab, unreliable = _mesh_vocabulary(program, resolver)
        if not vocab:
            return
        for modname, module in program.modules.items():
            if modname in unreliable:
                continue  # this module's own mesh didn't resolve
            # TPU105 owns anything inside a jit sharding kwarg
            claimed: set[int] = set()
            for kwval in self._jit_sharding_kwargs(module):
                claimed.update(id(n) for n in ast.walk(kwval))
            for node in ast.walk(module.tree):
                if not (isinstance(node, ast.Call)
                        and call_name(node) in _NAMED_SHARDING
                        and id(node) not in claimed):
                    continue
                for spec in self._spec_calls(node):
                    for axis, sub in _axis_strings(resolver, modname, spec):
                        if axis not in vocab:
                            yield Finding(
                                self.id, module.path, sub.lineno,
                                sub.col_offset,
                                f"NamedSharding spec names axis '{axis}', "
                                "which no Mesh in this program slice "
                                f"defines (known: "
                                f"{', '.join(sorted(vocab))}) — axis names "
                                "must come from parallel/mesh.py's "
                                "vocabulary")
