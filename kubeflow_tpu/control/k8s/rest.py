"""RestClient — the same Client interface against a real apiserver.

The reference's controllers get this from client-go / controller-runtime;
here it is a thin HTTPS layer (requests) with in-cluster config loading
(serviceaccount token + CA, exactly what client-go's rest.InClusterConfig
does). Controllers written against FakeCluster run unmodified against a
live cluster by swapping this in.
"""

from __future__ import annotations

import json
import logging
import os
import random
import ssl  # noqa: F401  (documents the TLS dependency)
import time
from typing import Any

from kubeflow_tpu.control.k8s import objects as ob

log = logging.getLogger("kubeflow_tpu.rest")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# Statuses where the server itself says "try again": it REFUSED the
# request, so no mutation was applied and retrying any verb is safe.
_REFUSED_STATUS = (429, 503)
# Statuses (and connection-level failures) where the request MAY have
# been applied before things went wrong — only verbs that are safe to
# replay get retried. GET re-reads; DELETE re-deleting is a 404 the
# callers already treat as done; PUT carries a resourceVersion
# precondition, so a replay of an applied update is a benign 409.
# POST (create) and PATCH (no precondition in general) are NOT replayed.
_AMBIGUOUS_STATUS = (500, 502, 504)
_REPLAY_SAFE = frozenset({"GET", "PUT", "DELETE"})

# kind → (plural, cluster_scoped). CRDs registered by our operators are
# included so no discovery round-trip is needed for the common path.
_KINDS: dict[str, tuple[str, bool]] = {
    "Pod": ("pods", False),
    "Service": ("services", False),
    "Endpoints": ("endpoints", False),
    "Event": ("events", False),
    "Namespace": ("namespaces", True),
    "Node": ("nodes", True),
    "ConfigMap": ("configmaps", False),
    "Secret": ("secrets", False),
    "ServiceAccount": ("serviceaccounts", False),
    "PersistentVolumeClaim": ("persistentvolumeclaims", False),
    "ResourceQuota": ("resourcequotas", False),
    "Deployment": ("deployments", False),
    "StatefulSet": ("statefulsets", False),
    "Role": ("roles", False),
    "RoleBinding": ("rolebindings", False),
    "ClusterRole": ("clusterroles", True),
    "ClusterRoleBinding": ("clusterrolebindings", True),
    "StorageClass": ("storageclasses", True),
    "CustomResourceDefinition": ("customresourcedefinitions", True),
    "MutatingWebhookConfiguration": ("mutatingwebhookconfigurations", True),
    "Lease": ("leases", False),
    "VirtualService": ("virtualservices", False),
    "Gateway": ("gateways", False),
    # kubeflow_tpu CRDs
    "JAXJob": ("jaxjobs", False),
    "Notebook": ("notebooks", False),
    "Profile": ("profiles", True),
    "Tensorboard": ("tensorboards", False),
    "PodDefault": ("poddefaults", False),
    "StudyJob": ("studyjobs", False),
    "TpuDef": ("tpudefs", True),
}


def plural_of(kind: str) -> tuple[str, bool]:
    if kind in _KINDS:
        return _KINDS[kind]
    p = kind.lower()
    p = p + "es" if p.endswith(("s", "x", "ch")) else p[:-1] + "ies" if p.endswith("y") else p + "s"
    return p, False


def _label_selector_str(sel: dict | str | None) -> str | None:
    if sel is None or isinstance(sel, str):
        return sel
    parts = [f"{k}={v}" for k, v in (sel.get("matchLabels") or {}).items()]
    for e in sel.get("matchExpressions") or []:
        if e["operator"] == "Exists":
            parts.append(e["key"])
        elif e["operator"] == "In" and len(e.get("values", [])) == 1:
            parts.append(f"{e['key']}={e['values'][0]}")
        elif e["operator"] == "NotIn" and len(e.get("values", [])) == 1:
            parts.append(f"{e['key']}!={e['values'][0]}")
        else:
            raise ob.Invalid("string selectors support only single-value In/NotIn/Exists")
    return ",".join(parts)


class RestClient:
    def __init__(
        self,
        base_url: str | None = None,
        token: str | None = None,
        ca_cert: str | bool | None = None,
        namespace: str | None = None,
        max_retries: int = 4,
        retry_base: float = 0.1,
        retry_cap: float = 2.0,
        rng: random.Random | None = None,
    ):
        import requests

        if base_url is None:  # in-cluster config (rest.InClusterConfig analogue)
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
            tok_path = os.path.join(SA_DIR, "token")
            if token is None and os.path.exists(tok_path):
                token = open(tok_path).read().strip()
            ca_path = os.path.join(SA_DIR, "ca.crt")
            if ca_cert is None and os.path.exists(ca_path):
                ca_cert = ca_path
            ns_path = os.path.join(SA_DIR, "namespace")
            if namespace is None and os.path.exists(ns_path):
                namespace = open(ns_path).read().strip()
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace or "default"
        # transient-fault retry policy (client-go's rest.Request retries
        # 429/5xx the same way); _sleep/_rng injectable so tests pin the
        # schedule against a fake clock instead of actually sleeping
        self.max_retries = max_retries
        self.retry_base = retry_base
        self.retry_cap = retry_cap
        # seeded by default (DET discipline): the jitter schedule is
        # replayable unless a caller injects entropy on purpose
        self._sleep = time.sleep
        self._rng = rng if rng is not None else random.Random(0)
        self._s = requests.Session()
        if token:
            self._s.headers["Authorization"] = f"Bearer {token}"
        self._s.verify = ca_cert if ca_cert is not None else False
        # eager (not lazy-on-first-event): two worker threads racing a
        # lazy init would build two recorders with split dedup maps
        from kubeflow_tpu.obs.events import EventRecorder

        self._event_recorder = EventRecorder(self)

    # -- path construction --------------------------------------------------

    def _path(self, api_version: str, kind: str, namespace: str | None, name: str | None) -> str:
        prefix = "/api/v1" if api_version == "v1" else f"/apis/{api_version}"
        plural, cluster_scoped = plural_of(kind)
        parts = [prefix]
        if not cluster_scoped and namespace:
            parts += ["namespaces", namespace]
        parts.append(plural)
        if name:
            parts.append(name)
        return "/".join(parts)

    def _backoff(self, attempt: int, retry_after: str | None) -> float:
        """Capped exponential backoff with full jitter; a parseable
        Retry-After (seconds form) raises the floor — the server knows
        better than our schedule when it will be ready."""
        delay = min(self.retry_cap, self.retry_base * (2 ** attempt))
        delay *= self._rng.uniform(0.5, 1.5)
        if retry_after:
            try:
                delay = max(delay, float(retry_after))
            except ValueError:
                pass  # HTTP-date form: keep the computed backoff
        return delay

    def _req(self, method: str, path: str, **kw) -> Any:
        """One verb against the apiserver, with transient-fault retries.

        Retry matrix (see _REFUSED_STATUS/_AMBIGUOUS_STATUS above): a
        429/503 response is an explicit refusal — the mutation was not
        applied, so every verb retries, honoring Retry-After. 5xx
        responses and connection-level errors are ambiguous (the write
        may have landed), so only replay-safe verbs (GET/PUT/DELETE)
        retry; POST/PATCH surface the error to the reconcile loop,
        whose level-triggered retry re-reads before re-writing."""
        attempt = 0
        while True:
            try:
                r = self._s.request(
                    method, self.base_url + path, timeout=30, **kw)
            except Exception as e:
                if method in _REPLAY_SAFE and attempt < self.max_retries:
                    delay = self._backoff(attempt, None)
                    log.warning("%s %s: connection error (%s); retry %d/%d "
                                "in %.2fs", method, path, e, attempt + 1,
                                self.max_retries, delay)
                    self._sleep(delay)
                    attempt += 1
                    continue
                raise
            code = r.status_code
            retryable = (
                code in _REFUSED_STATUS
                or (code in _AMBIGUOUS_STATUS and method in _REPLAY_SAFE))
            if retryable and attempt < self.max_retries:
                delay = self._backoff(attempt, r.headers.get("Retry-After"))
                log.warning("%s %s: HTTP %d; retry %d/%d in %.2fs",
                            method, path, code, attempt + 1,
                            self.max_retries, delay)
                r.close()
                self._sleep(delay)
                attempt += 1
                continue
            break

        def errtext() -> str:
            # surface the Status message (client-go behavior) — the
            # actionable part of e.g. an SSA conflict is its tail, which
            # raw-body truncation would cut. Non-dict JSON bodies (a
            # proxy's bare string/null) fall back to raw text.
            try:
                doc = r.json()
            except ValueError:
                doc = None
            if isinstance(doc, dict) and doc.get("message"):
                return doc["message"]
            return r.text[:300]

        if r.status_code == 404:
            raise ob.NotFound(f"{method} {path}: {errtext()}")
        if r.status_code == 409:
            raise ob.Conflict(f"{method} {path}: {errtext()}")
        if r.status_code == 422:
            raise ob.Invalid(f"{method} {path}: {errtext()}")
        if r.status_code >= 400:
            err = ob.ApiError(f"{method} {path}: HTTP {r.status_code}: {r.text[:500]}")
            err.code = r.status_code
            raise err
        return r.json() if r.content else None

    # -- Client verbs -------------------------------------------------------

    def create(self, obj: dict) -> dict:
        m = ob.meta(obj)
        path = self._path(obj["apiVersion"], obj["kind"], m.get("namespace"), None)
        return self._req("POST", path, json=obj)

    def get(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> dict:
        return self._req("GET", self._path(api_version, kind, namespace, name))

    def get_or_none(self, api_version: str, kind: str, name: str, namespace: str | None = None):
        try:
            return self.get(api_version, kind, name, namespace)
        except ob.NotFound:
            return None

    # client-go's default list chunk size; page N+1 is fetched with the
    # server's continue token so large collections never need one
    # monolithic response
    list_chunk = 500

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: str | None = None,
        label_selector: dict | str | None = None,
        field_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        params: dict[str, str] = {}
        sel = _label_selector_str(label_selector)
        if sel:
            params["labelSelector"] = sel
        if field_selector:
            params["fieldSelector"] = ",".join(f"{k}={v}" for k, v in field_selector.items())
        items, _rv = self._list_chunked(api_version, kind, namespace, params)
        return items

    def _list_chunked(
        self, api_version: str, kind: str, namespace: str | None,
        params: dict[str, str],
    ) -> tuple[list[dict], str]:
        """Follow limit/continue pages; returns (items, list rv). A 410
        on a continue token (snapshot expired mid-pagination) restarts
        the list from scratch, as client-go does."""
        params = dict(params)
        if self.list_chunk:
            params["limit"] = str(self.list_chunk)
        path = self._path(api_version, kind, namespace, None)
        items: list[dict] = []
        rv = ""
        while True:
            try:
                out = self._req("GET", path, params=params)
            except ob.ApiError as e:
                if getattr(e, "code", None) == 410 and "continue" in params:
                    params.pop("continue")
                    items = []
                    continue
                raise
            items.extend(out.get("items", []))
            meta = out.get("metadata") or {}
            rv = meta.get("resourceVersion", rv)
            cont = meta.get("continue", "")
            if not cont:
                break
            params["continue"] = cont
        for it in items:  # apiserver omits these on list items
            it.setdefault("apiVersion", api_version)
            it.setdefault("kind", kind)
        return items, rv

    def update(self, obj: dict) -> dict:
        m = ob.meta(obj)
        path = self._path(obj["apiVersion"], obj["kind"], m.get("namespace"), m["name"])
        return self._req("PUT", path, json=obj)

    def update_status(self, obj: dict) -> dict:
        m = ob.meta(obj)
        path = self._path(obj["apiVersion"], obj["kind"], m.get("namespace"), m["name"]) + "/status"
        return self._req("PUT", path, json=obj)

    def patch(
        self,
        api_version: str,
        kind: str,
        name: str,
        patch: dict | list,
        namespace: str | None = None,
    ) -> dict:
        path = self._path(api_version, kind, namespace, name)
        ctype = (
            "application/json-patch+json"
            if isinstance(patch, list)
            else "application/merge-patch+json"
        )
        return self._req(
            "PATCH", path, data=json.dumps(patch), headers={"Content-Type": ctype}
        )

    def apply(self, obj: dict, *, field_manager: str,
              force: bool = False) -> dict:
        """Server-side apply: PATCH the manager's full intent with the
        apply-patch content type. Conflicting fields owned by another
        manager raise Conflict (409) unless force=True transfers
        ownership. Same signature as FakeCluster.apply, so controllers
        written against either backend can declare state identically."""
        m = ob.meta(obj)
        path = self._path(obj["apiVersion"], obj["kind"],
                          m.get("namespace"), m["name"])
        params = {"fieldManager": field_manager}
        if force:
            params["force"] = "true"
        return self._req(
            "PATCH", path, params=params, data=json.dumps(obj),
            headers={"Content-Type": "application/apply-patch+yaml"})

    def delete(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> None:
        self._req("DELETE", self._path(api_version, kind, namespace, name))

    def record_event(
        self,
        involved: dict,
        reason: str,
        message: str,
        etype: str = "Normal",
        component: str = "kubeflow-tpu",
    ) -> dict:
        """Same EventRecorder (count-dedup) as FakeCluster.record_event —
        controllers get identical event semantics on either backend."""
        return self._event_recorder.event(involved, reason, message, etype,
                                          component=component)

    def watch(self, api_version: str, kind: str, namespace: str | None = None):
        """Streaming watch (chunked JSON lines), reconnecting on EOF."""
        return _RestWatchStream(self, api_version, kind, namespace)


class _RestWatchStream:
    """Reconnecting watch with the conformance behaviors controllers rely
    on against a real apiserver (notebook_controller.go:519-613's informer
    machinery provides the same): resume-from-resourceVersion after a
    dropped connection, BOOKMARK heartbeats so the resume point advances
    on idle streams, and 410 Gone -> relist. The relist re-yields every
    live object as MODIFIED (a resync for level-triggered reconcilers)
    and — informer-style — synthesizes DELETED for objects this stream
    had seen that vanished during the gap (objects that existed before
    the stream started are outside its view, as with any watch-from-now)."""

    def __init__(self, client: RestClient, api_version: str, kind: str, namespace: str | None):
        self._c = client
        self._args = (api_version, kind, namespace)
        self._closed = False
        # last-known FULL object per (ns, name) this stream has yielded
        # and not seen deleted — the informer store the 410 relist diffs
        # against. Synthesized DELETED events must carry the full last
        # state (labels, ownerReferences): owner/label mappers in the
        # controllers read them, and a bare {name} event would be
        # silently dropped (client-go's DeletedFinalStateUnknown exists
        # for exactly this).
        self._known: dict[tuple[str, str], dict] = {}

    @staticmethod
    def _key(obj: dict) -> tuple[str, str]:
        m = ob.meta(obj)
        return (m.get("namespace") or "", m.get("name") or "")

    def _relist(self):
        from kubeflow_tpu.control.k8s.fake import WatchEvent

        api_version, kind, namespace = self._args
        items, rv = self._c._list_chunked(api_version, kind, namespace, {})
        live: dict[tuple[str, str], dict] = {}
        for it in items:
            live[self._key(it)] = it
            yield WatchEvent("MODIFIED", it)
        for key, last_state in self._known.items():
            if key not in live:
                yield WatchEvent("DELETED", last_state)
        self._known = live
        return rv

    def __iter__(self):
        from kubeflow_tpu.control.k8s.fake import WatchEvent

        api_version, kind, namespace = self._args
        rv = ""
        while not self._closed:
            params = {"watch": "1", "allowWatchBookmarks": "true"}
            if rv:
                params["resourceVersion"] = rv
            path = self._c._path(api_version, kind, namespace, None)
            try:
                r = self._c._s.get(
                    self._c.base_url + path, params=params, stream=True,
                    timeout=300)
            except Exception:
                if self._closed:
                    return
                time.sleep(0.2)
                continue
            if r.status_code == 410:
                # our resume point predates the server's watch cache:
                # relist and resume from the fresh list's RV. A failed
                # relist must not kill the stream — retry (the next
                # reconnect 410s again and lands back here).
                r.close()
                try:
                    gen = self._relist()
                    while True:
                        try:
                            yield next(gen)
                        except StopIteration as fin:
                            rv = fin.value or ""
                            break
                except ob.ApiError:
                    time.sleep(0.2)
                continue
            try:
                for line in r.iter_lines():
                    if self._closed:
                        return
                    if not line:
                        continue
                    ev = json.loads(line)
                    obj = ev.get("object", {})
                    etype = ev.get("type")
                    rv = ob.meta(obj).get("resourceVersion", rv)
                    if etype == "BOOKMARK":
                        continue
                    if etype in ("ADDED", "MODIFIED"):
                        self._known[self._key(obj)] = obj
                    elif etype == "DELETED":
                        self._known.pop(self._key(obj), None)
                    if etype in ("ADDED", "MODIFIED", "DELETED"):
                        yield WatchEvent(etype, obj)
            except Exception:
                if self._closed:
                    return
            finally:
                r.close()

    def stop(self):
        self._closed = True
