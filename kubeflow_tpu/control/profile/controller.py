"""Profile controller: namespace-per-user multi-tenancy.

Reconcile mirrors profile_controller.go:100-279:
- Namespace create with istio-injection label + ownership conflict
  rejection (:122-186),
- default-editor / default-viewer ServiceAccounts (:199-212),
- namespaceAdmin RoleBinding for the owner (:218-239),
- ResourceQuota `kf-resource-quota` (:241-254) — TPU chips first-class,
- plugin dispatch (:257; Plugin interface :74-80) with Revoke on the
  deletion finalizer path (:48).

Istio ServiceRole/Binding from the reference's 2019-era istio-rbac API is
represented by AuthorizationPolicy-shaped unstructured objects (the
modern surface), keeping the same capability: only in-namespace principals
+ the owner reach the namespace workloads.
"""

from __future__ import annotations

import logging
from typing import Protocol

from kubeflow_tpu.control import reconcilehelper as rh
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.profile import types as T
from kubeflow_tpu.control.runtime import Controller, Reconciler, Request, Result

log = logging.getLogger("kubeflow_tpu.profile")


class Plugin(Protocol):
    """profile_controller.go:74-80."""

    def apply(self, client, profile: dict) -> None: ...

    def revoke(self, client, profile: dict) -> None: ...


def plugin_spec_field(profile: dict, kind: str, field: str) -> str | None:
    """Extract one field from the profile's plugin spec of the given kind
    (shared by all cloud-credential plugins)."""
    for p in (profile.get("spec") or {}).get("plugins") or []:
        if p.get("kind") == kind:
            return (p.get("spec") or {}).get(field)
    return None


class WorkloadIdentityPlugin:
    """GCP Workload Identity binding (plugin_workload_identity.go:32-156).

    Cloud IAM calls are delegated to an injectable ``iam`` backend (the
    reference holds a live google IAM client); the in-cluster half —
    annotating default-editor with the GSA — is real.
    """

    KIND = "WorkloadIdentity"
    ANNOTATION = "iam.gke.io/gcp-service-account"  # :32-36

    def __init__(self, iam_backend=None):
        self.iam = iam_backend  # .bind(gsa, ksa), .unbind(gsa, ksa)

    def _gsa(self, profile: dict) -> str | None:
        return plugin_spec_field(profile, self.KIND, "gcpServiceAccount")

    def apply(self, client, profile: dict) -> None:
        gsa = self._gsa(profile)
        if not gsa:
            return
        ns = ob.meta(profile)["name"]
        sa = client.get_or_none("v1", "ServiceAccount", T.SA_EDITOR, ns)
        if sa is None:
            return
        ob.set_annotation(sa, self.ANNOTATION, gsa)
        client.update(sa)
        if self.iam:
            self.iam.bind(gsa, f"{ns}/{T.SA_EDITOR}")

    def revoke(self, client, profile: dict) -> None:
        gsa = self._gsa(profile)
        if gsa and self.iam:
            self.iam.unbind(gsa, f"{ob.meta(profile)['name']}/{T.SA_EDITOR}")


class ProfileReconciler(Reconciler):
    def __init__(self, plugins: dict[str, Plugin] | None = None):
        self.plugins = plugins or {}

    # -- generators ---------------------------------------------------------

    def generate_namespace(self, profile: dict) -> dict:
        name = ob.meta(profile)["name"]
        owner = T.owner_name(profile) or ""
        return ob.new_object(
            "v1", "Namespace", name,
            labels={
                "istio-injection": "enabled",  # :131
                "app.kubernetes.io/part-of": "kubeflow-profile",
            },
            annotations={"owner": owner},
        )

    def generate_service_accounts(self, profile: dict) -> list[dict]:
        ns = ob.meta(profile)["name"]
        return [
            ob.new_object("v1", "ServiceAccount", T.SA_EDITOR, ns),
            ob.new_object("v1", "ServiceAccount", T.SA_VIEWER, ns),
        ]

    def generate_sa_rolebindings(self, profile: dict) -> list[dict]:
        """Bind the namespace SAs to kubeflow-edit/view ClusterRoles
        (:199-212)."""
        ns = ob.meta(profile)["name"]
        out = []
        for sa, role in ((T.SA_EDITOR, T.EDIT_CLUSTER_ROLE),
                         (T.SA_VIEWER, T.VIEW_CLUSTER_ROLE)):
            rb = ob.new_object(
                "rbac.authorization.k8s.io/v1", "RoleBinding", sa, ns,
                annotations={T.ANNO_ROLE: role.split("-")[-1]},
            )
            rb["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                            "kind": "ClusterRole", "name": role}
            rb["subjects"] = [{"kind": "ServiceAccount", "name": sa, "namespace": ns}]
            out.append(rb)
        return out

    def generate_owner_rolebinding(self, profile: dict) -> dict:
        """namespaceAdmin (:218-239): owner -> kubeflow-admin."""
        ns = ob.meta(profile)["name"]
        owner = T.owner_name(profile) or ""
        rb = ob.new_object(
            "rbac.authorization.k8s.io/v1", "RoleBinding", "namespaceAdmin", ns,
            annotations={T.ANNO_USER: owner, T.ANNO_ROLE: "admin"},
        )
        rb["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                        "kind": "ClusterRole", "name": T.ADMIN_CLUSTER_ROLE}
        rb["subjects"] = [{"apiGroup": "rbac.authorization.k8s.io",
                          "kind": "User", "name": owner}]
        return rb

    def generate_quota(self, profile: dict) -> dict | None:
        spec = (profile.get("spec") or {}).get("resourceQuotaSpec")
        if not spec or not spec.get("hard"):
            return None
        ns = ob.meta(profile)["name"]
        return ob.new_object("v1", "ResourceQuota", T.QUOTA_NAME, ns, spec=spec)

    def generate_authz_policy(self, profile: dict) -> dict:
        """The istio-rbac ServiceRole/Binding capability (:190) expressed
        as one AuthorizationPolicy: allow the owner + in-ns principals."""
        ns = ob.meta(profile)["name"]
        owner = T.owner_name(profile) or ""
        pol = ob.new_object(
            "security.istio.io/v1beta1", "AuthorizationPolicy", "ns-owner-access", ns,
            annotations={T.ANNO_USER: owner, T.ANNO_ROLE: "admin"},
            spec={
                "rules": [
                    {"when": [{"key": "request.headers[kubeflow-userid]",
                               "values": [owner]}]},
                    {"from": [{"source": {"namespaces": [ns]}}]},
                ]
            },
        )
        return pol

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, client, req: Request) -> Result | None:
        profile = client.get_or_none(T.API_VERSION, T.KIND, req.name)
        if profile is None:
            return None
        m = ob.meta(profile)

        if m.get("deletionTimestamp"):
            return self._finalize(client, profile)

        if T.FINALIZER not in (m.get("finalizers") or []):
            m.setdefault("finalizers", []).append(T.FINALIZER)
            profile = client.update(profile)

        # namespace, with ownership conflict rejection (:168-186)
        ns_name = m["name"]
        existing = client.get_or_none("v1", "Namespace", ns_name)
        owner = T.owner_name(profile) or ""
        if existing is not None:
            anno_owner = ob.annotations_of(existing).get("owner")
            owned_by_us = any(
                r.get("uid") == m.get("uid")
                for r in ob.meta(existing).get("ownerReferences") or []
            )
            if not owned_by_us and anno_owner not in (None, "", owner):
                ob.cond_set(profile, "Ready", "False", "NamespaceOwnershipConflict",
                            f"namespace {ns_name} owned by {anno_owner}")
                client.update_status(profile)
                return None
        rh.reconcile_child(client, profile, self.generate_namespace(profile))

        for sa in self.generate_service_accounts(profile):
            rh.reconcile_child(client, profile, sa)
        for rb in self.generate_sa_rolebindings(profile):
            rh.reconcile_child(client, profile, rb)
        rh.reconcile_child(client, profile, self.generate_owner_rolebinding(profile))
        rh.reconcile_child(client, profile, self.generate_authz_policy(profile))
        quota = self.generate_quota(profile)
        if quota is not None:
            rh.reconcile_child(client, profile, quota)

        for p in (profile.get("spec") or {}).get("plugins") or []:
            plugin = self.plugins.get(p.get("kind"))
            if plugin:
                plugin.apply(client, profile)
            else:
                log.warning("unknown profile plugin %s", p.get("kind"))

        ob.cond_set(profile, "Ready", "True", "ProfileReady")
        client.update_status(profile)
        return None

    def _finalize(self, client, profile: dict) -> None:
        for p in (profile.get("spec") or {}).get("plugins") or []:
            plugin = self.plugins.get(p.get("kind"))
            if plugin:
                plugin.revoke(client, profile)
        client.remove_finalizer(profile, T.FINALIZER)
        return None


def build_controller(client, plugins: dict[str, Plugin] | None = None) -> Controller:
    rec = ProfileReconciler(plugins=plugins)
    ctl = Controller("profile", client, rec)
    ctl.watches_primary(T.API_VERSION, T.KIND)
    ctl.owns("v1", "Namespace")
    return ctl
