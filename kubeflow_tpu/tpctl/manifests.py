"""Platform manifest renderer (the ksonnet/kustomize package registry
equivalent, in code).

The reference shipped its components as ksonnet packages in an external
registry (bootstrap/image_registries.yaml:5-10 — absent from the
snapshot) and later kustomize; each component also carries self-deploy
manifests (e.g. bootstrap/kustomize/*). Here every component of THIS
framework renders as plain dict objects from one place, with
kustomize-style overlay patches applied last — so `tpctl generate` is
the whole registry.
"""

from __future__ import annotations

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.tpctl.tpudef import TpuDef


def _deployment(name: str, ns: str, image: str, *, args: list[str] | None = None,
                env: dict[str, str] | None = None, port: int | None = None,
                sa: str | None = None, replicas: int = 1) -> dict:
    container: dict = {"name": name, "image": image}
    if args:
        container["args"] = args
    if env:
        container["env"] = [{"name": k, "value": v} for k, v in sorted(env.items())]
    if port:
        container["ports"] = [{"containerPort": port}]
    pod_spec: dict = {"containers": [container]}
    if sa:
        pod_spec["serviceAccountName"] = sa
    return ob.new_object(
        "apps/v1", "Deployment", name, ns,
        labels={"app": name, "app.kubernetes.io/part-of": "kubeflow-tpu"},
        spec={
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {"metadata": {"labels": {"app": name}},
                         "spec": pod_spec},
        },
    )


def _service(name: str, ns: str, port: int, target: int,
             scheme: str = "http") -> dict:
    # the port-name prefix drives Istio protocol selection: a TLS backend
    # behind an 'http-' port would have its ClientHello parsed as
    # plaintext by a mesh sidecar
    return ob.new_object(
        "v1", "Service", name, ns,
        spec={"selector": {"app": name},
              "ports": [{"name": f"{scheme}-{name}", "port": port,
                         "targetPort": target}]},
    )


def _webapps_virtualservice(ns: str, prefixes: dict[str, str]) -> dict:
    """ONE gateway VirtualService carrying every web-app prefix route,
    most-specific first. A single VS (vs one per app) because Istio's
    merge order across VirtualServices on the same host is
    non-deterministic — a separate '/' catch-all could shadow /jupyter/.
    Per-resource routes (/notebook/<ns>/<name>/) are added by the
    controllers, not here."""
    http = []
    for name, prefix in sorted(prefixes.items(),
                               key=lambda kv: -len(kv[1])):
        if prefix == "/":
            # the dashboard must NOT get a '/' prefix catch-all: the
            # notebook/tensorboard controllers create per-resource
            # VirtualServices (/notebook/<ns>/<name>/) on the same host,
            # and Istio's cross-VS merge order could let a catch-all
            # shadow them. Enumerate the dashboard's own surfaces
            # instead; unknown paths 404 at the gateway, deterministically.
            match = [{"uri": {"exact": "/"}},
                     {"uri": {"prefix": "/dashboard"}},
                     {"uri": {"prefix": "/api/"}}]
        else:
            match = [{"uri": {"prefix": prefix}}]
        rule: dict = {
            "match": match,
            "route": [{"destination": {
                "host": f"{name}.{ns}.svc.cluster.local",
                "port": {"number": 80}}}],
        }
        if prefix != "/":
            # apps are served at their own root; strip the gateway prefix
            rule["rewrite"] = {"uri": "/"}
        http.append(rule)
    return ob.new_object(
        "networking.istio.io/v1alpha3", "VirtualService",
        "kubeflow-webapps", ns,
        spec={"hosts": ["*"], "gateways": ["kubeflow/kubeflow-gateway"],
              "http": http},
    )


def _clusterrole(name: str, rules: list[dict]) -> dict:
    cr = ob.new_object("rbac.authorization.k8s.io/v1", "ClusterRole", name)
    cr["rules"] = rules
    return cr


def render(cfg: TpuDef) -> list[dict]:
    """All manifests for the selected applications, in apply order."""
    ns = cfg.namespace
    img = lambda c: f"{cfg.image_prefix}/{c}:latest"  # noqa: E731
    out: list[dict] = []
    apps = set(cfg.applications)

    if "crds" in apps:
        from kubeflow_tpu.control.jaxjob import types as JT
        from kubeflow_tpu.control.jaxservice import types as ST
        from kubeflow_tpu.control.notebook import types as NT
        from kubeflow_tpu.control.poddefault import webhook as PW
        from kubeflow_tpu.control.profile import types as PT
        from kubeflow_tpu.control.tensorboard import controller as TB
        from kubeflow_tpu.tune import studyjob as SJ

        out += [JT.crd_manifest(), ST.crd_manifest(), NT.crd_manifest(),
                PT.crd_manifest(), PW.crd_manifest(), TB.crd_manifest(),
                SJ.crd_manifest()]

    if "namespace" in apps:
        out.append(ob.new_object(
            "v1", "Namespace", ns,
            labels={"istio-injection": "enabled" if cfg.use_istio else "disabled"}))

    if "rbac" in apps:
        # the kubeflow-{admin,edit,view} ClusterRoles the profile
        # controller and KFAM bind to (profile_controller.go:58-62)
        every = [{"apiGroups": ["*"], "resources": ["*"], "verbs": ["*"]}]
        ro = [{"apiGroups": ["*"], "resources": ["*"],
               "verbs": ["get", "list", "watch"]}]
        out += [
            _clusterrole("kubeflow-admin", every),
            _clusterrole("kubeflow-edit", [
                {"apiGroups": ["", "apps", "kubeflow.org",
                               "tensorboard.kubeflow.org"],
                 "resources": ["*"], "verbs": ["*"]}]),
            _clusterrole("kubeflow-view", ro),
            ob.new_object("v1", "ServiceAccount", "kubeflow-controller", ns),
        ]
        crb = ob.new_object("rbac.authorization.k8s.io/v1", "ClusterRoleBinding",
                            "kubeflow-controller-admin")
        crb["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                          "kind": "ClusterRole", "name": "kubeflow-admin"}
        crb["subjects"] = [{"kind": "ServiceAccount",
                            "name": "kubeflow-controller", "namespace": ns}]
        out.append(crb)

    controllers = {
        "jaxjob-controller": ["python", "-m", "kubeflow_tpu.control.jaxjob"],
        "gang-scheduler": ["python", "-m", "kubeflow_tpu.control.scheduler"],
        "jaxservice-controller": ["python", "-m",
                                  "kubeflow_tpu.control.jaxservice"],
        "notebook-controller": ["python", "-m", "kubeflow_tpu.control.notebook"],
        "profile-controller": ["python", "-m", "kubeflow_tpu.control.profile"],
        "tensorboard-controller": ["python", "-m", "kubeflow_tpu.control.tensorboard"],
    }
    for name, cmd in controllers.items():
        if name not in apps:
            continue
        env = {"USE_ISTIO": str(cfg.use_istio).lower()}
        if name == "notebook-controller":
            env.update({"ENABLE_CULLING": "false", "CULL_IDLE_TIME": "1440"})
        replicas = 1
        if cfg.ha_controllers:
            # HA control plane: standby replica + Lease leader election
            # (--enable-leader-election parity, control/leases.py)
            env["ENABLE_LEADER_ELECTION"] = "true"
            env["POD_NAMESPACE"] = ns
            replicas = 2
        dep = _deployment(name, ns, img("controller"), args=cmd, env=env,
                          sa="kubeflow-controller")
        dep["spec"]["replicas"] = replicas
        out.append(dep)

    if "poddefault-webhook" in apps:
        # the apiserver only dials webhooks over verified HTTPS
        # (admission-webhook/main.go:541-542). Certs are NOT rendered here:
        # the pod self-bootstraps a CA + serving cert in its emptyDir at
        # startup and patches the live caBundle into this registration
        # (webhook.py publish_ca_bundle) — keys never touch manifests, the
        # state repo, or the operator's machine (README.md:66 leaves
        # caBundle to out-of-band provisioning; ours is in-cluster).
        dep = _deployment(
            "poddefault-webhook", ns, img("controller"),
            args=["python", "-m", "kubeflow_tpu.control.poddefault"],
            env={"WEBHOOK_CERTS_DIR": "/etc/webhook/certs",
                 "POD_NAMESPACE": ns},
            port=4443, sa="kubeflow-controller")
        pod = dep["spec"]["template"]["spec"]
        pod["volumes"] = [{"name": "certs", "emptyDir": {}}]
        pod["containers"][0]["volumeMounts"] = [{
            "name": "certs", "mountPath": "/etc/webhook/certs"}]
        out.append(dep)
        out.append(_service("poddefault-webhook", ns, 443, 4443,
                            scheme="https"))
        hook = ob.new_object(
            "admissionregistration.k8s.io/v1", "MutatingWebhookConfiguration",
            "poddefault-webhook")
        hook["webhooks"] = [{
            "name": "poddefault.kubeflow.org",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "clientConfig": {
                "service": {"name": "poddefault-webhook", "namespace": ns,
                            "path": "/apply-poddefault", "port": 443},
                # patched by the pod once its CA exists; empty until then
                "caBundle": "",
            },
            "rules": [{"apiGroups": [""], "apiVersions": ["v1"],
                       "operations": ["CREATE"], "resources": ["pods"]}],
            "failurePolicy": "Ignore",
        }]
        out.append(hook)

    services = {
        "kfam": (["python", "-m", "kubeflow_tpu.control.kfam"], 8081),
        "gatekeeper": (["python", "-m", "kubeflow_tpu.control.gatekeeper"], 8085),
        "centraldashboard": (["python", "-m", "kubeflow_tpu.webapps.dashboard_main"], 8082),
        "jupyter-web-app": (["python", "-m", "kubeflow_tpu.webapps.jwa_main"], 5000),
        "tensorboards-web-app": (
            ["python", "-m", "kubeflow_tpu.webapps.tensorboards_main"], 5005),
        "serving": (["python", "-m", "kubeflow_tpu.serving"], 8500),
        "metric-collector": (["python", "-m", "kubeflow_tpu.metric_collector"], 8088),
    }
    # gateway route prefix per web app — the VirtualServices that make the
    # dashboard's iframe paths (/jupyter/, /tensorboards/) resolve through
    # the platform gateway (reference ships the same per-app VS routing;
    # without it the iframe tabs would 404 against the dashboard origin)
    app_prefixes = {
        "centraldashboard": "/",
        "jupyter-web-app": "/jupyter/",
        "tensorboards-web-app": "/tensorboards/",
    }
    for name, (cmd, port) in services.items():
        if name not in apps:
            continue
        out.append(_deployment(name, ns, img("platform"), args=cmd, port=port,
                               sa="kubeflow-controller"))
        out.append(_service(name, ns, 80, port))
    routed = {n: p for n, p in app_prefixes.items() if n in apps}
    if cfg.use_istio and routed:
        out.append(_webapps_virtualservice(ns, routed))

    for patch in cfg.overlays:
        _apply_overlay(out, patch)
    return out


def _apply_overlay(objs: list[dict], overlay: dict) -> None:
    """kustomize-style strategic-merge overlay: {target: {kind, name},
    patch: {...}} merged into every matching object."""
    target = overlay.get("target") or {}
    patch = overlay.get("patch") or {}
    for i, o in enumerate(objs):
        if target.get("kind") and o.get("kind") != target["kind"]:
            continue
        if target.get("name") and ob.meta(o).get("name") != target["name"]:
            continue
        objs[i] = ob.merge_patch(o, patch)
