"""Whole-program layer for tpulint: modules, classes, and a cross-module
call graph with lock-context propagation.

PR 1's lockset checker saw one file at a time, so a lock taken in
``control/runtime.py`` could not vouch for a helper in another module,
and lock-acquisition *order* was invisible entirely. This module builds
the program model the LOCK2xx/TPU10x whole-program rules share:

- ``Program``: every scanned ``Module`` plus per-module import tables,
  top-level classes (with their locks and container-evidence attrs,
  the same evidence LOCK201 has always used) and functions.
- Call sites: each ``ast.Call`` inside a top-level function/method is
  resolved — ``self.method``, ``self.attr.method`` (via constructor
  attribute-type inference), module-level and ``from``-imported
  functions, and parameters annotated with a program class — and
  annotated with the lock tokens lexically held at the site.
- ``locked_entry``: the bounded greatest-fixpoint generalization of
  LOCK201's per-class locked-context pass. A private function's entry
  context is the intersection over all known call sites of (locks held
  at the site + the caller's own entry context), pruned by an
  entry-point pass so mutually-recursive helpers never vouch for each
  other without a genuinely locked way in.
- ``may_held``: the union (any-path) analogue, feeding LOCK203's
  lock-acquisition-order graph.
- ``writes()`` / ``guarded_map()``: attribute writes program-wide —
  including writes through parameters of a known class (``def
  seed_controller(c: Controller): c._streams.append(...)``) — with the
  lock tokens protecting each, and the resulting per-class
  guarded-attribute map that both static LOCK201 and the dynamic
  happens-before validator (analysis/dyntrace.py) consume.

Everything is resolution-bounded: an unresolvable callee or receiver
simply contributes nothing, so the analysis degrades to PR 1's per-file
behavior on a single module and never guesses.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterable, Iterator

from kubeflow_tpu.analysis.core import Module, call_name, dotted

# Lock evidence (shared with rules_lockset): ctor assignment or a
# lock-ish `with self.X:` name. `with self.mesh:` (jax Mesh activation)
# must not count.
LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
              "Lock", "RLock", "Condition"}
LOCKISH = re.compile(r"lock|mutex|cond|(^|_)(mu|cv)$")
MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
            "clear", "update", "setdefault", "add", "discard"}
CONTAINER_CTORS = {"dict", "list", "set", "collections.defaultdict",
                   "defaultdict", "collections.OrderedDict", "OrderedDict",
                   "collections.deque", "deque", "queue.Queue", "Queue"}

_FIXPOINT_CAP = 32  # bounded-depth: iterations, not recursion

# A lock token: (class qualname "mod:Class", lock attribute name).
Token = tuple[str, str]


def receiver_attr(node: ast.AST, recv: str) -> str | None:
    """'X' when node is the attribute access ``<recv>.X``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == recv):
        return node.attr
    return None


def receiver_attr_root(node: ast.AST, recv: str) -> str | None:
    """Root ``<recv>.X`` of a chain like ``recv.X[k]`` / ``recv.X.y[k]``."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        got = receiver_attr(node, recv)
        if got is not None:
            return got
        node = node.value
    return None


@dataclasses.dataclass
class FuncInfo:
    """A top-level function or method (nested defs belong to their
    enclosing FuncInfo; their bodies never outlive its analysis)."""

    qual: str                      # "mod:func" or "mod:Class.method"
    name: str
    node: ast.FunctionDef
    module: Module
    modname: str
    owner: "ClassInfo | None" = None
    # parameter name -> class qualname, for `self` and annotated params
    param_classes: dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def is_private(self) -> bool:
        return self.name.startswith("_") and not self.name.startswith("__")


@dataclasses.dataclass
class ClassInfo:
    qual: str                      # "mod:Class"
    name: str
    node: ast.ClassDef
    module: Module
    modname: str
    locks: set[str] = dataclasses.field(default_factory=set)
    containers: set[str] = dataclasses.field(default_factory=set)
    methods: dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    attr_classes: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CallSite:
    call: ast.Call
    caller: FuncInfo
    callee: str | None             # resolved FuncInfo qual, or None
    lex_held: frozenset[Token]     # tokens lexically held at the site


@dataclasses.dataclass
class WriteRec:
    """One attribute write, attributed to a program class."""

    class_qual: str
    attr: str
    node: ast.AST
    func: FuncInfo
    module: Module
    recv: str                      # receiver name at the write ("self", "c")
    # lock tokens of the OWNING class protecting this write (lexical +
    # the function's guaranteed entry context)
    tokens: frozenset[str]         # lock attr names of class_qual


def _find_locks(cls: ast.ClassDef) -> set[str]:
    locks: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.With):
            for item in node.items:
                attr = receiver_attr(item.context_expr, "self")
                if attr is not None and LOCKISH.search(attr):
                    locks.add(attr)
        elif isinstance(node, ast.Assign):
            if (isinstance(node.value, ast.Call)
                    and call_name(node.value) in LOCK_CTORS):
                for t in node.targets:
                    attr = receiver_attr(t, "self")
                    if attr is not None:
                        locks.add(attr)
    return locks


def _find_containers(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        is_container = isinstance(
            value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                    ast.ListComp, ast.SetComp)) or (
            isinstance(value, ast.Call)
            and call_name(value) in CONTAINER_CTORS)
        if not is_container:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            attr = receiver_attr(t, "self")
            if attr is not None:
                out.add(attr)
    return out


def _parse_imports(module: Module, modname: str) -> dict[str, tuple]:
    """Alias table: name -> ("mod", target) | ("sym", target, symbol)."""
    out: dict[str, tuple] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = (
                    ("mod", alias.name) if alias.asname
                    else ("mod", alias.name.split(".")[0]))
                if alias.asname is None and "." in alias.name:
                    # `import a.b.c` binds `a`, but calls spelled
                    # a.b.c.f() resolve through the full dotted prefix
                    out[alias.name] = ("mod", alias.name)
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                # relative import: strip `level` trailing components of
                # this module's dotted name, then append the target
                parts = modname.split(".")
                keep = parts[:max(len(parts) - node.level, 0)]
                base = ".".join(keep + ([node.module] if node.module else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = ("sym", base, alias.name)
    return out


class Program:
    """The whole-program model: modules, classes, functions, call graph."""

    def __init__(self, modules: dict[str, Module]):
        self.modules = modules                      # dotted name -> Module
        self.by_path = {m.path: m for m in modules.values()}
        self.imports: dict[str, dict[str, tuple]] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self._collect_defs()
        self._infer_param_classes()
        self._infer_attr_classes()
        self.calls: list[CallSite] = []
        self._collect_calls()
        self._locked_entry: dict[str, frozenset[Token]] | None = None
        self._may_held: dict[str, frozenset[Token]] | None = None
        self._writes: list[WriteRec] | None = None

    # -- construction --------------------------------------------------------

    def _collect_defs(self) -> None:
        for modname, module in self.modules.items():
            self.imports[modname] = _parse_imports(module, modname)
            for node in module.tree.body:
                if isinstance(node, ast.FunctionDef):
                    qual = f"{modname}:{node.name}"
                    self.functions[qual] = FuncInfo(
                        qual, node.name, node, module, modname)
                elif isinstance(node, ast.ClassDef):
                    cqual = f"{modname}:{node.name}"
                    info = ClassInfo(cqual, node.name, node, module, modname,
                                     locks=_find_locks(node),
                                     containers=_find_containers(node))
                    self.classes[cqual] = info
                    for sub in node.body:
                        if isinstance(sub, ast.FunctionDef):
                            fqual = f"{modname}:{node.name}.{sub.name}"
                            fi = FuncInfo(fqual, sub.name, sub, module,
                                          modname, owner=info)
                            info.methods[sub.name] = fi
                            self.functions[fqual] = fi

    def resolve_symbol(self, modname: str, name: str) -> str | None:
        """Resolve a bare or dotted name to a program class/function qual
        ("mod:Sym"), following one level of from-import indirection."""
        local = f"{modname}:{name.split('.')[0]}" if "." not in name else None
        if local and (local in self.classes or local in self.functions):
            return local
        table = self.imports.get(modname, {})
        head, _, rest = name.partition(".")
        got = table.get(name) or table.get(head)
        # longest-prefix match for `import a.b.c` style dotted calls
        if "." in name:
            parts = name.split(".")
            for cut in range(len(parts) - 1, 0, -1):
                prefix = ".".join(parts[:cut])
                if prefix in table and table[prefix][0] == "mod":
                    target, sym = table[prefix][1], ".".join(parts[cut:])
                    if "." in sym:
                        return None  # a.b.C.method etc.: out of scope
                    if target in self.modules:
                        q = f"{target}:{sym}"
                        if q in self.classes or q in self.functions:
                            return q
                    return None
        if got is None:
            return None
        if got[0] == "sym":
            _, target, sym = got
            if rest:                     # alias.attr: symbol of a symbol
                return None
            if target in self.modules:
                q = f"{target}:{sym}"
                if q in self.classes or q in self.functions:
                    return q
        elif got[0] == "mod" and rest:
            target = got[1]
            if target in self.modules and "." not in rest:
                q = f"{target}:{rest}"
                if q in self.classes or q in self.functions:
                    return q
        return None

    def _annotation_class(self, fi_mod: str, ann: ast.expr | None) -> str | None:
        if ann is None:
            return None
        name = dotted(ann) or (
            ann.value if isinstance(ann, ast.Constant)
            and isinstance(ann.value, str) else None)
        if not name:
            return None
        got = self.resolve_symbol(fi_mod, name)
        return got if got in self.classes else None

    def _infer_param_classes(self) -> None:
        for fi in self.functions.values():
            args = fi.node.args
            params = args.posonlyargs + args.args + args.kwonlyargs
            for i, a in enumerate(params):
                if fi.owner is not None and i == 0 and a.arg in ("self", "cls"):
                    if a.arg == "self":
                        fi.param_classes["self"] = fi.owner.qual
                    continue
                got = self._annotation_class(fi.modname, a.annotation)
                if got:
                    fi.param_classes[a.arg] = got

    def _infer_attr_classes(self) -> None:
        """``self.x = ClassName(...)`` pins attr x to a program class, so
        ``self.x.method()`` calls resolve across modules."""
        for cls in self.classes.values():
            for node in ast.walk(cls.node):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                name = call_name(node.value)
                target = self.resolve_symbol(cls.modname, name) if name else None
                if target not in self.classes:
                    continue
                for t in node.targets:
                    attr = receiver_attr(t, "self")
                    if attr is not None:
                        cls.attr_classes[attr] = target

    # -- lexical lock context ------------------------------------------------

    def lex_tokens(self, node: ast.AST, fi: FuncInfo) -> frozenset[Token]:
        """Lock tokens held at `node` by `with <recv>.<lock>` blocks
        inside fi's own body. A nested def breaks the chain (its body
        runs at call time, not necessarily under the enclosing with)."""
        held: set[Token] = set()
        for anc in fi.module.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    tok = self._with_token(item.context_expr, fi)
                    if tok is not None:
                        held.add(tok)
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # reached fi.node or a nested def first
        return frozenset(held)

    def _with_token(self, expr: ast.expr, fi: FuncInfo) -> Token | None:
        if not isinstance(expr, ast.Attribute):
            return None
        if not isinstance(expr.value, ast.Name):
            return None
        recv = expr.value.id
        cqual = fi.param_classes.get(recv)
        if cqual is None:
            return None
        cls = self.classes[cqual]
        if expr.attr in cls.locks:
            return (cqual, expr.attr)
        return None

    # -- call graph ----------------------------------------------------------

    def _collect_calls(self) -> None:
        for fi in self.functions.values():
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    self.calls.append(CallSite(
                        node, fi, self._resolve_call(node, fi),
                        self.lex_tokens(node, fi)))
        self._sites_by_callee: dict[str, list[CallSite]] = {}
        for site in self.calls:
            if site.callee is not None:
                self._sites_by_callee.setdefault(site.callee, []).append(site)

    def _resolve_call(self, call: ast.Call, fi: FuncInfo) -> str | None:
        name = call_name(call)
        if name is None:
            return None
        parts = name.split(".")
        # recv.method / recv.attr.method where recv is self or a typed param
        if parts[0] in fi.param_classes:
            cls = self.classes[fi.param_classes[parts[0]]]
            if len(parts) == 2:
                m = cls.methods.get(parts[1])
                return m.qual if m else None
            if len(parts) == 3:
                target = cls.attr_classes.get(parts[1])
                if target:
                    m = self.classes[target].methods.get(parts[2])
                    return m.qual if m else None
            return None
        got = self.resolve_symbol(fi.modname, name)
        if got in self.functions:
            return got
        if got in self.classes:
            init = self.classes[got].methods.get("__init__")
            return init.qual if init else None
        return None

    # -- entry-context fixpoints ---------------------------------------------

    def locked_entry(self) -> dict[str, frozenset[Token]]:
        """Tokens guaranteed held whenever a private function runs.

        Greatest fixpoint over the call graph (TOP = "every token"),
        then an entry-point pruning pass: a token survives only if some
        call path actually acquires it lexically — otherwise two
        mutually-recursive helpers called from nowhere locked would
        vouch for each other (PR 1's two-pass shape, program-wide)."""
        if self._locked_entry is not None:
            return self._locked_entry
        TOP = None  # lattice top: unconstrained
        entry: dict[str, frozenset[Token] | None] = {}
        candidates = [q for q, fi in self.functions.items()
                      if fi.is_private and self._sites_by_callee.get(q)]
        for q in self.functions:
            entry[q] = TOP if q in candidates else frozenset()
        for _ in range(_FIXPOINT_CAP):
            changed = False
            for q in candidates:
                acc: frozenset[Token] | None = TOP
                for site in self._sites_by_callee[q]:
                    ctx = entry.get(site.caller.qual, frozenset())
                    here = (TOP if ctx is TOP
                            else frozenset(site.lex_held | ctx))
                    if here is TOP:
                        continue
                    acc = here if acc is TOP else (acc & here)
                if acc is not TOP and entry[q] != acc:
                    entry[q] = acc
                    changed = True
            if not changed:
                break
        # entry-point pass, per token
        entered: dict[str, set[Token]] = {q: set() for q in candidates}
        for _ in range(_FIXPOINT_CAP):
            changed = False
            for q in candidates:
                for site in self._sites_by_callee[q]:
                    new = set(site.lex_held)
                    new |= entered.get(site.caller.qual, set())
                    if not new <= entered[q]:
                        entered[q] |= new
                        changed = True
            if not changed:
                break
        out: dict[str, frozenset[Token]] = {}
        for q in self.functions:
            e = entry[q]
            if e is TOP:
                out[q] = frozenset(entered.get(q, set()))
            else:
                out[q] = frozenset(e & entered[q]) if q in entered else e
        self._locked_entry = out
        return out

    def may_held(self) -> dict[str, frozenset[Token]]:
        """Tokens possibly held on SOME path into each function — the
        any-path union dual of locked_entry, for lock-order edges."""
        if self._may_held is not None:
            return self._may_held
        may: dict[str, set[Token]] = {q: set() for q in self.functions}
        for _ in range(_FIXPOINT_CAP):
            changed = False
            for site in self.calls:
                if site.callee is None:
                    continue
                new = set(site.lex_held) | may.get(site.caller.qual, set())
                if not new <= may[site.callee]:
                    may[site.callee] |= new
                    changed = True
            if not changed:
                break
        self._may_held = {q: frozenset(s) for q, s in may.items()}
        return self._may_held

    # -- writes and the guarded map ------------------------------------------

    def writes(self) -> list[WriteRec]:
        """Every attribute write attributable to a program class, with
        the owning class's lock tokens protecting it."""
        if self._writes is not None:
            return self._writes
        entry = self.locked_entry()
        out: list[WriteRec] = []
        for fi in self.functions.values():
            roots = fi.param_classes
            if not roots:
                continue
            ctx = entry.get(fi.qual, frozenset())
            for node in ast.walk(fi.node):
                for recv, attr, loc in self._write_targets(node, roots):
                    cqual = roots[recv]
                    cls = self.classes[cqual]
                    if attr in cls.locks:
                        continue  # assigning the lock itself
                    if (isinstance(loc, ast.Call)
                            and attr not in cls.containers):
                        continue  # mutator call without container evidence
                    held = self.lex_tokens(loc, fi) | ctx
                    tokens = frozenset(a for (cq, a) in held if cq == cqual)
                    out.append(WriteRec(cqual, attr, loc, fi, fi.module,
                                        recv, tokens))
        self._writes = out
        return out

    @staticmethod
    def _write_targets(node: ast.AST, roots: dict[str, str]
                       ) -> Iterator[tuple[str, str, ast.AST]]:
        """(receiver, attr, report-node) triples for one AST node."""
        def root_of(e: ast.AST) -> tuple[str, str] | None:
            for recv in roots:
                a = receiver_attr(e, recv)
                if a is None and isinstance(e, (ast.Subscript, ast.Attribute)):
                    a = receiver_attr_root(e, recv)
                if a is not None:
                    return recv, a
            return None

        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    got = None
                    for recv in roots:
                        a = receiver_attr(e, recv)
                        if a is None and isinstance(e, ast.Subscript):
                            a = receiver_attr_root(e, recv)
                        if a is not None:
                            got = (recv, a)
                            break
                    if got:
                        yield got[0], got[1], e
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                got = root_of(t)
                if got:
                    yield got[0], got[1], t
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATORS):
            got = root_of(node.func.value)
            if got:
                yield got[0], got[1], node

    def guarded_map(self) -> dict[str, dict[str, tuple[str, int, frozenset[str]]]]:
        """Per class: attr -> (path and line of first locked write,
        intersection of lock attrs over all locked writes). Writes in
        ``__init__`` are exempt (construction happens-before
        publication)."""
        out: dict[str, dict[str, tuple[str, int, frozenset[str]]]] = {}
        for w in self.writes():
            if not w.tokens or w.func.name == "__init__":
                continue
            per = out.setdefault(w.class_qual, {})
            if w.attr in per:
                path, line, locks = per[w.attr]
                per[w.attr] = (path, line, locks & w.tokens)
            else:
                per[w.attr] = (w.module.path, w.node.lineno, w.tokens)
        return out

    # -- lock-order edges (LOCK203 input) ------------------------------------

    def lock_order_edges(self) -> list[tuple[Token, Token, ast.With, Module]]:
        """Directed acquisition edges (held -> acquired), combining
        lexical nesting with the any-path may_held context, so an
        acquisition reached through a call made under a lock still
        orders after that lock."""
        may = self.may_held()
        edges: list[tuple[Token, Token, ast.With, Module]] = []
        for fi in self.functions.values():
            if not fi.param_classes:
                continue
            ctx = may.get(fi.qual, frozenset())
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.With):
                    continue
                prior: set[Token] = set()  # earlier items of this With
                for item in node.items:
                    tok = self._with_token(item.context_expr, fi)
                    if tok is None:
                        continue
                    held = self.lex_tokens(node, fi) | ctx | prior
                    for h in held:
                        if h != tok:
                            edges.append((h, tok, node, fi.module))
                    prior.add(tok)
        return edges


# -- construction helpers ----------------------------------------------------

def module_name_for(path) -> str:
    """Dotted module name from the filesystem: walk up while the parent
    directory holds an ``__init__.py``; fall back to the file stem."""
    import pathlib

    p = pathlib.Path(path).resolve()
    parts = [p.stem] if p.name != "__init__.py" else []
    cur = p.parent
    while (cur / "__init__.py").exists():
        parts.insert(0, cur.name)
        cur = cur.parent
    return ".".join(parts) or p.stem


def build_program(modules: Iterable[Module]) -> Program:
    """Program over already-parsed Modules, keyed by dotted name (path
    stem collisions fall back to the path so nothing is dropped)."""
    table: dict[str, Module] = {}
    for m in modules:
        name = module_name_for(m.path)
        if name in table:
            name = m.path
        table[name] = m
    return Program(table)
