import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    MeshSpec,
    batch_sharding,
    build_mesh,
    local_batch_size,
    mesh_summary,
)


def test_default_mesh_all_data(devices8):
    mesh = build_mesh()
    assert mesh.shape[AXIS_DATA] == 8
    assert mesh.devices.size == 8


def test_mesh_spec_resolve():
    spec = MeshSpec(model=2, seq=2).resolve(8)
    assert spec.data == 2
    assert spec.model == 2 and spec.seq == 2


def test_mesh_spec_bad_divisibility():
    with pytest.raises(ValueError):
        MeshSpec(model=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=4, model=4).resolve(8)


def test_mesh_spec_from_dict_rejects_unknown():
    with pytest.raises(ValueError):
        MeshSpec.from_dict({"tensor": 2})


def test_build_mesh_2d(devices8):
    mesh = build_mesh(MeshSpec(data=2, model=4))
    assert mesh.shape[AXIS_DATA] == 2
    assert mesh.shape[AXIS_MODEL] == 4


def test_batch_sharding_puts_batch_on_data(devices8):
    mesh = build_mesh(MeshSpec(data=4, fsdp=2))
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    xs = jax.device_put(x, batch_sharding(mesh, extra_dims=1))
    # batch dim sharded over data*fsdp = 8
    assert xs.sharding.spec == P((AXIS_DATA, "fsdp"), None)
    np.testing.assert_array_equal(np.asarray(xs), x)


def test_local_batch_size(devices8):
    mesh = build_mesh(MeshSpec(data=4, fsdp=2))
    assert local_batch_size(mesh, 32) == 4
    with pytest.raises(ValueError):
        local_batch_size(mesh, 30)


def test_mesh_summary(devices8):
    s = mesh_summary(build_mesh(MeshSpec(data=8)))
    assert "data=8" in s
