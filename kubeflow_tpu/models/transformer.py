"""Decoder-only transformer LM — the flagship distributed workload.

The reference's "big" workloads are opaque TF payloads; its platform
capabilities (PS data-parallelism, MPI allreduce) cap out at data
parallelism (SURVEY.md §2.5). This model is where the TPU build goes
beyond: every weight carries a mesh-axis annotation, so one module
definition runs under any combination of

- data / fsdp  (batch + ZeRO-3 parameter sharding)
- model        (Megatron-style tensor parallelism: column-parallel up
                projections, row-parallel down projections — XLA inserts
                the psum on the row-parallel matmul output)
- seq          (sequence/context parallelism; long sequences route
                attention through ops.ring_attention over the ICI ring)
- pipe         (pipeline stages via parallel.pipeline.PipelinedTransformer)
- expert       (MoE blocks; ops.moe all-to-all dispatch)

Architecture: pre-RMSNorm, rotary embeddings, GQA, SwiGLU — the standard
modern decoder (Llama-class), in bf16 with f32 logits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.models.registry import register_model
from kubeflow_tpu.parallel.mesh import (
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEQ,
    BATCH_AXES,
)

Dtype = Any

# Activation sharding: batch over (dcn, data, fsdp), sequence over seq,
# features over model only where the tensor is the "wide" intermediate.
HIDDEN_SPEC = P(BATCH_AXES, AXIS_SEQ, None)
WIDE_SPEC = P(BATCH_AXES, AXIS_SEQ, AXIS_MODEL)


def shard(x: jax.Array, spec: P) -> jax.Array:
    from kubeflow_tpu.parallel.mesh import shard_constraint

    return shard_constraint(x, spec)


def _part(init, names):
    return nn.with_partitioning(init, names)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    n_kv_heads: int = 4
    head_dim: int = 64
    d_ff: int = 2048
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Dtype = jnp.bfloat16
    attention_impl: str = "auto"   # auto | flash | reference | ring | ulysses
    # Flash kernel tiles (0 = KFTPU_FLASH_BLOCK_Q/K env, else the swept
    # default): explicit here so a measured operating point reproduces
    # from config alone, with no process-global state.
    flash_block_q: int = 0
    flash_block_k: int = 0
    # "auto" stores the decode KV cache in `dtype`; "int8" quantizes it
    # (per-token-head scales) — at long contexts the cache dominates
    # decode HBM traffic and int8 halves it.
    kv_cache_dtype: str = "auto"
    # Sliding-window attention (Mistral-style): keys further than
    # window-1 positions in the past are masked; flash skips the COMPUTE
    # of blocks left of the window (MXU work O(L * window); their DMA
    # still runs — see ops/flash_attention.py). 0 = full causal.
    # Supported by every attention path: flash/reference/ring/ulysses
    # in training, and decode masks the cache identically (train/serve
    # parity).
    attention_window: int = 0
    # Bounded decode cache for windowed models: the KV cache holds only
    # the last `attention_window` positions (slot = position % window),
    # so serving memory AND per-step cache bandwidth are O(window), not
    # O(max_seq). Requires attention_window > 0. Exact: token-for-token
    # equal to the full cache under the same window (pinned by tests).
    rolling_kv_cache: bool = False
    # Paged decode KV cache (serving): both > 0 turns the decode cache
    # into a fixed pool of `kv_pages` pages of `kv_page_size` positions
    # each, SHARED across decode slots; callers pass per-slot page
    # tables as a traced `page_table` [B, max_pages] argument
    # (runtime/kvcache.py owns allocation/prefix-sharing on the host).
    # Page 0 is the trash page: idle slots' writes land there so a
    # freed page can be re-owned by another slot without a stale
    # lockstep write corrupting it. Exact: token-for-token equal to
    # the dense cache (pinned by tests).
    kv_pages: int = 0
    kv_page_size: int = 0
    remat: bool = False
    # "full": nothing_saveable — minimum memory, recompute everything.
    # "dots": keep matmul outputs, recompute only elementwise — most of
    # the memory win at a fraction of the recompute tax (the MXU work is
    # NOT redone; usually the right policy for transformers).
    remat_policy: str = "full"
    # MoE: every `moe_every`-th block is a mixture layer (0 = dense only)
    moe_every: int = 0
    n_experts: int = 8
    expert_top_k: int = 2
    # Dispatch implementation (ops/moe.py): "auto" picks the sort+
    # all-to-all sparse path on meshes it covers (fsdp/model/seq/pipe
    # all 1), else the dense one-hot-einsum oracle; "dense"/"sparse"
    # force one.
    moe_impl: str = "auto"
    # Expert capacity = factor * tokens * top_k / n_experts per shard
    # (per batch row in the dense path). Tune against the measured
    # moe_fill / moe_drop step diagnostics: fill << 1 wastes expert
    # GEMM width on padding, drop >> 0 silently zeroes token updates.
    moe_capacity_factor: float = 1.25
    # Pipeline parallelism: split the block stack into this many stages
    # over the `pipe` mesh axis (0/1 = no pipelining).
    pipeline_stages: int = 0
    pp_microbatches: int = 4


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding over the last dim. x: [B, L, H, D]."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, L, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x32 = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(self.dtype)


def _kv_scale_rows(s):
    """[B, S, Hkv, 1] per-(position, head) int8-cache scales -> a layout
    broadcastable against [B, Hkv, G, Lq, S] attention logits/probs (the
    factored-scale decode path applies them there instead of
    dequantizing the cache elementwise)."""
    return s[..., 0].transpose(0, 2, 1)[:, :, None, None, :]


def _split_policy(policy: str) -> tuple[str, int | None]:
    """'slim@12' -> ('slim', 12): apply the named policy to the FIRST
    12 blocks and save everything on the rest — a fractional dial on
    the memory/recompute ladder between whole-model policy rungs. The
    r5 hardware ledger motivated it twice: gpt-760m bs8 slim missed
    fitting by 50MB (slim@15 would fit), and slim measurably BEAT
    no-remat at llama-1b bs8 (byte-bound regime), so the optimum can
    sit strictly between two whole-model policies. Plain names return
    (name, None) = every block."""
    if "@" in policy:
        name, k = policy.split("@", 1)
        if not name or not k.isdigit():
            raise ValueError(
                f"malformed remat_policy {policy!r}: expected "
                "'<dots|full|mlp|slim>@<layer count>' (e.g. slim@12)")
        return name, int(k)
    return policy, None


def _remat_policy(cfg: "TransformerConfig"):
    name, _ = _split_policy(cfg.remat_policy)
    if name == "dots":
        # dot outputs PLUS the flash kernel's named residuals (out, lse —
        # tagged inside its custom_vjp fwd rule, ops/flash_attention.py):
        # pallas_call is not a dot, so plain dots_saveable would replay
        # the whole flash forward in the backward (~6.5% of block MACs at
        # seq 2048) for want of an lse it threw away.
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_flash"))
    if name == "full":
        return jax.checkpoint_policies.nothing_saveable
    if name == "mlp":
        # Save every block intermediate EXCEPT d_ff-wide ones (gate/up/
        # silu/h). Implemented as a WIDTH predicate on the equation's
        # input avals, not checkpoint_name tags: flax wraps activations
        # like silu in jit, and a name applied after the pjit equation
        # leaves the pjit's own output saveable — round 3 shipped the
        # name-tag version and saved_residuals showed it retaining a
        # full d_ff-wide tensor per layer, which is why "mlp" OOMed at
        # the same batch sizes as no-remat (tools/remat_plan.py).
        # Replay cost: the gate/up matmuls + elementwise, ~2/9 of block
        # MACs — plus the down-projection matmul, whose INPUT is d_ff-
        # wide even though its output is d-wide: an input-aval predicate
        # cannot save it, so its ~1/9 of block MACs replays too (total
        # ~3/9). A width predicate on output avals alone would instead
        # retain the d_ff-wide gate/up outputs and lose the memory win.
        wide = cfg.d_ff

        def mlp_policy(prim, *avals, **params):
            del prim, params
            return not any(
                getattr(a, "shape", None) and a.shape[-1] >= wide
                for a in avals)

        return mlp_policy
    if name == "slim":
        # Whitelist, not blacklist: save ONLY the named d-wide bf16
        # anchors (norm outputs, post-rope q/k/v, pre-o attention
        # context, and the flash kernel's out/lse residuals). "mlp"
        # hardware runs OOMed at bs>=16 because save-everything-except
        # also keeps every unnamed residual the backward touches —
        # including the f32 RMSNorm duplicates, which alone match the
        # entire dropped mlp_wide set in bytes. Replay recomputes
        # gate/up + elementwise (~2/9 of block MACs): most of full
        # remat's memory floor at roughly half its recompute tax, with
        # zero flash-forward replay.
        return jax.checkpoint_policies.save_only_these_names(
            "block_norm", "attn_qkv", "attn_ctx", "attn_flash")
    raise ValueError(
        f"unknown remat_policy {cfg.remat_policy!r} (full|dots|mlp|slim)")


class Attention(nn.Module):
    cfg: TransformerConfig

    def _decode_paged(self, q, k, v, decode_index, pad_len, page_table):
        """Paged decode: the cache is a pool of [kv_pages, kv_page_size]
        position pages shared across slots; `page_table` [B, MP] maps
        each slot's logical page j (positions j*PS..(j+1)*PS-1) to a
        physical pool page. Writes scatter the chunk's K/V to
        (table[pos//PS], pos%PS) BEFORE attending (the full-cache
        write-then-attend discipline, so speculative verify chunks
        self-heal identically); reads gather the slot's pages back into
        a logical [B, MP*PS] view and run the same masked attention as
        the dense path — token-for-token equal by construction.

        Why it's safe that the gather sees unallocated (0 = trash-page)
        table entries: the allocator guarantees every position <= the
        slot's current decode index is backed by an owned or shared
        page, so trash content is only ever visible at masked
        (pos > qpos) positions. Idle lockstep slots have their whole
        row zeroed at free time, steering their stale writes into the
        trash page instead of a page another slot now owns."""
        cfg = self.cfg
        b, lq = q.shape[0], q.shape[1]
        hkv, hd = cfg.n_kv_heads, cfg.head_dim
        NP, PS = cfg.kv_pages, cfg.kv_page_size
        MP = page_table.shape[1]
        ck = self.variable("cache", "key_pages",
                           lambda: jnp.zeros((NP, PS, hkv, hd), cfg.dtype))
        cv = self.variable("cache", "value_pages",
                           lambda: jnp.zeros((NP, PS, hkv, hd), cfg.dtype))
        idx = jnp.asarray(decode_index, jnp.int32)
        if idx.ndim == 0:
            idx = jnp.full((b,), idx, jnp.int32)
        pos_q = idx[:, None] + jnp.arange(lq, dtype=jnp.int32)[None, :]
        k_w = k.astype(cfg.dtype)
        v_w = v.astype(cfg.dtype)
        # ---- write the chunk, THEN attend ----
        flat = pos_q.reshape(-1)                       # [b*lq] positions
        rows = jnp.repeat(jnp.arange(b, dtype=jnp.int32), lq)
        pages = page_table[rows, flat // PS]
        offs = flat % PS
        ck.value = ck.value.at[pages, offs].set(k_w.reshape(b * lq, hkv, hd))
        cv.value = cv.value.at[pages, offs].set(v_w.reshape(b * lq, hkv, hd))
        # gather the logical view (reference impl: a TPU kernel would
        # stream pages instead of materializing the gather)
        k_all = ck.value[page_table].reshape(b, MP * PS, hkv, hd)
        v_all = cv.value[page_table].reshape(b, MP * PS, hkv, hd)
        g = cfg.n_heads // hkv
        qg = q.reshape(b, lq, hkv, g, hd)
        logits = jnp.einsum(
            "bqhgd,bshd->bhgqs", qg, k_all,
            preferred_element_type=jnp.float32) * (hd ** -0.5)
        pos = jnp.arange(MP * PS)[None, None, None, None, :]
        qpos = pos_q[:, None, None, :, None]
        mask = pos <= qpos
        if cfg.attention_window:
            mask = mask & (pos > qpos - cfg.attention_window)
        if pad_len is not None:
            mask = mask & (pos >= pad_len[:, None, None, None, None])
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum(
            "bhgqs,bshd->bqhgd", probs.astype(cfg.dtype), v_all
        ).reshape(b, lq, cfg.n_heads, hd)

    def _decode_rolling(self, q, k, v, decode_index, pad_len):
        """Bounded-window decode: the cache keeps only the last W
        positions (slot = position % W), so memory and per-step cache
        bandwidth are O(W) instead of O(max_seq).

        Clobber-safe ordering: attention runs against the OLD cache (all
        positions < idx) plus the current chunk's keys directly, and the
        chunk is written only afterwards — a chunk write may overwrite
        slot p-W while an earlier chunk row still needs it, so
        write-then-attend (the full-cache path's order) would be wrong
        here. Exact under the same window: pinned against the full-cache
        path by tests/test_generate.py."""
        cfg = self.cfg
        b, lq = q.shape[0], q.shape[1]
        hkv, hd = cfg.n_kv_heads, cfg.head_dim
        W = min(cfg.attention_window, cfg.max_seq_len)
        quant = cfg.kv_cache_dtype == "int8"
        cache_dt = jnp.int8 if quant else cfg.dtype
        ck = self.variable("cache", "cached_key",
                           lambda: jnp.zeros((b, W, hkv, hd), cache_dt))
        cv = self.variable("cache", "cached_value",
                           lambda: jnp.zeros((b, W, hkv, hd), cache_dt))
        if quant:
            cks = self.variable("cache", "cached_key_scale",
                                lambda: jnp.zeros((b, W, hkv, 1), jnp.float32))
            cvs = self.variable("cache", "cached_value_scale",
                                lambda: jnp.zeros((b, W, hkv, 1), jnp.float32))
            # int8 feeds the matmuls directly; scales factor out of the
            # head_dim contraction (applied to scores / folded into
            # probs below) — see the full-cache path for the r5 ledger
            # evidence that elementwise dequant here costs 3.6x
            k_old = ck.value.astype(cfg.dtype)
            v_old = cv.value.astype(cfg.dtype)
            ksc_b = _kv_scale_rows(cks.value)
            vsc_b = _kv_scale_rows(cvs.value)
        else:
            k_old, v_old = ck.value, cv.value
            ksc_b = vsc_b = None

        idx = jnp.asarray(decode_index, jnp.int32)
        # Quantize the chunk BEFORE attending and attend its dequantized
        # values: the full-cache path writes first and attends from the
        # (dequantized) cache, so token-for-token parity under int8
        # requires the in-chunk term to see the same quantize->dequantize
        # round trip.
        if quant:
            from kubeflow_tpu.ops.quantize import symmetric_int8

            k_w, ks_w = symmetric_int8(k, -1)
            v_w, vs_w = symmetric_int8(v, -1)
            # the in-chunk term sees the same int8 + factored-scale math
            # as a cache read, so a token attends identically now and
            # after it lands in the cache
            k_c = k_w.astype(cfg.dtype)
            v_c = v_w.astype(cfg.dtype)
            ksw_b = _kv_scale_rows(ks_w)
            vsw_b = _kv_scale_rows(vs_w)
        else:
            k_w, v_w = k.astype(cfg.dtype), v.astype(cfg.dtype)
            k_c, v_c = k_w, v_w
            ksw_b = vsw_b = None
        g = cfg.n_heads // hkv
        qg = q.reshape(b, lq, hkv, g, hd)
        scale = hd ** -0.5
        # old-cache term [b,h,g,lq,W] + in-chunk term [b,h,g,lq,lq]
        lc = jnp.einsum("bqhgd,bshd->bhgqs", qg, k_old,
                        preferred_element_type=jnp.float32) * scale
        ls = jnp.einsum("bqhgd,bchd->bhgqc", qg, k_c,
                        preferred_element_type=jnp.float32) * scale
        if quant:
            lc = lc * ksc_b
            ls = ls * ksw_b

        slots = jnp.arange(W, dtype=jnp.int32)
        cols = jnp.arange(lq, dtype=jnp.int32)
        if idx.ndim == 0:
            # scalar start: query row r sits at absolute position idx+r
            qpos = idx + cols                                   # [lq]
            cur_old = idx - 1
            # absolute position currently held by each slot (the largest
            # p <= cur_old with p % W == slot); negative = never written
            pos_abs = cur_old - ((cur_old - slots) % W)         # [W]
            mc = (pos_abs[None, :] >= 0) \
                & (pos_abs[None, :] > qpos[:, None] - W)        # [lq, W]
            mc = jnp.broadcast_to(mc[None], (b, lq, W))
            ms = (cols[None, :] <= cols[:, None]) \
                & (cols[None, :] > cols[:, None] - W)           # [lq, lq]
            ms = jnp.broadcast_to(ms[None], (b, lq, lq))
            if pad_len is not None:
                mc = mc & (pos_abs[None, None, :] >= pad_len[:, None, None])
                ms = ms & ((idx + cols)[None, None, :]
                           >= pad_len[:, None, None])
        else:
            # per-row positions (continuous batching): lq == 1
            if lq != 1:
                raise ValueError(
                    "rolling_kv_cache vector decode is single-token "
                    f"(got chunk width {lq}); speculative/paged chunks "
                    "need the full or paged cache")
            cur_old = idx - 1                                   # [b]
            pos_abs = cur_old[:, None] - (
                (cur_old[:, None] - slots[None, :]) % W)        # [b, W]
            mc = (pos_abs >= 0) & (pos_abs > idx[:, None] - W)
            mc = mc[:, None, :]                                 # [b, 1, W]
            ms = jnp.ones((b, 1, 1), bool)
            if pad_len is not None:
                mc = mc & (pos_abs[:, None, :] >= pad_len[:, None, None])
                ms = ms & (idx[:, None, None] >= pad_len[:, None, None])

        neg = jnp.float32(-1e30)
        lc = jnp.where(mc[:, None, None, :, :], lc, neg)
        ls = jnp.where(ms[:, None, None, :, :], ls, neg)
        probs = jax.nn.softmax(jnp.concatenate([lc, ls], axis=-1), axis=-1)
        pc, ps = probs[..., :W], probs[..., W:]
        if quant:
            pc = pc * vsc_b
            ps = ps * vsw_b
        out = (jnp.einsum("bhgqs,bshd->bqhgd", pc.astype(cfg.dtype), v_old)
               + jnp.einsum("bhgqc,bchd->bqhgd", ps.astype(cfg.dtype), v_c))
        out = out.reshape(b, lq, cfg.n_heads, hd)

        # ---- write the (already-quantized) chunk, AFTER attending ----
        if idx.ndim == 0:
            # only the last W chunk columns survive a wrap; among those
            # the slot map (idx+c) % W is injective
            wslot = (idx + cols) % W                            # [lq]
            alive = cols >= lq - W
            hot = (slots[:, None] == wslot[None, :]) & alive[None, :]
            hit = hot.any(axis=1)                               # [W]

            def wr(old, new):
                upd = jnp.einsum("sc,bc...->bs...", hot.astype(new.dtype),
                                 new).astype(old.dtype)
                keep = jnp.reshape(~hit, (1, W) + (1,) * (old.ndim - 2))
                return jnp.where(keep, old, upd)

            ck.value = wr(ck.value, k_w)
            cv.value = wr(cv.value, v_w)
            if quant:
                cks.value = wr(cks.value, ks_w)
                cvs.value = wr(cvs.value, vs_w)
        else:
            hot = (slots[None, :] == (idx % W)[:, None])[:, :, None, None]
            ck.value = jnp.where(hot, k_w, ck.value)
            cv.value = jnp.where(hot, v_w, cv.value)
            if quant:
                cks.value = jnp.where(hot, ks_w, cks.value)
                cvs.value = jnp.where(hot, vs_w, cvs.value)
        return out

    @nn.compact
    def __call__(self, x, positions, segment_ids=None, decode_index=None,
                 pad_len=None, page_table=None):
        cfg = self.cfg
        init = nn.initializers.normal(0.02)
        dense = lambda feats, names, name: nn.DenseGeneral(  # noqa: E731
            feats,
            axis=-1,
            use_bias=False,
            dtype=cfg.dtype,
            kernel_init=_part(init, names),
            name=name,
        )
        # Column-parallel QKV: heads sharded over `model`.
        q = dense((cfg.n_heads, cfg.head_dim), (AXIS_FSDP, AXIS_MODEL, None), "q")(x)
        k = dense((cfg.n_kv_heads, cfg.head_dim), (AXIS_FSDP, AXIS_MODEL, None), "k")(x)
        v = dense((cfg.n_kv_heads, cfg.head_dim), (AXIS_FSDP, AXIS_MODEL, None), "v")(x)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        # remat anchors for the "slim" whitelist policy: saving post-rope
        # q/k/v lets the flash backward run without recomputing the
        # projections (its own fwd replay still happens — lse is a
        # custom_vjp residual the policy can't reach)
        q = checkpoint_name(q, "attn_qkv")
        k = checkpoint_name(k, "attn_qkv")
        v = checkpoint_name(v, "attn_qkv")

        if decode_index is not None and page_table is not None:
            if not (cfg.kv_pages and cfg.kv_page_size):
                raise ValueError(
                    "page_table passed but the model was built without "
                    "kv_pages/kv_page_size")
            if cfg.rolling_kv_cache:
                raise ValueError(
                    "paged decode is exclusive with rolling_kv_cache "
                    "(the page pool already bounds cache memory)")
            if cfg.kv_cache_dtype != "auto":
                raise ValueError(
                    "paged decode supports kv_cache_dtype='auto' only "
                    "(int8 page pools are not composed yet)")
            # falls through to the SHARED output projection below, like
            # the rolling path — 'o' must stay single-sited
            out = self._decode_paged(q, k, v, decode_index, pad_len,
                                     page_table)
        elif decode_index is not None and cfg.rolling_kv_cache:
            if not cfg.attention_window:
                raise ValueError(
                    "rolling_kv_cache requires attention_window > 0")
            if cfg.kv_cache_dtype not in ("auto", "int8"):
                raise ValueError(
                    f"unknown kv_cache_dtype {cfg.kv_cache_dtype!r} "
                    "(auto|int8)")
            # falls through to the SHARED output projection below — the
            # 'o' DenseGeneral must stay single-sited or the two decode
            # paths silently diverge in init/sharding
            out = self._decode_rolling(q, k, v, decode_index, pad_len)
        elif decode_index is not None:
            # KV-cache decode: x is the single new token [B, 1, ...]; write
            # its K/V at decode_index and attend q against the full cache
            # with a <=index mask. Cache layout [B, max_seq, Hkv, D].
            # kv_cache_dtype="int8" stores quantized values + per-token-
            # head scales: at long contexts the cache (not the weights)
            # dominates decode HBM traffic, and int8 halves it.
            b = x.shape[0]
            if cfg.kv_cache_dtype not in ("auto", "int8"):
                # a typo'd value silently running full-precision would
                # report an int8 configuration that never happened
                raise ValueError(
                    f"unknown kv_cache_dtype {cfg.kv_cache_dtype!r} "
                    "(auto|int8)")
            quant = cfg.kv_cache_dtype == "int8"
            cache_dt = jnp.int8 if quant else cfg.dtype
            ck = self.variable(
                "cache", "cached_key",
                lambda: jnp.zeros((b, cfg.max_seq_len, cfg.n_kv_heads,
                                   cfg.head_dim), cache_dt))
            cv = self.variable(
                "cache", "cached_value",
                lambda: jnp.zeros((b, cfg.max_seq_len, cfg.n_kv_heads,
                                   cfg.head_dim), cache_dt))
            if quant:
                cks = self.variable(
                    "cache", "cached_key_scale",
                    lambda: jnp.zeros((b, cfg.max_seq_len, cfg.n_kv_heads,
                                       1), jnp.float32))
                cvs = self.variable(
                    "cache", "cached_value_scale",
                    lambda: jnp.zeros((b, cfg.max_seq_len, cfg.n_kv_heads,
                                       1), jnp.float32))

                from kubeflow_tpu.ops.quantize import symmetric_int8

                k_w, ks_w = symmetric_int8(k, -1)  # per-token-head scale
                v_w, vs_w = symmetric_int8(v, -1)
            else:
                k_w, v_w = k.astype(cfg.dtype), v.astype(cfg.dtype)
            idx = jnp.asarray(decode_index, jnp.int32)
            if idx.ndim == 0:
                dus = jax.lax.dynamic_update_slice
                ck.value = dus(ck.value, k_w, (0, idx, 0, 0))
                cv.value = dus(cv.value, v_w, (0, idx, 0, 0))
                if quant:
                    cks.value = dus(cks.value, ks_w, (0, idx, 0, 0))
                    cvs.value = dus(cvs.value, vs_w, (0, idx, 0, 0))
            elif x.shape[1] == 1:
                # per-row positions (continuous batching: every slot is at
                # its own decode index): one-hot scatter along seq — a
                # [B, S] elementwise select per layer, the static-shape
                # way to write B different positions in one program
                hot = (jnp.arange(cfg.max_seq_len)[None, :]
                       == idx[:, None])[:, :, None, None]
                ck.value = jnp.where(hot, k_w, ck.value)
                cv.value = jnp.where(hot, v_w, cv.value)
                if quant:
                    cks.value = jnp.where(hot, ks_w, cks.value)
                    cvs.value = jnp.where(hot, vs_w, cvs.value)
            else:
                # per-row positions, MULTI-token chunk (lockstep
                # speculative verify: every slot consumes its own
                # [cur, d_1..d_k] chunk at its own position): row c of
                # slot b lands at idx[b] + c. One-hot over (row, seq)
                # folded by an einsum — the [B, lq, S] static-shape
                # scatter; per-slot chunk positions are distinct so the
                # fold never sums two writes
                lw = x.shape[1]
                posw = idx[:, None] + jnp.arange(lw, dtype=jnp.int32)[None, :]
                hotw = (jnp.arange(cfg.max_seq_len)[None, None, :]
                        == posw[:, :, None])
                hitw = hotw.any(axis=1)                          # [B, S]

                def _wr(old, new):
                    upd = jnp.einsum("bls,bl...->bs...",
                                     hotw.astype(new.dtype),
                                     new).astype(old.dtype)
                    keep = jnp.reshape(
                        ~hitw, hitw.shape + (1,) * (old.ndim - 2))
                    return jnp.where(keep, old, upd)

                ck.value = _wr(ck.value, k_w)
                cv.value = _wr(cv.value, v_w)
                if quant:
                    cks.value = _wr(cks.value, ks_w)
                    cvs.value = _wr(cvs.value, vs_w)
            if quant:
                # The int8 cache feeds the matmuls DIRECTLY (int8->bf16
                # convert is exact for [-127,127] and fuses into the
                # operand load). Round 3 dequantized elementwise here,
                # materializing + streaming a full-width copy each tick —
                # measured r5: int8-KV decode 3.6x SLOWER than bf16, the
                # opposite of the feature's point. The per-(position,
                # head) scales factor out of the head_dim contraction:
                #   scores = (q · k_int8) * ks[s]     (scale on scores)
                #   out    = (probs * vs[s]) · v_int8 (scale into probs)
                # so cache traffic is 1 byte/elt and the scale math is
                # head_dim-times smaller than a dequantized cache.
                k_all = ck.value.astype(cfg.dtype)
                v_all = cv.value.astype(cfg.dtype)
                ks_b = _kv_scale_rows(cks.value)
                vs_b = _kv_scale_rows(cvs.value)
            else:
                k_all, v_all = ck.value, cv.value
                ks_b = vs_b = None
            # Grouped-query attention WITHOUT jnp.repeat: expanding K/V
            # to n_heads would materialize (and stream) a G-times-larger
            # bf16 tensor every decode step — the exact traffic the int8
            # cache exists to avoid. Group the query heads instead.
            g = cfg.n_heads // cfg.n_kv_heads
            lq = q.shape[1]
            qg = q.reshape(b, lq, cfg.n_kv_heads, g, cfg.head_dim)
            logits = jnp.einsum(
                "bqhgd,bshd->bhgqs", qg, k_all,
                preferred_element_type=jnp.float32) * (cfg.head_dim ** -0.5)
            if ks_b is not None:
                logits = logits * ks_b
            pos = jnp.arange(cfg.max_seq_len)[None, None, None, None, :]
            if idx.ndim == 0:
                # chunked decode: query row r sits at absolute position
                # idx + r and may attend keys <= that (causal within the
                # chunk; degenerates to pos <= idx at lq == 1)
                qpos = (idx + jnp.arange(lq, dtype=jnp.int32)
                        )[None, None, None, :, None]
            else:
                # vector idx: row c of slot b queries from idx[b] + c
                # (degenerates to the old idx[:,None,...] at lq == 1)
                qpos = (idx[:, None] + jnp.arange(lq, dtype=jnp.int32)
                        [None, :])[:, None, None, :, None]
            mask = pos <= qpos
            if cfg.attention_window:
                # same sliding window as training (train/serve parity);
                # this path keeps max_seq cache slots — set
                # rolling_kv_cache for the O(window) bounded cache
                mask = mask & (pos > qpos - cfg.attention_window)
            if pad_len is not None:
                # left-padded ragged prompts: positions before each row's
                # real start are pad garbage and must not be attended to
                # (RoPE is relative, so masked left-padding is exact)
                mask = mask & (pos >= pad_len[:, None, None, None, None])
            logits = jnp.where(mask, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            if vs_b is not None:
                probs = probs * vs_b
            out = jnp.einsum(
                "bhgqs,bshd->bqhgd", probs.astype(cfg.dtype), v_all
            ).reshape(b, lq, cfg.n_heads, cfg.head_dim)
        elif cfg.attention_impl == "ring":
            from kubeflow_tpu.ops.ring_attention import ring_attention

            out = ring_attention(q, k, v, axis_name=AXIS_SEQ,
                                 segment_ids=segment_ids,
                                 window=cfg.attention_window)
        elif cfg.attention_impl == "ulysses":
            from kubeflow_tpu.ops.ulysses import ulysses_attention

            out = ulysses_attention(q, k, v, axis_name=AXIS_SEQ,
                                    segment_ids=segment_ids,
                                    block_q=cfg.flash_block_q,
                                    block_k=cfg.flash_block_k,
                                    window=cfg.attention_window)
        else:
            from kubeflow_tpu.ops.attention import attention

            out = attention(
                q, k, v, causal=True, impl=cfg.attention_impl,
                segment_ids=segment_ids,
                block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
                window=cfg.attention_window,
            )
        out = checkpoint_name(out, "attn_ctx")
        # Row-parallel output projection: contraction dim sharded over
        # `model` — GSPMD inserts the all-reduce here.
        out = nn.DenseGeneral(
            x.shape[-1],
            axis=(-2, -1),
            use_bias=False,
            dtype=cfg.dtype,
            kernel_init=_part(init, (AXIS_MODEL, None, AXIS_FSDP)),
            name="o",
        )(out)
        return shard(out, HIDDEN_SPEC)


class SwiGLU(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        init = nn.initializers.normal(0.02)
        # Column-parallel up projections. EVERY d_ff-wide tensor carries
        # the "mlp_wide" checkpoint name so remat_policy="mlp" can drop
        # exactly these from the saved residuals. That includes
        # silu(gate): the product's backward consumes it, and round 3
        # shipped it unnamed — saved_residuals showed the "mlp" policy
        # retaining a full d_ff-wide tensor per layer anyway, which is
        # why it OOMed at the same batch sizes as no-remat on hardware
        # (tools/remat_plan.py).
        gate = checkpoint_name(nn.DenseGeneral(
            cfg.d_ff, use_bias=False, dtype=cfg.dtype,
            kernel_init=_part(init, (AXIS_FSDP, AXIS_MODEL)), name="gate",
        )(x), "mlp_wide")
        up = checkpoint_name(nn.DenseGeneral(
            cfg.d_ff, use_bias=False, dtype=cfg.dtype,
            kernel_init=_part(init, (AXIS_FSDP, AXIS_MODEL)), name="up",
        )(x), "mlp_wide")
        sg = checkpoint_name(nn.silu(gate), "mlp_wide")
        h = checkpoint_name(shard(sg * up, WIDE_SPEC), "mlp_wide")
        # Row-parallel down projection (psum on output)
        out = nn.DenseGeneral(
            x.shape[-1], use_bias=False, dtype=cfg.dtype,
            kernel_init=_part(init, (AXIS_MODEL, AXIS_FSDP)), name="down",
        )(h)
        return shard(out, HIDDEN_SPEC)


class LMHead(nn.Module):
    """Vocab projection: bf16 operands, f32 accumulation/output.

    An f32×f32 dot can't ride the MXU's native bf16 datapath — XLA
    decomposes it into multiple passes (~4× the cycles). The head is
    ~6·V·d of the step's FLOPs (7% on gpt-350m), so running it f32 costs
    ~20% of the whole step. bf16 inputs with
    preferred_element_type=float32 keep full-precision logits for the
    softmax at bf16 matmul speed. Param tree path stays
    lm_head/kernel (shape [d_model, vocab])."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        kernel = self.param(
            "kernel",
            _part(nn.initializers.normal(0.02), (AXIS_FSDP, AXIS_MODEL)),
            (cfg.d_model, cfg.vocab_size),
            jnp.float32,
        )
        return jnp.einsum(
            "...d,dv->...v", x.astype(cfg.dtype), kernel.astype(cfg.dtype),
            preferred_element_type=jnp.float32)


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None, decode_index=None,
                 pad_len=None, page_table=None):
        cfg = self.cfg
        # "block_norm" anchors both norm outputs: they are the weight-grad
        # inputs of the q/k/v and gate/up matmuls, so saving these d-wide
        # bf16 tensors (instead of the f32 RMSNorm internals a blacklist
        # policy keeps) is what lets the "slim" replay skip the norms.
        ln1 = checkpoint_name(
            RMSNorm(dtype=cfg.dtype, name="ln_attn")(x), "block_norm")
        x = x + Attention(cfg, name="attn")(
            ln1, positions, segment_ids, decode_index, pad_len, page_table
        )
        ln2 = checkpoint_name(
            RMSNorm(dtype=cfg.dtype, name="ln_mlp")(x), "block_norm")
        if self.use_moe:
            from kubeflow_tpu.ops.moe import MoEBlock

            mlp_out = MoEBlock(
                cfg, capacity_factor=cfg.moe_capacity_factor,
                name="moe")(ln2)
        else:
            mlp_out = SwiGLU(cfg, name="mlp")(ln2)
        return x + mlp_out


class Stage(nn.Module):
    """One pipeline stage: n_layers/pipeline_stages consecutive blocks.

    Takes batch-free 1-D positions (SPMDPipeline's broadcast-input
    contract) and broadcasts them to the microbatch rows itself."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions_1d):
        cfg = self.cfg
        positions = jnp.broadcast_to(positions_1d[None, :], x.shape[:2])
        block = Block
        if cfg.remat:
            if _split_policy(cfg.remat_policy)[1] is not None:
                raise ValueError(
                    f"mixed remat policy {cfg.remat_policy!r} is not "
                    "supported under pipeline parallelism (stages would "
                    "carry unequal activation memory)")
            block = nn.remat(Block, policy=_remat_policy(cfg))
        for p in range(cfg.n_layers // cfg.pipeline_stages):
            x = block(cfg, name=f"block_{p}")(x, positions)
        return x


class TransformerLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, train: bool = True, segment_ids=None,
                 decode_index=None, pad_len=None, page_table=None,
                 return_hidden=False):
        cfg = self.cfg
        del train  # no dropout in the speed-run configuration
        emb = self.param(
            "embedding",
            # vocab over (model, fsdp), d unsharded: the gradient of a
            # d-over-fsdp table needs a batch-shard -> feature-shard
            # reshard of dx that the pre-Shardy partitioner can only do
            # as replicate-then-slice ("Involuntary full
            # rematerialization"); vocab-sharding makes both the lookup
            # and the grad scatter the standard ZeRO gather/scatter over
            # the vocab dim instead
            _part(nn.initializers.normal(1.0), ((AXIS_MODEL, AXIS_FSDP), None)),
            (cfg.vocab_size, cfg.d_model),
            jnp.float32,
        )
        x = jnp.asarray(emb, cfg.dtype)[tokens]
        x = shard(x, HIDDEN_SPEC)
        if decode_index is not None:
            # KV-cache decode step: tokens [B, Lq] starting at absolute
            # position decode_index (runtime/generate.py drives Lq=1;
            # speculative verify passes a k-token chunk).
            if cfg.pipeline_stages > 1:
                raise ValueError("decode is not supported under pipeline "
                                 "parallelism yet")
            idx = jnp.asarray(decode_index, jnp.int32)
            # scalar: whole batch starting at one position (generate.py's
            # loop and chunked/speculative decode);
            # vector [B]: per-row positions (continuous batching slots,
            # single-token only)
            offs = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            positions = (jnp.broadcast_to(idx + offs, tokens.shape)
                         if idx.ndim == 0 else idx[:, None] + offs[None, :])
            for i in range(cfg.n_layers):
                use_moe = cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
                x = Block(cfg, use_moe=use_moe, name=f"layer_{i}")(
                    x, positions, None, decode_index, pad_len, page_table)
            x = RMSNorm(dtype=cfg.dtype, name="ln_f")(x)
            return LMHead(cfg, name="lm_head")(x)
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32), tokens.shape
        )
        if cfg.pipeline_stages > 1:
            if cfg.n_layers % cfg.pipeline_stages:
                raise ValueError(
                    f"n_layers={cfg.n_layers} not divisible by "
                    f"pipeline_stages={cfg.pipeline_stages}"
                )
            if (cfg.moe_every or cfg.attention_impl in ("ring", "ulysses")
                    or segment_ids is not None):
                raise ValueError("pipeline stages support dense blocks with "
                                 "local attention only (no moe/ring/ulysses/"
                                 "segments yet)")
            from kubeflow_tpu.parallel.pipeline import SPMDPipeline

            x = SPMDPipeline(
                stage_cls=Stage,
                stage_args=(cfg,),
                n_stages=cfg.pipeline_stages,
                n_microbatches=cfg.pp_microbatches,
                name="pipeline",
            )(x, jnp.arange(tokens.shape[1], dtype=jnp.int32))
        else:
            rblock = Block
            k_mix = None
            if cfg.remat:
                _, k_mix = _split_policy(cfg.remat_policy)
                if k_mix is not None and not 0 < k_mix <= cfg.n_layers:
                    raise ValueError(
                        f"remat_policy {cfg.remat_policy!r}: layer count "
                        f"must be in 1..{cfg.n_layers}")
                rblock = nn.remat(Block, policy=_remat_policy(cfg))
            for i in range(cfg.n_layers):
                use_moe = cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
                # mixed policy: first k_mix blocks remat, the rest save
                # everything (remat never changes values, only residuals)
                blk = rblock if (k_mix is None or i < k_mix) else Block
                x = blk(cfg, use_moe=use_moe, name=f"layer_{i}")(x, positions, segment_ids)
        x = RMSNorm(dtype=cfg.dtype, name="ln_f")(x)
        if return_hidden:
            # Chunked-loss path (ops.xent.chunked_lm_xent): the caller
            # projects through lm_head/kernel chunk-by-chunk so the
            # [B, L, V] logits tensor never materializes. LMHead params
            # still exist (init runs with return_hidden=False).
            return x
        # Untied head, column-parallel over vocab; f32 logits out of a
        # bf16 matmul (see LMHead).
        return LMHead(cfg, name="lm_head")(x)

    def flops_per_token(self, seq_len: int | None = None) -> float:
        """Train FLOPs per token: 6*N over the dense params, plus the
        attention score/value matmuls when seq_len is given — per token
        per layer that's 12*h*d_head*T (QK^T + PV, fwd+bwd), halved for
        causal masking (the PaLM-appendix accounting)."""
        cfg = self.cfg
        attn = cfg.d_model * cfg.head_dim * (cfg.n_heads * 2 + cfg.n_kv_heads * 2)
        mlp = 3 * cfg.d_model * cfg.d_ff          # SwiGLU: gate+up+down
        n_moe = (cfg.n_layers // cfg.moe_every) if cfg.moe_every else 0
        n_dense = cfg.n_layers - n_moe
        # MoE layer: top_k expert MLPs execute per token, plus the router
        moe = cfg.expert_top_k * mlp + cfg.d_model * cfg.n_experts
        emb = cfg.vocab_size * cfg.d_model
        flops = 6.0 * (cfg.n_layers * attn + n_dense * mlp + n_moe * moe
                       + 2 * emb)
        if seq_len:
            flops += 12.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim * seq_len / 2
        return flops


def _build(name: str, **overrides):
    cfg_kw = {}
    model_fields = {f.name for f in dataclasses.fields(TransformerConfig)}
    for k in list(overrides):
        if k in model_fields:
            cfg_kw[k] = overrides.pop(k)
    if overrides:
        raise ValueError(f"unknown transformer kwargs {sorted(overrides)}")
    return TransformerLM(TransformerConfig(**cfg_kw))


@register_model("transformer-test")
def transformer_test(**kw) -> TransformerLM:
    """Tiny config for unit tests / dryruns."""
    base = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                head_dim=16, d_ff=128, max_seq_len=256)
    base.update(kw)
    return _build("transformer-test", **base)


@register_model("gpt-125m")
def gpt_125m(**kw) -> TransformerLM:
    base = dict(d_model=768, n_layers=12, n_heads=12, n_kv_heads=12, head_dim=64, d_ff=3072)
    base.update(kw)
    return _build("gpt-125m", **base)


@register_model("gpt-350m")
def gpt_350m(**kw) -> TransformerLM:
    """GPT-3 Medium shape (d=1024, L=24). With the SwiGLU MLP this lands
    ~430M actual params; the name tracks the family spec, flops_per_token
    tracks the real architecture."""
    base = dict(d_model=1024, n_layers=24, n_heads=16, n_kv_heads=16,
                head_dim=64, d_ff=4096)
    base.update(kw)
    return _build("gpt-350m", **base)


@register_model("gpt-760m")
def gpt_760m(**kw) -> TransformerLM:
    """GPT-3 Large shape, head_dim kept at 64 (24 heads) so attention
    matmuls tile the 128-lane MXU cleanly."""
    base = dict(d_model=1536, n_layers=24, n_heads=24, n_kv_heads=24,
                head_dim=64, d_ff=6144)
    base.update(kw)
    return _build("gpt-760m", **base)


@register_model("llama-1b")
def llama_1b(**kw) -> TransformerLM:
    base = dict(d_model=2048, n_layers=16, n_heads=32, n_kv_heads=8, head_dim=64, d_ff=8192)
    base.update(kw)
    return _build("llama-1b", **base)


@register_model("llama-1b-hd128")
def llama_1b_hd128(**kw) -> TransformerLM:
    """TPU-shaped 1B: identical to llama-1b except 16 heads x head_dim
    128 (GQA 4 kv heads) instead of 32 x 64. The v5e MXU contracts over
    a 128-lane dimension, so head_dim 64 caps the attention matmuls at
    half the systolic array; r5's op microbench measured the flash
    fwd+bwd at ~0.10-0.11 utilization vs ~0.66 for the MLP block,
    making attention the headline-MFU bottleneck. head_dim 128 is the
    established TPU-era choice (Llama-2-7B, Gemma); param count and
    attention FLOPs are unchanged."""
    base = dict(d_model=2048, n_layers=16, n_heads=16, n_kv_heads=4,
                head_dim=128, d_ff=8192)
    base.update(kw)
    return _build("llama-1b-hd128", **base)


@register_model("moe-test")
def moe_test(**kw) -> TransformerLM:
    base = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
                head_dim=16, d_ff=128, moe_every=2, n_experts=4, expert_top_k=2)
    base.update(kw)
    return _build("moe-test", **base)


@register_model("gpt-moe-8e")
def gpt_moe_8e(**kw) -> TransformerLM:
    """Benchmark-scale MoE: gpt-350m backbone with 8 experts (top-2)
    every second layer — ~1.6B total params, ~550M active per token.
    Single chip measures the dispatch/combine overhead (EP=1, all
    experts local); the `expert` mesh axis shards them across chips."""
    base = dict(d_model=1024, n_layers=24, n_heads=16, n_kv_heads=16,
                head_dim=64, d_ff=4096, moe_every=2, n_experts=8,
                expert_top_k=2)
    base.update(kw)
    return _build("gpt-moe-8e", **base)
