import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    MeshSpec,
    batch_sharding,
    build_mesh,
    local_batch_size,
    mesh_summary,
)


def test_default_mesh_all_data(devices8):
    mesh = build_mesh()
    assert mesh.shape[AXIS_DATA] == 8
    assert mesh.devices.size == 8


def test_mesh_spec_resolve():
    spec = MeshSpec(model=2, seq=2).resolve(8)
    assert spec.data == 2
    assert spec.model == 2 and spec.seq == 2


def test_mesh_spec_bad_divisibility():
    with pytest.raises(ValueError):
        MeshSpec(model=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=4, model=4).resolve(8)


def test_mesh_spec_from_dict_rejects_unknown():
    with pytest.raises(ValueError):
        MeshSpec.from_dict({"tensor": 2})


def test_build_mesh_2d(devices8):
    mesh = build_mesh(MeshSpec(data=2, model=4))
    assert mesh.shape[AXIS_DATA] == 2
    assert mesh.shape[AXIS_MODEL] == 4


def test_batch_sharding_puts_batch_on_data(devices8):
    mesh = build_mesh(MeshSpec(data=4, fsdp=2))
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    xs = jax.device_put(x, batch_sharding(mesh, extra_dims=1))
    # batch dim sharded over dcn*data*fsdp*expert = 8 (size-1 axes free;
    # expert is a batch axis so EP meshes don't duplicate dense compute)
    assert xs.sharding.spec == P(("dcn", AXIS_DATA, "fsdp", "expert"), None)
    np.testing.assert_array_equal(np.asarray(xs), x)


def test_local_batch_size(devices8):
    mesh = build_mesh(MeshSpec(data=4, fsdp=2))
    assert local_batch_size(mesh, 32) == 4
    with pytest.raises(ValueError):
        local_batch_size(mesh, 30)


def test_mesh_summary(devices8):
    s = mesh_summary(build_mesh(MeshSpec(data=8)))
    assert "data=8" in s


class TestDcnAxis:
    """Multislice: the outer `dcn` axis (VERDICT #2 / SURVEY §2.5 "DCN
    across slices")."""

    def test_dcn_in_resolve_and_batch_axes(self):
        from kubeflow_tpu.parallel.mesh import AXIS_DCN, BATCH_AXES

        spec = MeshSpec(dcn=2, model=2).resolve(8)
        assert spec.data == 2
        assert spec.axis_sizes()[AXIS_DCN] == 2
        assert BATCH_AXES == (AXIS_DCN, AXIS_DATA, "fsdp", "expert")
        assert spec.batch_axes == BATCH_AXES

    def test_build_mesh_dcn_outermost_contiguous_ranks(self, devices8):
        """CPU fallback: ranks [0..3] form dcn group 0, [4..7] group 1 —
        the contiguous-rank layout the JAXJob controller assigns
        slice_id = rank // per_slice by."""
        from kubeflow_tpu.parallel.mesh import AXIS_DCN

        mesh = build_mesh(MeshSpec(dcn=2, data=2, model=2))
        assert mesh.shape[AXIS_DCN] == 2
        devs = mesh.devices  # shape (dcn, data, fsdp, pipe, expert, seq, model)
        slice0 = {d.id for d in devs[0].flat}
        slice1 = {d.id for d in devs[1].flat}
        assert slice0 == {0, 1, 2, 3} and slice1 == {4, 5, 6, 7}

    def test_local_batch_counts_dcn(self, devices8):
        mesh = build_mesh(MeshSpec(dcn=2, data=2, model=2))
        assert local_batch_size(mesh, 32) == 8  # 32 / (2 dcn * 2 data)

    def test_dcn_step_executes_with_psum_over_slices(self, devices8):
        """A jitted step sharded over (dcn, data) must produce the same
        global gradient sum as single-device math — the all-reduce
        crosses the dcn axis."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        from kubeflow_tpu.parallel.mesh import batch_spec

        mesh = build_mesh(MeshSpec(dcn=2, data=4))
        x = jnp.arange(16.0).reshape(16, 1)

        def loss(w, x):
            return jnp.mean((x @ w) ** 2)

        w = jnp.ones((1, 1))
        with mesh:
            g = jax.jit(
                jax.grad(loss),
                in_shardings=(NamedSharding(mesh, P()),
                              NamedSharding(mesh, batch_spec(mesh, 1))),
            )(w, x)
        ref = jax.grad(loss)(w, x)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref), rtol=1e-6)
