"""Job lifecycle sidecar: readiness gate + master watch + artifact copy.

Mirrors openmpi-controller/controller/controller.py, re-targeted to TPU:

- file-based handshake over a shared emptyDir (:9-11): touch SIGCONT when
  the environment is ready (main container blocks on it), SIGTERM when
  the job should exit;
- readiness gate: where the reference polls /proc/driver/nvidia/version
  (:14, :73-90), this sidecar waits for libtpu devices to be visible
  (accept-4-chips semantics via jax.devices) or, cheaper, for the TPU
  device files /dev/accel* to appear — both gated behind a timeout;
- data staging: download before SIGCONT, upload artifacts after the job
  finishes (:104-116) through a pluggable object-store copier (gs://
  via gsutil, s3:// via awscli, file:// for tests);
- master-phase watch (:92-102): poll the master pod's phase through the
  K8s API until Succeeded/Failed (workers use this to exit when rank 0
  is done).
"""

from __future__ import annotations

import logging
import os
import pathlib
import shutil
import subprocess
import time

log = logging.getLogger("kubeflow_tpu.sidecar")

SIGNAL_DIR = ".kubeflow-tpu-sidecar"   # the shared-volume dir (:9)
SIGCONT_FILE = "SIGCONT"
SIGTERM_FILE = "SIGTERM"
PHASE_SUCCEEDED = "Succeeded"          # :12-13
PHASE_FAILED = "Failed"
TPU_DEV_GLOB = "/dev/accel*"           # the nvidia version-file analogue


def default_copier(src: str, dst: str) -> None:
    """Object-store copy: gs:// (gsutil), s3:// (aws cli), file://|path."""
    def is_remote(p):
        return p.startswith(("gs://", "s3://"))

    if src.startswith("gs://") or dst.startswith("gs://"):
        subprocess.run(["gsutil", "-m", "cp", "-r", src, dst], check=True)
    elif src.startswith("s3://") or dst.startswith("s3://"):
        subprocess.run(["aws", "s3", "cp", "--recursive", src, dst], check=True)
    else:
        src_p = pathlib.Path(src.removeprefix("file://"))
        dst_p = pathlib.Path(dst.removeprefix("file://"))
        if src_p.is_dir():
            shutil.copytree(src_p, dst_p, dirs_exist_ok=True)
        else:
            dst_p.parent.mkdir(parents=True, exist_ok=True)
            shutil.copy2(src_p, dst_p)


def tpu_devices_present() -> bool:
    """The /proc/driver/nvidia/version analogue: device files, or a live
    libtpu if JAX is importable in the sidecar image."""
    import glob

    if glob.glob(TPU_DEV_GLOB):
        return True
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


class SidecarController:
    def __init__(
        self,
        shared_dir: str,
        *,
        master_pod: str | None = None,
        namespace: str = "default",
        client=None,
        download: tuple[str, str] | None = None,   # (src, dst)
        upload: tuple[str, str] | None = None,
        copier=default_copier,
        device_check=tpu_devices_present,
        timeout_s: float = 600.0,
        poll_s: float = 1.0,
    ):
        self.dir = pathlib.Path(shared_dir) / SIGNAL_DIR
        self.master_pod = master_pod
        self.namespace = namespace
        self.client = client
        self.download = download
        self.upload = upload
        self.copier = copier
        self.device_check = device_check
        self.timeout_s = timeout_s
        self.poll_s = poll_s

    # -- signal files (:39-57) ----------------------------------------------

    def __enter__(self) -> "SidecarController":
        self.dir.mkdir(parents=True, exist_ok=True)
        return self

    def __exit__(self, *exc) -> None:
        (self.dir / SIGTERM_FILE).touch()  # :51

    def signal_ready(self) -> None:
        (self.dir / SIGCONT_FILE).touch()  # :57

    def is_ready(self) -> bool:
        return (self.dir / SIGCONT_FILE).exists()

    def should_terminate(self) -> bool:
        return (self.dir / SIGTERM_FILE).exists()

    # -- phases -------------------------------------------------------------

    def wait_ready(self) -> None:
        """Device gate + data download, then SIGCONT (:53-57)."""
        deadline = time.monotonic() + self.timeout_s
        while not self.device_check():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"TPU devices not visible within {self.timeout_s}s")
            log.info("waiting for TPU devices...")
            time.sleep(self.poll_s)
        if self.download:
            self.copier(*self.download)
        self.signal_ready()

    def poll_master_phase(self) -> str:
        pod = self.client.get_or_none("v1", "Pod", self.master_pod, self.namespace)
        if pod is None:
            return PHASE_FAILED  # master gone = job dead
        return (pod.get("status") or {}).get("phase", "Pending")

    def wait_done(self) -> str:
        """Poll master pod phase to terminal (:59, :92-102), then upload."""
        deadline = time.monotonic() + self.timeout_s
        while True:
            phase = self.poll_master_phase()
            if phase in (PHASE_SUCCEEDED, PHASE_FAILED):
                break
            if time.monotonic() > deadline:
                raise TimeoutError("master pod never reached a terminal phase")
            time.sleep(self.poll_s)
        if self.upload:
            self.copier(*self.upload)
        return phase

    def run(self) -> str:
        """Full lifecycle (main.py:7-33)."""
        with self:
            self.wait_ready()
            return self.wait_done()


def main() -> int:  # pragma: no cover - container entry
    import argparse

    p = argparse.ArgumentParser("kubeflow-tpu-sidecar")
    p.add_argument("--shared-dir", default="/kubeflow-tpu")
    p.add_argument("--master-pod", required=True)
    p.add_argument("--namespace", default=os.environ.get("POD_NAMESPACE", "default"))
    p.add_argument("--download", nargs=2, metavar=("SRC", "DST"))
    p.add_argument("--upload", nargs=2, metavar=("SRC", "DST"))
    p.add_argument("--timeout-secs", type=float, default=600.0)
    args = p.parse_args()
    from kubeflow_tpu.control.k8s.rest import RestClient

    ctl = SidecarController(
        args.shared_dir, master_pod=args.master_pod, namespace=args.namespace,
        client=RestClient(), download=tuple(args.download) if args.download else None,
        upload=tuple(args.upload) if args.upload else None,
        timeout_s=args.timeout_secs,
    )
    phase = ctl.run()
    return 0 if phase == PHASE_SUCCEEDED else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
