"""kubeflow_tpu.control.jaxservice — the production serving plane CRD.

A JAXService runs N interchangeable model-server replicas behind the
token-aware router (``serving/router.py``), autoscaled on router queue
depth and tokens/sec between ``spec.replicas.min`` and ``.max``, with
drain-before-delete scale-down. See docs/serving.md.

- ``types``      — CRD spec/validation, the endpoints annotation
  re-export, condition vocabulary.
- ``controller`` — the Reconciler: provisioning through the gang
  scheduler, readiness tracking, endpoints publication, hysteretic
  autoscaling, the cordon → drain → delete state machine.
"""

from __future__ import annotations


def watch_endpoints(apiserver: str, namespace: str, name: str,
                    router,
                    frontend=None,
                    sleep=None,
                    ) -> None:  # pragma: no cover - container glue
    """Router-side membership feed: watch ONE JAXService and apply its
    endpoints annotation to the router on every event (plus an initial
    read). When a ``RouterFrontend`` is passed, the spec's resilience
    defaults (band/deadline/hedge) are adopted per event too, so a spec
    edit retunes the request path without a router restart. Runs
    forever; stream death resubscribes (the control/runtime watch
    discipline)."""
    import logging
    import time as _time

    from kubeflow_tpu.control.jaxservice import types as T
    from kubeflow_tpu.control.k8s.rest import RestClient
    from kubeflow_tpu.serving.router import HttpTransport

    log = logging.getLogger("kubeflow_tpu.jaxservice")
    # injectable resubscribe backoff (DET603): a reference, not a call,
    # so the real sleep stays the default outside tests
    sleep = sleep if sleep is not None else _time.sleep
    client = RestClient(base_url=apiserver or None)
    factory = lambda ep: HttpTransport(ep["addr"])  # noqa: E731

    def apply(obj: dict) -> None:
        router.sync_from_object(obj, transport_factory=factory)
        if frontend is not None:
            frontend.apply_spec(obj)

    while True:
        try:
            obj = client.get_or_none(T.API_VERSION, T.KIND, name, namespace)
            if obj is not None:
                apply(obj)
            for ev in client.watch(T.API_VERSION, T.KIND):
                m = (ev.object.get("metadata") or {})
                if m.get("name") == name \
                        and (m.get("namespace") or "default") == namespace:
                    apply(ev.object)
        except Exception:
            log.exception("endpoints watch failed; resubscribing")
        sleep(0.5)
