"""Coordinator: Apply(PLATFORM) -> Apply(K8S) with retry + conditions.

Mirrors kfctlServer.handleDeployment (kfctlServer.go:105-327): write the
config, apply the platform (cloud infra), build cluster credentials, then
apply K8S manifests with x3 constant backoff (:290-294), appending
KfAvailable/KfDegraded status conditions (:320-327). Second apply is a
no-op on an unchanged config (kfctl_second_apply.py contract).

Platform providers are pluggable; `existing` targets a cluster that is
already up (the common GKE TPU case — node pools carry the TPU chips),
`gke-tpu` shells out to gcloud to create TPU node pools and is exercised
only when gcloud is available.
"""

from __future__ import annotations

import logging
import subprocess
import time

import prometheus_client as prom

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.tpctl import manifests
from kubeflow_tpu.tpctl.tpudef import COND_AVAILABLE, COND_DEGRADED, TpuDef

log = logging.getLogger("kubeflow_tpu.tpctl")

_METRICS: dict[str, object] = {}


def _metric(name, kind, doc, **kw):
    # deploy metrics of bootstrap/cmd/bootstrap/app/server.go:68-132
    if name not in _METRICS:
        _METRICS[name] = kind(name, doc, **kw)
    return _METRICS[name]


def deploy_requests():
    return _metric("tpctl_deploy_requests_total", prom.Counter, "deploy requests")


def deploy_failures():
    return _metric("tpctl_deployments_failure_total", prom.Counter, "failed deploys")


def deploy_duration():
    return _metric(
        "tpctl_dep_duration_seconds", prom.Histogram, "deployment wall time",
        buckets=tuple(30 * i for i in range(1, 16)),  # 30s linear x15 (:112)
    )


class PlatformProvider:
    def apply(self, cfg: TpuDef) -> None: ...

    def delete(self, cfg: TpuDef) -> None: ...


class ExistingCluster(PlatformProvider):
    def apply(self, cfg: TpuDef) -> None:
        log.info("platform=existing: nothing to provision")

    def delete(self, cfg: TpuDef) -> None:
        pass


class GkeTpuPlatform(PlatformProvider):
    """TPU node-pool provisioning via gcloud (the DM/kfctl-gcp analogue).

    The gcloud CLI contract is pinned by an offline stateful test double
    (tests/test_gcloud_double.py runs a fake `gcloud` on PATH through the
    REAL subprocess path), so every command here is executed in CI, not
    just string-asserted:

    - describe-before-create/delete makes apply and delete idempotent
      (re-applies and double-deletes are normal coordinator behavior);
    - machine type derives from the accelerator;
    - multi-host slices pass --tpu-topology and the host count that GKE
      requires (num-nodes = chips / chips-per-host).
    """

    # accelerator -> (machine type, chips per host)
    MACHINE_TYPES = {
        "tpu-v4-podslice": ("ct4p-hightpu-4t", 4),
        "tpu-v5-lite-podslice": ("ct5lp-hightpu-4t", 4),
        "tpu-v5p-slice": ("ct5p-hightpu-4t", 4),
        "tpu-v6e-slice": ("ct6e-standard-4t", 4),
    }

    def __init__(self, runner=subprocess.run):
        self.runner = runner

    @staticmethod
    def _chips(topology: str) -> int:
        # the ONE topology parser (control/scheduler/topology.py);
        # empty means a single-chip pool
        from kubeflow_tpu.control.scheduler.topology import chip_count

        return chip_count(topology or "1")

    def _machine(self, cfg: TpuDef) -> tuple[str, int]:
        if cfg.accelerator not in self.MACHINE_TYPES:
            raise ValueError(
                f"unknown TPU accelerator {cfg.accelerator!r}; known: "
                f"{sorted(self.MACHINE_TYPES)} (a typo here would "
                "provision the wrong TPU generation)")
        machine, per_host = self.MACHINE_TYPES[cfg.accelerator]
        hosts = max(1, self._chips(cfg.topology) // per_host)
        return machine, hosts

    def _scope(self, cfg: TpuDef) -> list[str]:
        return [f"--project={cfg.project}", f"--zone={cfg.zone}",
                f"--cluster={cfg.name}"]

    def _run(self, cmd: list[str]) -> None:
        """check=True with stderr preserved: CalledProcessError's message
        omits captured output, and 'Insufficient quota ...' must reach
        the operator's Degraded condition, not vanish."""
        r = self.runner(cmd, check=False, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd[:4])} failed rc={r.returncode}: "
                f"{(r.stderr or r.stdout or '').strip()[-500:]}")

    def describe_pool(self, cfg: TpuDef) -> dict | None:
        """The live pool document, None if absent. Any OTHER describe
        failure (expired credentials, network, API outage) raises — an
        auth error must never read as 'pool already gone'."""
        r = self.runner(
            ["gcloud", "container", "node-pools", "describe",
             f"{cfg.name}-tpu", *self._scope(cfg), "--format=json"],
            check=False, capture_output=True, text=True)
        if r.returncode == 0:
            import json as _json

            try:
                return _json.loads(r.stdout) or {}
            except ValueError:
                return {}
        err = (r.stderr or r.stdout or "").lower()
        if "not found" in err or "404" in err:
            return None
        raise RuntimeError(
            f"gcloud describe failed rc={r.returncode}: "
            f"{(r.stderr or '').strip()[-500:]}")

    def pool_exists(self, cfg: TpuDef) -> bool:
        return self.describe_pool(cfg) is not None

    def commands(self, cfg: TpuDef) -> list[list[str]]:
        machine, hosts = self._machine(cfg)
        cmd = [
            "gcloud", "container", "node-pools", "create", f"{cfg.name}-tpu",
            *self._scope(cfg),
            f"--machine-type={machine}",
            f"--num-nodes={hosts}",
            f"--node-labels=cloud.google.com/gke-tpu-accelerator={cfg.accelerator},"
            f"cloud.google.com/gke-tpu-topology={cfg.topology}",
        ]
        if hosts > 1:
            # multi-host slice: GKE needs the physical topology to wire
            # ICI across the hosts
            cmd.append(f"--tpu-topology={cfg.topology}")
        return [cmd]

    def apply(self, cfg: TpuDef) -> None:
        live = self.describe_pool(cfg)
        if live is not None:
            # idempotent only if the live pool MATCHES the spec: silently
            # keeping a stale 2x4 pool under a 4x4 TpuDef would report
            # Available while the workload can never schedule
            machine, hosts = self._machine(cfg)
            config = live.get("config") or {}
            drift = []
            if config.get("machineType") not in (None, machine):
                drift.append(f"machineType {config['machineType']} "
                             f"!= {machine}")
            live_topo = (config.get("labels") or {}).get(
                "cloud.google.com/gke-tpu-topology")
            if live_topo not in (None, cfg.topology):
                drift.append(f"topology {live_topo} != {cfg.topology}")
            if live.get("initialNodeCount") not in (None, hosts):
                drift.append(f"hosts {live['initialNodeCount']} != {hosts}")
            if drift:
                raise RuntimeError(
                    f"node pool {cfg.name}-tpu exists with a different "
                    f"shape ({'; '.join(drift)}); delete it before "
                    "re-applying the changed TpuDef")
            log.info("node pool %s-tpu exists and matches; skipping create",
                     cfg.name)
            return
        for cmd in self.commands(cfg):
            log.info("platform exec: %s", " ".join(cmd))
            self._run(cmd)

    def delete(self, cfg: TpuDef) -> None:
        if self.describe_pool(cfg) is None:
            return  # genuinely gone: delete is idempotent
        self._run([
            "gcloud", "container", "node-pools", "delete", f"{cfg.name}-tpu",
            *self._scope(cfg), "--quiet",
        ])


PROVIDERS = {"existing": ExistingCluster, "gke-tpu": GkeTpuPlatform}


class Coordinator:
    K8S_RETRIES = 3  # kfctlServer.go:290-294

    def __init__(self, client, provider: PlatformProvider | None = None):
        self.client = client
        self.provider = provider

    def _provider_for(self, cfg: TpuDef) -> PlatformProvider:
        if self.provider is not None:
            return self.provider
        cls = PROVIDERS.get(cfg.platform)
        if cls is None:
            raise ValueError(f"unknown platform {cfg.platform!r}; "
                             f"valid: {sorted(PROVIDERS)}")
        return cls()

    def apply(self, cfg: TpuDef) -> dict:
        """Full deployment; returns the stored TpuDef object with
        conditions. Idempotent: identical spec re-applies cleanly."""
        deploy_requests().inc()
        t0 = time.monotonic()
        stored = self._store_tpudef(cfg)
        try:
            self._provider_for(cfg).apply(cfg)
            self._apply_k8s(cfg)
        except Exception as e:
            deploy_failures().inc()
            ob.cond_set(stored, COND_DEGRADED, "True", "ApplyFailed", str(e)[:500])
            self._update_status(stored)
            raise
        deploy_duration().observe(time.monotonic() - t0)
        ob.cond_set(stored, COND_AVAILABLE, "True", "ApplySucceeded",
                    f"{len(cfg.applications)} applications applied")
        ob.cond_set(stored, COND_DEGRADED, "False", "ApplySucceeded", "")
        return self._update_status(stored)

    def _store_tpudef(self, cfg: TpuDef) -> dict:
        obj = cfg.to_object()
        existing = self.client.get_or_none(obj["apiVersion"], obj["kind"],
                                           ob.meta(obj)["name"])
        if existing is None:
            return self.client.create(obj)
        if existing.get("spec") != obj.get("spec"):
            existing["spec"] = obj["spec"]
            return self.client.update(existing)
        return existing

    def _update_status(self, obj: dict) -> dict:
        fresh = self.client.get(obj["apiVersion"], obj["kind"], ob.meta(obj)["name"])
        fresh["status"] = obj.get("status", {})
        return self.client.update_status(fresh)

    def _apply_k8s(self, cfg: TpuDef) -> None:
        objs = manifests.render(cfg)
        last_err: Exception | None = None
        for attempt in range(self.K8S_RETRIES):
            try:
                for o in objs:
                    self._apply_one(o)
                return
            except ob.ApiError as e:
                last_err = e
                log.warning("k8s apply attempt %d failed: %s", attempt + 1, e)
                time.sleep(0.01 * (attempt + 1))
        raise last_err  # type: ignore[misc]

    def _apply_one(self, desired: dict) -> None:
        """Server-side-apply-ish create-or-update keyed on spec equality."""
        m = ob.meta(desired)
        found = self.client.get_or_none(
            desired["apiVersion"], desired["kind"], m["name"], m.get("namespace"))
        if found is None:
            self.client.create(desired)
            return
        merged = ob.merge_patch(found, {k: v for k, v in desired.items()
                                        if k not in ("metadata", "status")})
        # labels are additive, like the reconcilehelper policy
        want_labels = {**(ob.labels_of(found)), **(ob.labels_of(desired))}
        if merged != found or want_labels != ob.labels_of(found):
            ob.meta(merged).setdefault("labels", {}).update(want_labels)
            self.client.update(merged)

    def delete(self, cfg: TpuDef) -> None:
        """Teardown: platform resources + the TpuDef (children GC)."""
        self._provider_for(cfg).delete(cfg)
        for o in reversed(manifests.render(cfg)):
            m = ob.meta(o)
            try:
                self.client.delete(o["apiVersion"], o["kind"], m["name"],
                                   m.get("namespace"))
            except ob.NotFound:
                pass
        try:
            self.client.delete(API_VERSION_KIND[0], API_VERSION_KIND[1], cfg.name)
        except ob.NotFound:
            pass

    def status(self, name: str) -> dict | None:
        return self.client.get_or_none(API_VERSION_KIND[0], API_VERSION_KIND[1], name)


from kubeflow_tpu.tpctl.tpudef import API_VERSION as _AV, KIND as _K  # noqa: E402

API_VERSION_KIND = (_AV, _K)
