"""Flash attention kernel vs reference, interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.attention import reference_attention
from kubeflow_tpu.ops.flash_attention import flash_attention


def make_qkv(b=2, l=256, h=2, hk=2, d=64, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, l, h, d), dtype)
    k = jax.random.normal(ks[1], (b, l, hk, d), dtype)
    v = jax.random.normal(ks[2], (b, l, hk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = make_qkv()
    want = reference_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_gqa():
    q, k, v = make_qkv(h=4, hk=2)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_small_blocks():
    q, k, v = make_qkv(l=64)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_rejects_ragged_lengths():
    q, k, v = make_qkv(l=100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_flash_block_fallback_for_non_multiple_lengths():
    """Lengths that are multiples of 128 but not of the swept 512
    default (640, 896, ...) must halve the block down to a divisor
    instead of raising — the %128 support gate admits them."""
    q, k, v = make_qkv(l=640)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)  # default 512 blocks
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_gradients_match_reference():
    q, k, v = make_qkv(b=1, l=128, h=2, hk=2, d=64)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, block_q=64, block_k=64) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
            err_msg=f"grad d{name} mismatch",
        )


def test_flash_bf16():
    q, k, v = make_qkv(dtype=jnp.bfloat16)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2, rtol=3e-2
    )


def test_flash_cross_length_causal_end_aligned():
    """lq != lk: causality must be end-aligned (tril k=lk-lq), the KV-cache
    decode / chunked-prefill convention reference_attention implements."""
    q, _, _ = make_qkv(l=128)
    _, k, v = make_qkv(l=256, seed=1)
    want = reference_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_flash_cross_length_causal_gradients():
    q, _, _ = make_qkv(l=128)
    _, k, v = make_qkv(l=256, seed=1)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-4, rtol=5e-4)


def test_pallas_bwd_matches_xla_oracle():
    # The fused Pallas backward vs the blockwise-XLA oracle, directly.
    from kubeflow_tpu.ops.flash_attention import (
        _flash_bwd_pallas,
        _flash_bwd_xla,
        _flash_fwd,
    )

    rng = jax.random.split(jax.random.PRNGKey(7), 4)
    bh, lq, d = 4, 64, 16
    q = jax.random.normal(rng[0], (bh, lq, d), jnp.float32)
    k = jax.random.normal(rng[1], (bh, lq, d), jnp.float32)
    v = jax.random.normal(rng[2], (bh, lq, d), jnp.float32)
    g = jax.random.normal(rng[3], (bh, lq, d), jnp.float32)
    scale = d ** -0.5
    for causal in (True, False):
        out, lse = _flash_fwd(q, k, v, scale, causal, 32, 32, True)
        got = _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal,
                                32, 32, True)
        want = _flash_bwd_xla(q, k, v, out, lse, g, scale, causal, 32)
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4,
                err_msg=f"d{name} mismatch (causal={causal})")


def test_block_size_env_override_reaches_kernel(monkeypatch):
    """KFTPU_FLASH_BLOCK_Q/K tune the kernel tiles per run (the
    autotuning sweep hook) — dispatcher passes them through and results
    stay correct."""
    from kubeflow_tpu.ops import attention as A

    seen = {}
    real = __import__("kubeflow_tpu.ops.flash_attention",
                      fromlist=["flash_attention"]).flash_attention

    def spy(q, k, v, **kw):
        seen.update(kw)
        return real(q, k, v, **kw)

    monkeypatch.setattr("kubeflow_tpu.ops.flash_attention.flash_attention",
                        spy)
    monkeypatch.setenv("KFTPU_FLASH_BLOCK_Q", "64")
    monkeypatch.setenv("KFTPU_FLASH_BLOCK_K", "64")
    rng = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(rng[0], (1, 128, 2, 64), jnp.float32)
    k = jax.random.normal(rng[1], (1, 128, 2, 64), jnp.float32)
    v = jax.random.normal(rng[2], (1, 128, 2, 64), jnp.float32)
    out = A.attention(q, k, v, causal=True, impl="flash")
    assert seen["block_q"] == 64 and seen["block_k"] == 64
    want = A.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


# ---- sequence packing (segment ids) ------------------------------------


from conftest import make_segments as _segments  # noqa: E402


@pytest.mark.parametrize("causal", [True, False])
def test_flash_segments_match_reference(causal):
    q, k, v = make_qkv(l=256)
    seg = _segments(2, 256, 3)
    want = reference_attention(q, k, v, causal=causal, segment_ids=seg)
    got = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_segments_gradients_match_reference():
    q, k, v = make_qkv(b=1, l=128)
    seg = _segments(1, 128, 2)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, segment_ids=seg,
                                block_q=64, block_k=64)
                .astype(jnp.float32) ** 2).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True, segment_ids=seg)
                .astype(jnp.float32) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_segments_block_no_cross_document_leak():
    """The value of a query must not depend on keys in OTHER segments.
    Poison document 1 (the PAST) and assert document 2's outputs are
    unchanged — that direction is causally allowed and only the segment
    mask blocks it (poisoning doc 2 would be vacuous: causality already
    hides future keys from doc-1 queries)."""
    q, k, v = make_qkv(l=256)
    seg = jnp.concatenate([jnp.zeros((2, 128), jnp.int32),
                           jnp.ones((2, 128), jnp.int32)], axis=1)
    base = flash_attention(q, k, v, causal=True, segment_ids=seg,
                           block_q=128, block_k=128)
    v2 = v.at[:, :128].add(100.0)  # poison document 1's values
    got = flash_attention(q, k, v2, causal=True, segment_ids=seg,
                          block_q=128, block_k=128)
    np.testing.assert_array_equal(np.asarray(base[:, 128:]),
                                  np.asarray(got[:, 128:]))
    assert not np.allclose(np.asarray(base[:, :128]), np.asarray(got[:, :128]))


def test_flash_segments_gqa():
    q, k, v = make_qkv(h=4, hk=2, l=256)
    seg = _segments(2, 256, 2)
    want = reference_attention(q, k, v, causal=True, segment_ids=seg)
    got = flash_attention(q, k, v, causal=True, segment_ids=seg,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_dispatch_routes_segments_through_flash(monkeypatch):
    """attention(impl='flash', segment_ids=...) must call the Pallas
    kernel, not silently fall back to the O(L^2) reference path."""
    from kubeflow_tpu.ops import attention as attention_mod
    from kubeflow_tpu.ops import flash_attention as fa

    called = {}
    real = fa.flash_attention

    def spy(*a, **kw):
        called["seg"] = kw.get("segment_ids") is not None
        return real(*a, **kw)

    monkeypatch.setattr(fa, "flash_attention", spy)
    q, k, v = make_qkv(l=256)
    seg = _segments(2, 256, 2)
    attention_mod.attention(q, k, v, causal=True, impl="flash",
                            segment_ids=seg)
    assert called.get("seg") is True


# ---- sliding-window attention ------------------------------------------


@pytest.mark.parametrize("window", [16, 64, 100])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_window_matches_reference(window, causal):
    q, k, v = make_qkv(l=256)
    want = reference_attention(q, k, v, causal=causal, window=window)
    got = flash_attention(q, k, v, causal=causal, window=window,
                          block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_window_gradients_match_reference():
    q, k, v = make_qkv(b=1, l=128)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, window=32,
                                block_q=32, block_k=32)
                .astype(jnp.float32) ** 2).sum()

    def f_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True, window=32)
                .astype(jnp.float32) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_window_composes_with_segments():
    q, k, v = make_qkv(l=256)
    seg = _segments(2, 256, 3)
    want = reference_attention(q, k, v, causal=True, segment_ids=seg,
                               window=48)
    got = flash_attention(q, k, v, causal=True, segment_ids=seg,
                          window=48, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_window_blocks_left_of_window_are_skipped():
    """A far-left kv block must be skipped by the run predicate: poison
    keys far outside the window and assert outputs are untouched."""
    q, k, v = make_qkv(l=256)
    base = flash_attention(q, k, v, causal=True, window=32,
                           block_q=64, block_k=64)
    k2 = k.at[:, :64].add(1000.0)   # first kv block, > window away from
    v2 = v.at[:, :64].add(1000.0)   # every query in the last two blocks
    got = flash_attention(q, k2, v2, causal=True, window=32,
                          block_q=64, block_k=64)
    np.testing.assert_array_equal(np.asarray(base[:, 128:]),
                                  np.asarray(got[:, 128:]))


@pytest.mark.parametrize("window", [32, 64])
def test_window_pruned_grid_long_sequence(window):
    """Round-4 grid pruning: with a window, the k axis of the fwd grid
    shrinks to the window-reachable span (out-of-window blocks are never
    DMA'd, not just compute-skipped). l=512 @ 64x64 blocks: nk=8 but
    nkw=3 — most of the grid is gone; parity with reference pins the
    index-map remap and the clamped tail block."""
    q, k, v = make_qkv(l=512)
    want = reference_attention(q, k, v, causal=True, window=window)
    got = flash_attention(q, k, v, causal=True, window=window,
                          block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    # gradients flow through the pruned fwd's saved lse
    def f(q):
        return flash_attention(q, k, v, causal=True, window=window,
                               block_q=64, block_k=64).sum()

    def r(q):
        return reference_attention(q, k, v, causal=True,
                                   window=window).sum()

    gf = jax.grad(f)(q)
    gr = jax.grad(r)(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("l,window,bq,bk", [
    (192, 40, 64, 64),    # l not a multiple of block, window < block
    (256, 300, 64, 64),   # window >= l: pruning degenerates to full
    (384, 64, 64, 128),   # asymmetric blocks (bk = 2*bq)
    (512, 8, 128, 64),    # tiny window inside one block
])
def test_window_pruned_grid_edge_shapes(l, window, bq, bk):
    """Pruned-grid edge cases: windows wider than the sequence, windows
    narrower than a block, asymmetric block shapes (l=192 exercises the
    auto-halving of blocks for non-multiple lengths). Reference parity
    fwd+bwd pins the kb_lo/qb_lo remaps and the clamped tail loads at
    every geometry."""
    q, k, v = make_qkv(l=l)
    want = reference_attention(q, k, v, causal=True, window=window)
    got = flash_attention(q, k, v, causal=True, window=window,
                          block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)

    def f(q):
        return flash_attention(q, k, v, causal=True, window=window,
                               block_q=bq, block_k=bk).sum()

    def r(q):
        return reference_attention(q, k, v, causal=True,
                                   window=window).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)),
                               np.asarray(jax.grad(r)(q)),
                               atol=3e-4, rtol=3e-4)
