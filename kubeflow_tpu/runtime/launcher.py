"""In-pod launcher — the TPU-native replacement for tf-cnn's launcher.py.

Reference contract (tf-controller-examples/tf-cnn/launcher.py):
  - decode TF_CONFIG into --job_name/--ps_hosts/--worker_hosts/--task_index
    (:68-80), exec the payload (:31), then *sleep forever* on success so
    the operator's restartPolicy doesn't rerun it (:90-93).

This launcher:
  - decodes JAXJOB_* env (parallel/dist.py) and joins the jax.distributed
    cluster, with a TCP readiness gate on the coordinator instead of
    sleep-based ordering;
  - waits for TPU devices to be visible (the libtpu analogue of the
    openmpi sidecar's /proc/driver/nvidia/version poll, controller.py:73-90);
  - runs either a built-in trainer (--config JSON/YAML → TrainConfig) or a
    user command;
  - exits 0 on success, 1 on failure, and EX_TEMPFAIL (75) when a
    SIGTERM preemption notice made the trainer checkpoint and leave
    early — the JAXJob controller reads 75 as "gang-restart me, resume
    from the checkpoint", not as a crash. No sleep loop in the pod.

Usage:
    python -m kubeflow_tpu.runtime.launcher --config cfg.yaml
    python -m kubeflow_tpu.runtime.launcher -- python my_train.py --flag
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import subprocess
import sys
import time

from kubeflow_tpu.obs import trace as obs_trace

log = logging.getLogger("kubeflow_tpu.launcher")


def wait_for_devices(timeout_s: float = 300.0, expect_platform: str | None = None) -> int:
    """Block until jax sees accelerator devices (libtpu ready)."""
    import jax

    deadline = time.monotonic() + timeout_s
    while True:
        try:
            devs = jax.devices(expect_platform) if expect_platform else jax.devices()
            if devs:
                log.info("devices ready: %d x %s", len(devs), devs[0].device_kind)
                return len(devs)
        except RuntimeError:
            pass
        if time.monotonic() > deadline:
            raise TimeoutError(f"no {expect_platform or 'accelerator'} devices after {timeout_s}s")
        time.sleep(2.0)


def load_config(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        from kubeflow_tpu.utils import yaml_lite

        return yaml_lite.loads(text)


def run_builtin_trainer(cfg_dict: dict) -> int:
    from kubeflow_tpu.runtime import metrics as rt_metrics
    from kubeflow_tpu.runtime.preemption import EX_TEMPFAIL, PreemptionNotice
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    metrics_port = int(os.environ.get("JAXRT_METRICS_PORT", "9100"))
    try:
        rt_metrics.serve_metrics(metrics_port)
    except OSError:
        log.warning("metrics port %d busy; metrics endpoint disabled", metrics_port)
    # The worker span: child of the job root (TRACEPARENT env, stamped
    # by the JAXJob controller) — trainer/step spans nest inside it, so
    # one trace runs from "JAXJob created" to "step done".
    from kubeflow_tpu.parallel import dist as D

    try:
        with obs_trace.TRACER.span(
                "worker", process=os.environ.get(D.ENV_PID, ""),
                job=os.environ.get(D.ENV_NAME, "")):
            cfg = TrainConfig.from_dict(cfg_dict)
            # SIGTERM (pod eviction / TPU maintenance) => checkpoint +
            # EX_TEMPFAIL so the JAXJob controller gang-restarts and resumes.
            notice = PreemptionNotice().install()
            world_file = os.environ.get(D.ENV_WORLD_FILE)
            if world_file:
                # elastic job: the controller projects its world stamp
                # into this file (downward API); the coordinator resizes
                # the training world in place on shrink/grow instead of
                # dying with the gang (docs/elastic.md)
                import socket

                from kubeflow_tpu.runtime.elastic import (
                    BATCH_PRESERVE, ElasticCoordinator, file_world_source,
                )

                coord = ElasticCoordinator(
                    file_world_source(world_file),
                    my_name=os.environ.get("HOSTNAME")
                    or socket.gethostname(),
                    notice=notice,
                    batch_policy=os.environ.get(D.ENV_BATCH_POLICY,
                                                BATCH_PRESERVE))
                _, summary = coord.run(
                    cfg, full_world=int(
                        os.environ.get(D.ENV_NPROC, "1")))
            else:
                trainer = Trainer(cfg)
                _, summary = trainer.fit(stop=notice)
    finally:
        _dump_trace()
    print(json.dumps({"summary": summary}), flush=True)
    return EX_TEMPFAIL if summary.get("preempted") else 0


def _dump_trace() -> None:
    """Persist this process's spans (KFTPU_TRACE_FILE=<path>.jsonl);
    tools/trace2perfetto.py turns the dump into a Perfetto timeline."""
    path = os.environ.get("KFTPU_TRACE_FILE")
    if not path:
        return
    try:
        obs_trace.write_jsonl(path, obs_trace.COLLECTOR.spans())
    except OSError as e:
        log.warning("could not write trace dump %s: %s", path, e)


def run_user_command(argv: list[str]) -> int:
    """Exec the user payload, streaming output (launcher.py:31
    run_and_stream analogue, minus the sleep-forever)."""
    log.info("exec: %s", " ".join(argv))
    proc = subprocess.Popen(argv, stdout=sys.stdout, stderr=sys.stderr)
    return proc.wait()


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    user_cmd: list[str] = []
    if "--" in argv:
        i = argv.index("--")
        argv, user_cmd = argv[:i], argv[i + 1 :]

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="TrainConfig JSON/YAML for the built-in trainer")
    p.add_argument("--wait-devices", action="store_true",
                   help="block until accelerator devices are visible before starting")
    p.add_argument("--device-timeout", type=float, default=300.0)
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    # Honor JAX_PLATFORMS even when a sitecustomize imported jax before this
    # process's env was consulted (jax snapshots the var at import time).
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    from kubeflow_tpu.parallel import backends as B
    from kubeflow_tpu.parallel import dist as D

    log.info("collectives backend: %s", B.get_backend().name)

    # Adopt the job's trace context before any spans open: the JAXJob
    # controller stamped TRACEPARENT into the pod env, and attaching it
    # here parents every worker-side span on the job's root span.
    ctx = obs_trace.context_from_env()
    if ctx is not None:
        obs_trace.TRACER.attach(ctx)

    world_file = os.environ.get(D.ENV_WORLD_FILE)
    if world_file and args.config:
        # Elastic built-in-trainer job: the pod env describes the FULL
        # gang, but the live membership is whatever the controller
        # stamped into the world file — under partial admission (or a
        # grow-back replacement joining a shrunken world) they
        # disagree, and a global initialize at the env size would block
        # for peers that were never admitted until it times out. Leave
        # the first world formation to the ElasticCoordinator
        # (wait_for_membership + form_world), which forms from the
        # stamp and retries when the stamp moves mid-join. Only the
        # --config path wires a coordinator: a user command keeps the
        # eager env formation below (its payload owns its own world,
        # and gets no elastic resize — docs/elastic.md).
        log.info("elastic world file %s set: deferring world formation "
                 "to the elastic coordinator", world_file)
    else:
        if world_file:
            log.warning("%s is set but a user command is being run: "
                        "elastic resize only applies to the built-in "
                        "trainer (--config); forming the world from the "
                        "gang env", D.ENV_WORLD_FILE)
        cfg = D.initialize_from_env()
        log.info("process %d/%d (job=%s)", cfg.process_id, cfg.num_processes, cfg.job_name or "-")

    if args.wait_devices:
        wait_for_devices(args.device_timeout)

    if args.config:
        # On-demand xprof capture server (JAXRT_PROFILER_PORT) so
        # tensorboard "Capture profile" works against the live pod. Only
        # on the built-in-trainer path: user commands run in a subprocess
        # (the process doing the JAX work), which inherits the env and
        # starts its own server.
        from kubeflow_tpu.runtime.profiler import start_server_from_env

        start_server_from_env()
        return run_builtin_trainer(load_config(args.config))
    if user_cmd:
        return run_user_command(user_cmd)
    p.error("need --config or a user command after --")
    return 2


if __name__ == "__main__":
    sys.exit(main())
