"""KFAM entry: python -m kubeflow_tpu.control.kfam."""
import argparse

from kubeflow_tpu.control.k8s.rest import RestClient
from kubeflow_tpu.control.kfam.service import KfamService

p = argparse.ArgumentParser("kfam")
p.add_argument("--port", type=int, default=8081)
p.add_argument("--apiserver", default="")
args = p.parse_args()
svc = KfamService(RestClient(base_url=args.apiserver or None)).serve(port=args.port)
print(f"kfam on :{svc.port}")
svc.serve_forever()
