"""kubeflow_tpu.control — the Kubernetes control plane of the framework.

The reference implements its control plane as Go kubebuilder operators
(components/{notebook,profile,tensorboard}-controller, admission-webhook,
access-management; shared lib components/common/reconcilehelper). This image
ships no Go toolchain, so the TPU build implements the same capability
surface in Python on an in-tree API machinery layer:

- ``control.k8s``            — unstructured objects, an in-memory fake
  cluster with watches/finalizers/ownerRef GC (the fake backend the
  reference lacks — SURVEY.md §4), and a REST client for real apiservers.
- ``control.runtime``        — the controller engine (workqueue + watches +
  requeue; controller-runtime's Manager/Controller analogue).
- ``control.reconcilehelper``— create-or-update diff/copy semantics
  (components/common/reconcilehelper/util.go).
- ``control.jaxjob``         — the training-job operator (TFJob/OpenMPI
  analogue): gang TPU pod sets + jax.distributed env injection.
- ``control.scheduler``      — the TPU gang scheduler (kube-scheduler/
  Kueue analogue): slice-topology node model, per-namespace gang queue,
  all-or-nothing admission, priority preemption (docs/scheduler.md).
- ``control.notebook``, ``control.profile``, ``control.tensorboard``,
  ``control.poddefault`` (admission webhook), ``control.kfam``,
  ``control.gatekeeper`` — the remaining operators/services, one per
  reference component (SURVEY.md §2.2).
"""
