"""Speculative decoding == the target's own greedy decode, exactly.

Greedy acceptance makes equality a THEOREM, not a tolerance: every
accepted token matched the target argmax and the bonus token IS the
target argmax — so any token-level difference is a cache/mask/position
bug. The draft model's quality only moves the stats, never the output.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.runtime.generate import generate
from kubeflow_tpu.runtime.speculative import speculative_generate


def _models(seed_t=0, seed_d=1, **kw):
    target = get_model("transformer-test", dtype=jnp.float32,
                       max_seq_len=64, **kw)
    draft = get_model("transformer-test", dtype=jnp.float32,
                      max_seq_len=64, n_layers=1, d_model=32, n_heads=2,
                      n_kv_heads=2, head_dim=16, d_ff=64, **kw)
    prompt = (jnp.arange(10, dtype=jnp.int32).reshape(1, 10) * 13 + 5) % 250
    tv = target.init(jax.random.PRNGKey(seed_t), prompt, train=False)
    dv = draft.init(jax.random.PRNGKey(seed_d), prompt, train=False)
    return target, tv, draft, dv, prompt


@pytest.mark.parametrize("k", [2, 4, 7])
def test_speculative_equals_target_greedy(k):
    target, tv, draft, dv, prompt = _models()
    want = np.asarray(generate(target, tv, prompt, max_new_tokens=16,
                               temperature=0.0))
    got, stats = speculative_generate(
        target, tv, draft, dv, prompt, max_new_tokens=16, k=k)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["tokens"] == 16
    assert stats["rounds"] >= 1


def test_speculative_with_self_draft_accepts_everything():
    """Draft == target: every proposal matches, so each round accepts
    all k proposals and emits k+1 tokens — the acceptance ceiling."""
    target, tv, _, _, prompt = _models()
    got, stats = speculative_generate(
        target, tv, target, tv, prompt, max_new_tokens=12, k=4)
    want = np.asarray(generate(target, tv, prompt, max_new_tokens=12,
                               temperature=0.0))
    np.testing.assert_array_equal(np.asarray(got), want)
    # perfect draft: every round accepts all k proposals
    assert stats["accepted"] == stats["rounds"] * 4


def test_speculative_with_padded_prompt():
    target, tv, draft, dv, prompt = _models()
    pad = jnp.asarray([3], jnp.int32)
    padded = prompt.at[:, :3].set(0)
    want = np.asarray(generate(target, tv, padded, max_new_tokens=8,
                               temperature=0.0, pad_len=pad))
    got, _ = speculative_generate(
        target, tv, draft, dv, padded, max_new_tokens=8, k=3, pad_len=pad)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_rejects_batch_and_overflow():
    target, tv, draft, dv, prompt = _models()
    with pytest.raises(ValueError, match="batch-1"):
        speculative_generate(target, tv, draft, dv,
                             jnp.zeros((2, 8), jnp.int32), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_seq_len"):
        speculative_generate(target, tv, draft, dv, prompt,
                             max_new_tokens=60, k=4)


def test_served_speculative_matches_plain_served_generate():
    """The serving layer's draft_model path must emit the same tokens as
    the plain served generator (greedy acceptance == target greedy)."""
    from kubeflow_tpu.serving.server import serve_lm_generator

    common = dict(prompt_len=12, max_new_tokens=8, seed=3)
    plain = serve_lm_generator("plain", "transformer-test", **common)
    spec = serve_lm_generator(
        "spec", "transformer-test", draft_model="transformer-test",
        draft_k=3, **common)
    try:
        reqs = [{"tokens": [9, 8, 7, 6, 5]}, {"tokens": [1, 2, 3]}]
        want = plain.predict(reqs)
        got = spec.predict(reqs)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert spec.signature["draft_k"] == 3
    finally:
        plain.close()
        spec.close()


def test_served_speculative_rejects_bad_combos():
    from kubeflow_tpu.serving.server import serve_lm_generator

    # draft + continuous batching is now the LOCKSTEP speculative path
    # (ISSUE 9) — valid; the remaining hard exclusions still refuse at
    # registration
    with pytest.raises(ValueError, match="greedy-only"):
        serve_lm_generator("y", "transformer-test",
                           draft_model="transformer-test",
                           temperature=0.7)
    with pytest.raises(ValueError, match="continuous_batching"):
        serve_lm_generator("z", "transformer-test",
                           kv_pages=16, kv_page_size=4)
    with pytest.raises(ValueError, match="kv_page_size"):
        serve_lm_generator("z2", "transformer-test",
                           continuous_batching=True, kv_pages=16)
    with pytest.raises(ValueError, match="single-chip"):
        serve_lm_generator("z3", "transformer-test",
                           continuous_batching=True, kv_pages=16,
                           kv_page_size=4, mesh={"data": 2})


def test_served_speculative_exports_acceptance_metrics():
    import prometheus_client

    from kubeflow_tpu.serving.server import serve_lm_generator

    spec = serve_lm_generator(
        "specm", "transformer-test", prompt_len=8, max_new_tokens=4,
        draft_model="transformer-test", draft_k=2)
    try:
        spec.predict([{"tokens": [4, 2]}])
        scrape = prometheus_client.generate_latest().decode()
        assert 'serving_speculative_drafted_total{model="specm"}' in scrape
        assert 'serving_speculative_accepted_total{model="specm"}' in scrape
    finally:
        spec.close()


def test_speculative_refuses_rolling_cache():
    """Rejection rewinds the decode index; a rolling cache slot would
    then hold a rejected newer position that the window mask dates as an
    older one — refused up front (runtime/speculative.py)."""
    from flax.core import meta as _meta

    target = get_model("transformer-test", max_seq_len=64,
                       attention_window=16, rolling_kv_cache=True)
    draft = get_model("transformer-test", max_seq_len=64)
    tok = jnp.zeros((1, 4), jnp.int32)
    tvars = _meta.unbox(target.init(jax.random.PRNGKey(0), tok))
    dvars = _meta.unbox(draft.init(jax.random.PRNGKey(1), tok))
    with pytest.raises(ValueError, match="rolling_kv_cache"):
        speculative_generate(target, tvars, draft, dvars, tok,
                             max_new_tokens=4)
