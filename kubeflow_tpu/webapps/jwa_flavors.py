"""JWA UI flavors: pluggable spawner variants selected by $UI.

The reference ships two spawner backends behind one dispatch —
``UI=default|rok`` (jupyter-web-app/backend/main.py:12-29). The "rok"
flavor overrides the notebook POST to wire workspaces to Rok block
snapshots and adds a per-namespace token endpoint reading a Secret
(kubeflow_jupyter/rok/app.py:27-62, :56+).

The TPU-native rethink keeps the extension-point SHAPE (env-selected
flavor, POST override, token endpoint) but swaps Rok's proprietary block
snapshots for cloud object storage: the "snapshot" flavor seeds a new
notebook's workspace from a gs://|s3:// prefix — the same copier
contract the job sidecar already implements (sidecar/controller.py
default_copier) — via an annotation an init process consumes.
"""

from __future__ import annotations

import base64

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.utils.httpd import ApiHttpError, HttpReq

# annotation consumed by the notebook image's init hook (the sidecar
# copier contract): seed $HOME from this object-store prefix on start
ANNO_SNAPSHOT_SRC = "notebooks.kubeflow.org/snapshot-source"
# the per-namespace Secret holding object-store credentials (the rok
# token Secret analogue, rok.py rok_secret_name)
SNAPSHOT_SECRET = "snapshot-access"
FLAVORS = ("default", "snapshot")


def select_flavor(env: dict | None = None) -> str:
    import os

    ui = (env or os.environ).get("UI", "default")
    if ui not in FLAVORS:
        # main.py:27-29 logs "There is no <ui> UI to load" and dies; fail
        # just as loudly but with the valid set in the message
        raise ValueError(f"unknown UI flavor {ui!r}; valid: {FLAVORS}")
    return ui


class SnapshotFlavor:
    """Installed onto a JupyterWebApp when UI=snapshot."""

    def __init__(self, app):
        self.app = app

    # -- POST override (rok/app.py:56+ analogue) ---------------------------

    def mutate_notebook(self, nb: dict, form: dict) -> dict:
        src = form.get("snapshotUrl") or ""
        if not src:
            return nb
        if not isinstance(src, str) or not src.startswith(("gs://", "s3://")):
            raise ApiHttpError(
                400, f"snapshotUrl must be gs:// or s3://, got {src!r}")
        ob.set_annotation(nb, ANNO_SNAPSHOT_SRC, src)
        return nb

    # -- token endpoint (rok/app.py:27-52 contract) ------------------------

    def get_token(self, req: HttpReq):
        ns = req.params["ns"]
        token = {"name": SNAPSHOT_SECRET, "value": ""}
        secret = self.app.client.get_or_none(
            "v1", "Secret", SNAPSHOT_SECRET, ns)
        if secret is None:
            return {"success": False, "token": token,
                    "log": f"snapshot Secret doesn't exist in "
                           f"namespace '{ns}'"}
        raw = (secret.get("data") or {}).get("token")
        if not raw:
            return {"success": False, "token": token,
                    "log": f"Secret {SNAPSHOT_SECRET!r} has no 'token' key"}
        try:
            token["value"] = base64.b64decode(raw).decode()
        except Exception:
            return {"success": False, "token": token,
                    "log": "snapshot Secret token is not valid base64"}
        return {"success": True, "token": token}

    def add_routes(self, router) -> None:
        router.route("GET", "/api/snapshot/namespaces/{ns}/token",
                     self.get_token)
