"""JWA + dashboard backend semantics (reference: jupyter-web-app
backend tests shape; centraldashboard api_workgroup_test.ts shape)."""

import json

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.kfam.service import KfamService
from kubeflow_tpu.control.notebook import types as NT
from kubeflow_tpu.control.poddefault import new_poddefault
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.utils.httpd import HttpReq
from kubeflow_tpu.webapps.dashboard import Dashboard
from kubeflow_tpu.webapps.jwa import JupyterWebApp

USER = "alice@example.com"


def mkreq(method, path, user=USER, body=None, query=None):
    h = {"kubeflow-userid": user} if user else {}
    b = json.dumps(body).encode() if body is not None else b""
    return HttpReq(method=method, path=path, params={}, query=query or {},
                   headers=h, body=b)


def J(resp):
    assert resp.status < 300, resp.body
    return json.loads(resp.body)


@pytest.fixture()
def cluster():
    c = FakeCluster()
    c.create(ob.new_object("v1", "Namespace", "team-a"))
    return c


class TestJwa:
    @pytest.fixture()
    def jwa(self, cluster):
        return cluster, JupyterWebApp(cluster).router()

    def test_config_and_namespaces(self, jwa):
        cluster, r = jwa
        cfg = J(r.dispatch(mkreq("GET", "/api/config")))["config"]
        assert "tpu" in cfg
        out = J(r.dispatch(mkreq("GET", "/api/namespaces")))
        assert out["namespaces"] == ["team-a"]

    def test_create_notebook_with_tpu_form(self, jwa):
        cluster, r = jwa
        form = {
            "name": "mynb",
            "image": "kubeflow-tpu/jax-notebook-tpu:latest",
            "cpu": "2", "memory": "4Gi",
            "tpu": {"count": 4, "accelerator": "tpu-v5-lite-podslice",
                    "topology": "2x2"},
            "workspaceVolume": {"name": "ws-mynb", "mountPath": "/home/jovyan"},
        }
        out = J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                                 body=form)))
        assert out["name"] == "mynb"
        nb = cluster.get(NT.API_VERSION, NT.KIND, "mynb", "team-a")
        c0 = nb["spec"]["template"]["spec"]["containers"][0]
        assert c0["resources"]["limits"][NT.RESOURCE_TPU] == 4
        sel = nb["spec"]["template"]["spec"]["nodeSelector"]
        assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
        assert sel["cloud.google.com/gke-tpu-topology"] == "2x2"
        assert c0["volumeMounts"][0]["mountPath"] == "/home/jovyan"
        # duplicate -> 409
        assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                                body=form)).status == 409

    def test_cpu_only_form_has_no_tpu(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "cpu-nb"})))
        nb = cluster.get(NT.API_VERSION, NT.KIND, "cpu-nb", "team-a")
        limits = (nb["spec"]["template"]["spec"]["containers"][0]
                  .get("resources", {}).get("limits", {}))
        assert NT.RESOURCE_TPU not in limits

    def test_list_notebooks_status_phases(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "nb1"})))
        rows = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/notebooks")))
        assert rows["notebooks"][0]["status"]["phase"] == "waiting"
        nb = cluster.get(NT.API_VERSION, NT.KIND, "nb1", "team-a")
        nb["status"] = {"readyReplicas": 1}
        cluster.update_status(nb)
        rows = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/notebooks")))
        assert rows["notebooks"][0]["status"]["phase"] == "ready"

    def test_stop_start_notebook(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "nb1"})))
        J(r.dispatch(mkreq("PATCH", "/api/namespaces/team-a/notebooks/nb1",
                           body={"stopped": True})))
        nb = cluster.get(NT.API_VERSION, NT.KIND, "nb1", "team-a")
        assert NT.STOP_ANNOTATION in ob.annotations_of(nb)
        J(r.dispatch(mkreq("PATCH", "/api/namespaces/team-a/notebooks/nb1",
                           body={"stopped": False})))
        nb = cluster.get(NT.API_VERSION, NT.KIND, "nb1", "team-a")
        assert NT.STOP_ANNOTATION not in ob.annotations_of(nb)

    def test_delete_notebook(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                           body={"name": "nb1"})))
        assert r.dispatch(mkreq("DELETE",
                                "/api/namespaces/team-a/notebooks/nb1")).status == 200
        assert r.dispatch(mkreq("DELETE",
                                "/api/namespaces/team-a/notebooks/nb1")).status == 404

    def test_pvcs_and_poddefaults(self, jwa):
        cluster, r = jwa
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/pvcs",
                           body={"name": "data", "size": "20Gi"})))
        pvcs = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/pvcs")))["pvcs"]
        assert pvcs == [{"name": "data", "size": "20Gi", "mode": "ReadWriteOnce"}]
        cluster.create(new_poddefault("tpu-access", "team-a", desc="Mount TPU libs"))
        pds = J(r.dispatch(mkreq("GET",
                                 "/api/namespaces/team-a/poddefaults")))["poddefaults"]
        assert pds == [{"name": "tpu-access", "desc": "Mount TPU libs",
                        "matchLabels": {}}]


class TestDashboard:
    @pytest.fixture()
    def dash(self, cluster):
        kfam = KfamService(cluster, cluster_admin="root@example.com")
        return cluster, Dashboard(cluster, kfam=kfam).router()

    def test_exists_and_create_workgroup(self, dash):
        cluster, r = dash
        assert J(r.dispatch(mkreq("GET", "/api/workgroup/exists")))["hasWorkgroup"] is False
        J(r.dispatch(mkreq("POST", "/api/workgroup/create", body={"namespace": "alice"})))
        assert J(r.dispatch(mkreq("GET", "/api/workgroup/exists")))["hasWorkgroup"] is True
        prof = cluster.get(PT.API_VERSION, PT.KIND, "alice")
        assert prof["spec"]["owner"]["name"] == USER

    def test_env_info_lists_roles(self, dash):
        cluster, r = dash
        J(r.dispatch(mkreq("POST", "/api/workgroup/create", body={"namespace": "alice"})))
        # contributor binding in another namespace
        rb = ob.new_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                           "user-alice-clusterrole-edit", "team-a",
                           annotations={PT.ANNO_USER: USER, PT.ANNO_ROLE: "edit"})
        cluster.create(rb)
        info = J(r.dispatch(mkreq("GET", "/api/workgroup/env-info")))
        assert {"namespace": "alice", "role": "owner"} in info["namespaces"]
        assert {"namespace": "team-a", "role": "edit"} in info["namespaces"]
        assert info["isClusterAdmin"] is False

    def test_get_all_namespaces_admin_only(self, dash):
        _, r = dash
        assert r.dispatch(mkreq("GET", "/api/workgroup/get-all-namespaces")).status == 403
        out = J(r.dispatch(mkreq("GET", "/api/workgroup/get-all-namespaces",
                                 user="root@example.com")))
        assert "team-a" in out["namespaces"]

    def test_contributors_listing(self, dash):
        cluster, r = dash
        for u in ("bob@example.com", "eve@example.com"):
            rb = ob.new_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                               f"user-{u.split('@')[0]}-clusterrole-edit", "team-a",
                               annotations={PT.ANNO_USER: u, PT.ANNO_ROLE: "edit"})
            cluster.create(rb)
        out = J(r.dispatch(mkreq(
            "GET", "/api/workgroup/get-contributors/team-a")))
        assert out["contributors"] == ["bob@example.com", "eve@example.com"]

    def test_nuke_self(self, dash):
        cluster, r = dash
        J(r.dispatch(mkreq("POST", "/api/workgroup/create", body={"namespace": "alice"})))
        out = J(r.dispatch(mkreq("DELETE", "/api/workgroup/nuke-self")))
        assert "1" in out["message"]
        # profile has a finalizer; deletionTimestamp set, reconciler would reap
        prof = cluster.get_or_none(PT.API_VERSION, PT.KIND, "alice")
        assert prof is None or "deletionTimestamp" in ob.meta(prof)

    def test_activities_feed(self, dash):
        cluster, r = dash
        nb = cluster.create(ob.new_object(NT.API_VERSION, NT.KIND, "nb", "team-a",
                                          spec={}))
        cluster.record_event(nb, "Created", "statefulset created")
        out = J(r.dispatch(mkreq("GET", "/api/activities/team-a")))
        assert out["events"][0]["reason"] == "Created"

    def test_tpu_chip_metrics(self, dash):
        cluster, r = dash
        node = ob.new_object("v1", "Node", "tpu-node-1",
                             labels={"cloud.google.com/gke-tpu-accelerator":
                                     "tpu-v5-lite-podslice",
                                     "cloud.google.com/gke-tpu-topology": "2x4"})
        node["status"] = {"capacity": {"cpu": "8", "memory": "32Gi",
                                       "google.com/tpu": "4"}}
        cluster.create(node)
        out = J(r.dispatch(mkreq("GET", "/api/metrics/tpu-chips")))
        assert out["values"] == [{"node": "tpu-node-1", "chips": "4",
                                  "accelerator": "tpu-v5-lite-podslice",
                                  "topology": "2x4"}]
        cpu = J(r.dispatch(mkreq("GET", "/api/metrics/node-cpu")))
        assert cpu["values"][0]["capacity"] == "8"
        assert r.dispatch(mkreq("GET", "/api/metrics/bogus")).status == 404

    def test_unauthenticated_401(self, dash):
        _, r = dash
        assert r.dispatch(mkreq("GET", "/api/workgroup/exists", user=None)).status == 401


def test_dashboard_serves_ui(cluster):
    from kubeflow_tpu.webapps.dashboard import Dashboard

    r = Dashboard(cluster).router()
    page = r.dispatch(mkreq("GET", "/"))
    assert page.status == 200 and page.content_type == "text/html"
    assert b"kubeflow-tpu" in page.body and b"/api/workgroup/env-info" in page.body
    # API routes still reachable alongside the UI route
    assert r.dispatch(mkreq("GET", "/api/workgroup/env-info")).status < 500


def test_jwa_serves_spawner_ui(cluster):
    from kubeflow_tpu.webapps.jwa import JupyterWebApp

    r = JupyterWebApp(cluster).router()
    page = r.dispatch(mkreq("GET", "/"))
    assert page.status == 200 and page.content_type == "text/html"
    # relative path: the spawner is served behind the gateway's
    # /jupyter/ prefix rewrite, so absolute /api/ would miss the app
    assert b"'api/config'" in page.body and b"TPU chips" in page.body
    assert r.dispatch(mkreq("GET", "/api/config")).status == 200


class TestContributorManagement:
    """add/remove-contributor (api_workgroup.ts:189-235,380-385)."""

    @pytest.fixture()
    def world(self, cluster):
        kfam = KfamService(cluster, cluster_admin="root@example.com")
        r = Dashboard(cluster, kfam=kfam).router()
        # alice owns the namespace (KFAM authz checks profile ownership)
        J(r.dispatch(mkreq("POST", "/api/workgroup/create",
                           body={"namespace": "alice"})))
        return cluster, r

    def test_add_contributor_creates_binding_and_returns_list(self, world):
        cluster, r = world
        out = J(r.dispatch(mkreq(
            "POST", "/api/workgroup/add-contributor/alice",
            body={"contributor": "bob@example.com"})))
        assert out["contributors"] == ["bob@example.com"]
        rbs = cluster.list("rbac.authorization.k8s.io/v1", "RoleBinding",
                           namespace="alice")
        assert any(ob.annotations_of(rb).get(PT.ANNO_USER) == "bob@example.com"
                   for rb in rbs)

    def test_remove_contributor(self, world):
        cluster, r = world
        J(r.dispatch(mkreq("POST", "/api/workgroup/add-contributor/alice",
                           body={"contributor": "bob@example.com"})))
        out = J(r.dispatch(mkreq(
            "DELETE", "/api/workgroup/remove-contributor/alice",
            body={"contributor": "bob@example.com"})))
        assert out["contributors"] == []

    def test_invalid_email_rejected(self, world):
        _, r = world
        resp = r.dispatch(mkreq("POST", "/api/workgroup/add-contributor/alice",
                                body={"contributor": "not-an-email"}))
        assert resp.status == 400
        resp = r.dispatch(mkreq("POST", "/api/workgroup/add-contributor/alice",
                                body={}))
        assert resp.status == 400

    def test_non_owner_cannot_add(self, world):
        _, r = world
        resp = r.dispatch(mkreq("POST", "/api/workgroup/add-contributor/alice",
                                user="mallory@example.com",
                                body={"contributor": "bob@example.com"}))
        assert resp.status == 403

    def test_cluster_admin_can_manage_any_namespace(self, world):
        _, r = world
        out = J(r.dispatch(mkreq(
            "POST", "/api/workgroup/add-contributor/alice",
            user="root@example.com",
            body={"contributor": "bob@example.com"})))
        assert out["contributors"] == ["bob@example.com"]


class TestDashboardUiDom:
    """DOM-level assertions on the served SPA (the reference's Polymer
    component tests' shape: registration-page, manage-users-view,
    resource-chart are all present and wired)."""

    @pytest.fixture()
    def page(self, cluster):
        r = Dashboard(cluster).router()
        resp = r.dispatch(mkreq("GET", "/"))
        assert resp.status == 200 and resp.content_type == "text/html"
        return resp.body.decode()

    def test_registration_walkthrough_steps(self, page):
        # five steps, dots, RFC-1123 live validation, create wiring
        for frag in ('data-step="0"', 'data-step="4"', 'id="dots"',
                     "NS_RGX", "/api/workgroup/create"):
            assert frag in page, frag

    def test_manage_contributors_view(self, page):
        for frag in ("add-contributor", "remove-contributor",
                     'id="contrib-email"', 'id="contrib-add"'):
            assert frag in page, frag

    def test_resource_chart_tabs(self, page):
        for frag in ('data-m="tpu-chips"', 'data-m="node-cpu"',
                     'data-m="node-memory"', "/api/metrics/"):
            assert frag in page, frag

    def test_activity_feed_wiring(self, page):
        assert "/api/activities/" in page
        assert "badge" in page


class TestJwaUiDom:
    """DOM-level assertions on the spawner page: volume section,
    configurations, stop/start controls all present and wired."""

    @pytest.fixture()
    def page(self, cluster):
        r = JupyterWebApp(cluster).router()
        resp = r.dispatch(mkreq("GET", "/spawner"))
        assert resp.status == 200 and resp.content_type == "text/html"
        return resp.body.decode()

    def test_volume_section(self, page):
        for frag in ('id="vol-mode"', 'id="pvcs"', "/pvcs",
                     'id="vol-size"', 'id="vol-mount"'):
            assert frag in page, frag

    def test_configurations_section(self, page):
        for frag in ('id="poddefaults"', "/poddefaults", "matchLabels"):
            assert frag in page, frag

    def test_stop_start_and_delete_controls(self, page):
        assert "PATCH" in page and "stopped" in page
        assert "DELETE" in page or "'delete'" in page

    def test_poddefaults_expose_match_labels(self, cluster):
        cluster.create(new_poddefault(
            "add-secret", "team-a", selector={"matchLabels": {"use-secret": "true"}},
            desc="Mount the team secret"))
        r = JupyterWebApp(cluster).router()
        out = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/poddefaults")))
        [pd] = out["poddefaults"]
        assert pd["matchLabels"] == {"use-secret": "true"}


def test_configuration_labels_reach_pod_template_and_webhook():
    """End-to-end: spawner 'configurations' -> notebook labels -> STS pod
    template -> PodDefault admission injection. Guards against the
    labels-only-on-CR no-op failure mode."""
    from kubeflow_tpu.control.notebook.controller import (
        build_controller as build_nb_controller,
    )
    from kubeflow_tpu.control.poddefault import PodDefaultMutator
    from kubeflow_tpu.control.runtime import seed_controller
    from kubeflow_tpu.webapps.jwa import notebook_from_form

    cluster = FakeCluster()
    cluster.create(ob.new_object("v1", "Namespace", "team-a"))
    pd = new_poddefault("tpu-libs", "team-a",
                        selector={"matchLabels": {"tpu-libs": "true"}},
                        desc="Mount libtpu")
    pd["spec"]["env"] = [{"name": "TPU_LIBRARY_PATH", "value": "/lib/libtpu.so"}]
    cluster.create(pd)
    mutator = PodDefaultMutator(cluster)
    cluster.add_admission_hook(mutator.admission_hook)

    # what the spawner form submits when the configuration is checked
    nb = notebook_from_form("team-a", {
        "name": "my-nb", "labels": {"tpu-libs": "true"}})
    # pod-template labels present (not just CR metadata)
    assert nb["spec"]["template"]["metadata"]["labels"]["tpu-libs"] == "true"
    cluster.create(nb)
    ctl = seed_controller(build_nb_controller(cluster))
    for _ in range(4):
        ctl.run_until_idle(advance_delayed=True)
    sts = cluster.get("apps/v1", "StatefulSet", "my-nb", "team-a")
    tmpl = sts["spec"]["template"]
    assert tmpl["metadata"]["labels"]["tpu-libs"] == "true"
    # a pod created from that template gets the PodDefault injection
    pod = ob.new_object("v1", "Pod", "my-nb-0", "team-a",
                        labels=tmpl["metadata"]["labels"],
                        spec=ob.deep_copy(tmpl["spec"]))
    created = cluster.create(pod)
    env = {e["name"]: e.get("value")
           for e in created["spec"]["containers"][0].get("env", [])}
    assert env.get("TPU_LIBRARY_PATH") == "/lib/libtpu.so"


class TestContributorEdgeCases:
    @pytest.fixture()
    def world(self, cluster):
        kfam = KfamService(cluster, cluster_admin="root@example.com")
        r = Dashboard(cluster, kfam=kfam).router()
        J(r.dispatch(mkreq("POST", "/api/workgroup/create",
                           body={"namespace": "alice"})))
        return cluster, r

    def test_non_string_contributor_is_400_not_500(self, world):
        _, r = world
        for bad in (123, True, ["x"],):
            resp = r.dispatch(mkreq(
                "POST", "/api/workgroup/add-contributor/alice",
                body={"contributor": bad}))
            assert resp.status == 400, bad
        resp = r.dispatch(mkreq("POST", "/api/workgroup/add-contributor/alice",
                                body=["not", "a", "dict"]))
        assert resp.status == 400

    def test_remove_uses_the_bindings_actual_role(self, world):
        """A kubeflow-view contributor must be removable, not just edit."""
        cluster, r = world
        from kubeflow_tpu.control.kfam.service import binding_name
        rb = ob.new_object(
            "rbac.authorization.k8s.io/v1", "RoleBinding",
            binding_name("carol@example.com", "view"), "alice",
            annotations={PT.ANNO_USER: "carol@example.com",
                         PT.ANNO_ROLE: "view"})
        cluster.create(rb)
        out = J(r.dispatch(mkreq(
            "DELETE", "/api/workgroup/remove-contributor/alice",
            body={"contributor": "carol@example.com"})))
        assert out["contributors"] == []


class TestNotebooksCard:
    """/api/namespaces/{ns}/notebooks — the notebooks-card.js data source."""

    def test_lists_notebooks_with_status_and_connect_url(self, cluster):
        r = Dashboard(cluster).router()
        nb = NT.new_notebook("my-nb", "team-a", tpu_chips=4)
        cluster.create(nb)
        stored = cluster.get(NT.API_VERSION, NT.KIND, "my-nb", "team-a")
        stored.setdefault("status", {})["containerState"] = \
            {"running": {"startedAt": "2026-07-30T00:00:00Z"}}
        cluster.update(stored)
        out = J(r.dispatch(mkreq(
            "GET", "/api/namespaces/team-a/notebooks")))
        [row] = out["notebooks"]
        assert row["name"] == "my-nb"
        assert row["status"] == "running"
        assert row["tpu_chips"] == 4
        assert row["connect"] == "/notebook/team-a/my-nb/"

    def test_stopped_annotation_wins_over_container_state(self, cluster):
        r = Dashboard(cluster).router()
        nb = NT.new_notebook("idle-nb", "team-a")
        ob.set_annotation(nb, NT.STOP_ANNOTATION, "2026-07-30T00:00:00Z")
        cluster.create(nb)
        out = J(r.dispatch(mkreq(
            "GET", "/api/namespaces/team-a/notebooks")))
        assert out["notebooks"][0]["status"] == "stopped"

    def test_requires_identity(self, cluster):
        r = Dashboard(cluster).router()
        resp = r.dispatch(mkreq(
            "GET", "/api/namespaces/team-a/notebooks", user=None))
        assert resp.status == 401


def test_dashboard_ui_has_nav_and_notebook_card(cluster):
    """The SPA page carries the nav/iframe/not-found views and the
    notebooks card markup (main-page.js / iframe-container.js /
    not-found-view.js / notebooks-card.js analogues)."""
    r = Dashboard(cluster).router()
    page = r.dispatch(mkreq("GET", "/")).body
    for marker in (b'id="appnav"', b'id="app-frame"', b'id="notfound-view"',
                   b'id="notebooks"', b"/api/namespaces/",
                   b"#/tensorboards"):
        assert marker in page, marker


def test_notebooks_listing_survives_null_template_spec(cluster):
    """preserve-unknown-fields CRDs admit spec.template.spec: null; one
    malformed notebook must not 500 the whole namespace listing."""
    r = Dashboard(cluster).router()
    bad = ob.new_object(NT.API_VERSION, NT.KIND, "bad-nb", "team-a")
    bad["spec"] = {"template": {"spec": None}}
    cluster.create(bad)
    out = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/notebooks")))
    assert out["notebooks"][0]["name"] == "bad-nb"
    assert out["notebooks"][0]["image"] == ""


class TestTensorboardsApp:
    """Tensorboards CRUD web app on crud_backend (the next-gen CRUD-app
    pattern of components/crud-web-apps; Tensorboard semantics from
    tensorboard-controller)."""

    @pytest.fixture()
    def app(self, cluster):
        from kubeflow_tpu.webapps.crud_backend import Authorizer
        from kubeflow_tpu.webapps.tensorboards import TensorboardsApp

        cluster.create(PT.new_profile("team-a", USER))
        authz = Authorizer(cluster)
        return cluster, TensorboardsApp(cluster, authz).router()

    def test_create_list_delete_lifecycle(self, app):
        cluster, r = app
        out = J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                                 body={"name": "tb1",
                                       "logspath": "gs://bucket/logs"})))
        assert out["success"] is True
        rows = J(r.dispatch(mkreq(
            "GET", "/api/namespaces/team-a/tensorboards")))["tensorboards"]
        [row] = rows
        assert row["storage"] == "cloud"
        assert row["phase"] == "waiting"
        assert row["connect"] == "/tensorboard/team-a/tb1/"
        # controller marks Ready -> phase flips
        from kubeflow_tpu.control.tensorboard import API_VERSION, KIND
        tb = cluster.get(API_VERSION, KIND, "tb1", "team-a")
        ob.cond_set(tb, "Ready", "True", "DeploymentReady")
        cluster.update_status(tb)
        rows = J(r.dispatch(mkreq(
            "GET", "/api/namespaces/team-a/tensorboards")))["tensorboards"]
        assert rows[0]["phase"] == "ready"
        J(r.dispatch(mkreq("DELETE",
                           "/api/namespaces/team-a/tensorboards/tb1")))
        assert J(r.dispatch(mkreq(
            "GET", "/api/namespaces/team-a/tensorboards")))["tensorboards"] == []

    def test_validation_and_conflicts(self, app):
        _, r = app
        assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                                body={"name": "Bad_Name",
                                      "logspath": "gs://x"})).status == 400
        assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                                body={"name": "tb1"})).status == 400
        ok = {"name": "tb1", "logspath": "/pvc/logs"}
        J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                           body=ok)))
        assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                                body=ok)).status == 409
        assert r.dispatch(mkreq(
            "DELETE", "/api/namespaces/team-a/tensorboards/nope")).status == 404
        # pvc path reported as pvc storage
        rows = J(r.dispatch(mkreq(
            "GET", "/api/namespaces/team-a/tensorboards")))["tensorboards"]
        assert rows[0]["storage"] == "pvc"

    def test_authz_denies_stranger(self, app):
        _, r = app
        resp = r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                                body={"name": "tb2", "logspath": "gs://x"},
                                user="mallory@example.com"))
        assert resp.status == 403
        assert r.dispatch(mkreq("GET", "/api/namespaces/team-a/tensorboards",
                                user=None)).status == 401

    def test_shared_crud_routes_present(self, app):
        _, r = app
        assert J(r.dispatch(mkreq("GET", "/api/namespaces")))  # crud_backend
        page = r.dispatch(mkreq("GET", "/"))
        assert page.status == 200
        assert b"New tensorboard" in page.body and b"/tensorboards" in page.body


def test_tensorboard_validation_rejects_relative_path_and_nonstring(cluster):
    from kubeflow_tpu.webapps.tensorboards import TensorboardsApp

    r = TensorboardsApp(cluster).router()
    # relative logspath would render a non-absolute mountPath the
    # apiserver rejects — must 400, not create a stuck tensorboard
    assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                            body={"name": "tb", "logspath": "my/logs"})
                      ).status == 400
    assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                            body={"name": 123, "logspath": "gs://x"})
                      ).status == 400
    assert r.dispatch(mkreq("POST", "/api/namespaces/team-a/tensorboards",
                            body={"name": "tb", "logspath": 9})
                      ).status == 400


def test_manifests_route_webapp_prefixes_through_gateway():
    """The dashboard iframes /jupyter/ and /tensorboards/; the platform
    manifests must ship gateway VirtualServices for those prefixes (and
    the dashboard catch-all) or the tabs 404."""
    from kubeflow_tpu.tpctl.manifests import render
    from kubeflow_tpu.tpctl.tpudef import TpuDef

    objs = render(TpuDef(use_istio=True))
    [vs] = [o for o in objs if o.get("kind") == "VirtualService"]
    # ONE VirtualService, most-specific prefix first: Istio's merge order
    # across VSes on the same host is non-deterministic, so a separate
    # '/' catch-all could shadow the app prefixes
    assert ob.meta(vs)["name"] == "kubeflow-webapps"
    rules = vs["spec"]["http"]
    app_rules = {r["route"][0]["destination"]["host"].split(".")[0]: r
                 for r in rules}
    for name in ("jupyter-web-app", "tensorboards-web-app"):
        assert app_rules[name]["rewrite"] == {"uri": "/"}
    assert app_rules["jupyter-web-app"]["match"][0]["uri"] == \
        {"prefix": "/jupyter/"}
    # the dashboard enumerates its surfaces instead of a '/' prefix
    # catch-all, which could shadow the controllers' per-resource
    # /notebook/... VirtualServices under Istio's cross-VS merge order
    dash = app_rules["centraldashboard"]
    assert "rewrite" not in dash
    dash_uris = dash["match"]
    assert {"uri": {"exact": "/"}} in dash_uris
    assert {"uri": {"prefix": "/api/"}} in dash_uris
    assert not any(m["uri"].get("prefix") == "/" for m in dash_uris)
    # app prefixes come before the dashboard rule
    assert rules.index(app_rules["jupyter-web-app"]) < rules.index(dash)
    # istio off -> no webapp VirtualServices rendered
    objs_plain = render(TpuDef(use_istio=False))
    assert not [o for o in objs_plain if o.get("kind") == "VirtualService"]


class TestJaxjobsCard:
    """/api/namespaces/{ns}/jaxjobs — the dashboard's training-jobs
    card (TPU-native analogue of the reference's workload cards)."""

    def test_lists_jobs_with_phase_and_counters(self, cluster):
        from kubeflow_tpu.control.jaxjob import types as JT

        r = Dashboard(cluster).router()
        job = JT.new_jaxjob("train", namespace="team-a", replicas=4,
                            accelerator="tpu-v5-lite-podslice",
                            topology="2x2", chips_per_worker=4)
        cluster.create(job)
        stored = cluster.get(JT.API_VERSION, JT.KIND, "train", "team-a")
        ob.cond_set(stored, JT.COND_RUNNING, "True", "AllWorkersRunning")
        stored.setdefault("status", {}).update(
            {"restarts": 1, "preemptions": 2})
        cluster.update(stored)
        out = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/jaxjobs")))
        [row] = out["jaxjobs"]
        assert row["phase"] == "running"
        assert row["replicas"] == 4
        assert row["restarts"] == 1 and row["preemptions"] == 2

    def test_terminal_phases(self, cluster):
        from kubeflow_tpu.control.jaxjob import types as JT

        r = Dashboard(cluster).router()
        for name, cond in (("ok", JT.COND_SUCCEEDED), ("bad", JT.COND_FAILED)):
            j = JT.new_jaxjob(name, namespace="team-a")
            ob.cond_set(j, cond, "True", "x")
            cluster.create(j)
        out = J(r.dispatch(mkreq("GET", "/api/namespaces/team-a/jaxjobs")))
        phases = {row["name"]: row["phase"] for row in out["jaxjobs"]}
        assert phases == {"ok": "succeeded", "bad": "failed"}


def test_jwa_spawner_config_from_yaml(cluster, tmp_path, monkeypatch):
    """spawner_ui_config.yaml contract: admin YAML deep-merges over the
    built-in defaults and drives both /api/config and form fallbacks."""
    import yaml as _yaml

    from kubeflow_tpu.webapps.jwa import load_spawner_config

    cfg_file = tmp_path / "spawner_ui_config.yaml"
    cfg_file.write_text(_yaml.safe_dump({
        "spawnerFormDefaults": {
            "image": {"value": "corp/jax:2.0"},
            "memory": {"value": "8Gi"},
        }}))
    monkeypatch.setenv("JWA_CONFIG", str(cfg_file))
    app = JupyterWebApp(cluster)
    r = app.router()
    cfg = J(r.dispatch(mkreq("GET", "/api/config")))["config"]
    assert cfg["image"]["value"] == "corp/jax:2.0"
    assert cfg["memory"]["value"] == "8Gi"
    # untouched keys survive the merge
    assert cfg["tpu"]["options"] == [0, 1, 4, 8]
    # the overridden default reaches created notebooks
    J(r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                       body={"name": "nb1"})))
    nb = cluster.get(NT.API_VERSION, NT.KIND, "nb1", "team-a")
    assert nb["spec"]["template"]["spec"]["containers"][0]["image"] == \
        "corp/jax:2.0"
    # without the env var: pure defaults
    monkeypatch.delenv("JWA_CONFIG")
    assert load_spawner_config()["image"]["value"] == \
        "kubeflow-tpu/jax-notebook:latest"


class TestServingCard:
    def test_proxies_model_inventory(self, cluster):
        r = Dashboard(cluster, fetch_json=lambda url: {
            "models": [{"name": "mnist", "versions": [1],
                        "method": "predict", "micro_batching": False}]
        }).router()
        out = J(r.dispatch(mkreq("GET", "/api/serving/models")))
        assert out["models"][0]["name"] == "mnist"

    def test_degrades_when_serving_unreachable(self, cluster):
        def boom(url):
            raise OSError("connection refused")

        r = Dashboard(cluster, fetch_json=boom).router()
        out = J(r.dispatch(mkreq("GET", "/api/serving/models")))
        assert out["models"] == [] and "refused" in out["error"]

    def test_requires_identity(self, cluster):
        r = Dashboard(cluster, fetch_json=lambda u: {"models": []}).router()
        assert r.dispatch(mkreq("GET", "/api/serving/models",
                                user=None)).status == 401


class TestJwaFlavors:
    """UI-flavor dispatch (reference main.py:12-29 UI=default|rok): the
    snapshot flavor overrides the notebook POST and adds the token
    endpoint, reshaped from Rok block snapshots to object storage."""

    def _app(self, flavor="snapshot"):
        from kubeflow_tpu.webapps.jwa import JupyterWebApp

        cluster = FakeCluster()
        return cluster, JupyterWebApp(cluster, flavor=flavor).router()

    def test_unknown_flavor_fails_loudly(self, monkeypatch):
        from kubeflow_tpu.webapps.jwa_flavors import select_flavor

        with pytest.raises(ValueError):
            select_flavor({"UI": "nope"})
        assert select_flavor({}) == "default"
        assert select_flavor({"UI": "snapshot"}) == "snapshot"

    def test_snapshot_url_annotates_notebook(self):
        from kubeflow_tpu.webapps.jwa_flavors import ANNO_SNAPSHOT_SRC

        cluster, r = self._app()
        resp = r.dispatch(mkreq(
            "POST", "/api/namespaces/team-a/notebooks",
            body={"name": "snap-nb", "snapshotUrl": "gs://bkt/ws/alice/"}))
        assert resp.status == 200, resp.body
        nb = cluster.get("kubeflow.org/v1beta1", "Notebook", "snap-nb",
                         "team-a")
        assert ob.annotations_of(nb)[ANNO_SNAPSHOT_SRC] == "gs://bkt/ws/alice/"

    def test_bad_snapshot_url_is_400(self):
        cluster, r = self._app()
        resp = r.dispatch(mkreq(
            "POST", "/api/namespaces/team-a/notebooks",
            body={"name": "snap-nb", "snapshotUrl": "http://evil"}))
        assert resp.status == 400
        assert not cluster.list("kubeflow.org/v1beta1", "Notebook",
                                namespace="team-a")

    def test_token_endpoint_reads_secret(self):
        import base64

        cluster, r = self._app()
        out = J(r.dispatch(mkreq(
            "GET", "/api/snapshot/namespaces/team-a/token")))
        assert out["success"] is False and out["token"]["value"] == ""
        sec = ob.new_object("v1", "Secret", "snapshot-access", "team-a")
        sec["data"] = {"token": base64.b64encode(b"s3cret").decode()}
        cluster.create(sec)
        out = J(r.dispatch(mkreq(
            "GET", "/api/snapshot/namespaces/team-a/token")))
        assert out["success"] is True
        assert out["token"]["value"] == "s3cret"

    def test_default_flavor_has_no_snapshot_surface(self):
        cluster, r = self._app(flavor="default")
        resp = r.dispatch(mkreq(
            "GET", "/api/snapshot/namespaces/team-a/token"))
        assert resp.status == 404
        # snapshotUrl silently ignored (no annotation) on default flavor
        r.dispatch(mkreq("POST", "/api/namespaces/team-a/notebooks",
                         body={"name": "plain", "snapshotUrl": "gs://x/"}))
        nb = cluster.get("kubeflow.org/v1beta1", "Notebook", "plain",
                         "team-a")
        from kubeflow_tpu.webapps.jwa_flavors import ANNO_SNAPSHOT_SRC

        assert ANNO_SNAPSHOT_SRC not in ob.annotations_of(nb)
