"""Notebook image version matrix + contrib (reference:
tensorflow-notebook-image/versions 30-variant layout + components/contrib)."""

import os

from kubeflow_tpu.release.image_matrix import (
    CONTRIB_STACKS,
    NOTEBOOK_JAX_VERSIONS,
    all_images,
    contrib_images,
    notebook_matrix,
    render_versions,
)
from kubeflow_tpu.release.releaser import (
    IMAGES,
    build_commands,
    release_workflow,
)


class TestMatrix:
    def test_every_version_gets_cpu_and_tpu_variants(self):
        specs = notebook_matrix()
        assert len(specs) == len(NOTEBOOK_JAX_VERSIONS) * 2
        names = {s.name for s in specs}
        for v in NOTEBOOK_JAX_VERSIONS:
            assert f"jax-notebook-jax-{v}" in names       # cpu
            assert f"jax-notebook-jax-{v}-tpu" in names   # tpu

    def test_build_args_pin_version_and_variant(self):
        [spec] = [s for s in notebook_matrix()
                  if s.name == "jax-notebook-jax-0.7-tpu"]
        [cmd] = build_commands(spec, "gcr.io/kf", "v1")
        assert "--build-arg" in cmd
        assert "JAX_VERSION=0.7" in cmd and "JAX_EXTRA=tpu" in cmd

    def test_contrib_images_layer_extra_pip(self):
        specs = contrib_images()
        assert {s.name for s in specs} == {
            "jax-notebook-" + n for n in CONTRIB_STACKS}
        for s in specs:
            args = dict(s.build_args)
            assert args["EXTRA_PIP"] == CONTRIB_STACKS[
                s.name.removeprefix("jax-notebook-")]

    def test_all_images_includes_core_matrix_and_contrib(self):
        every = all_images()
        names = [s.name for s in every]
        assert len(names) == len(set(names))  # no duplicate image names
        for s in IMAGES:
            assert s.name in names
        assert len(every) == len(IMAGES) + len(notebook_matrix()) + \
            len(contrib_images())

    def test_release_workflow_builds_the_whole_matrix(self):
        ran = []
        wf = release_workflow("gcr.io/kf", "v1", images=all_images(),
                              runner=lambda cmd: ran.append(cmd), push=False)
        wf.run()
        builds = [c for c in ran if c[:2] == ["docker", "build"]]
        assert len(builds) == len(all_images())


class TestRenderVersions(object):
    def test_renders_pinned_stub_per_variant(self, tmp_path):
        # copy the real parent Dockerfile into a scratch tree
        src = os.path.join(os.path.dirname(__file__), "..", "images",
                           "notebook", "Dockerfile")
        d = tmp_path / "images" / "notebook"
        d.mkdir(parents=True)
        (d / "Dockerfile").write_text(open(src).read())
        written = render_versions(str(tmp_path))
        assert len(written) == len(NOTEBOOK_JAX_VERSIONS) * 2 + \
            len(CONTRIB_STACKS)
        pinned = (tmp_path / "images" / "notebook" / "versions" /
                  "jax-0.6-tpu" / "Dockerfile").read_text()
        assert "ARG JAX_VERSION=0.6" in pinned
        assert "ARG JAX_EXTRA=tpu" in pinned
        llm = (tmp_path / "images" / "notebook" / "versions" / "llm" /
               "Dockerfile").read_text()
        assert 'ARG EXTRA_PIP="transformers datasets sentencepiece"' in llm

    def test_repo_tree_matrix_is_current(self):
        """The committed versions/ tree matches the generator (like the
        reference keeping versions/ in sync with its template)."""
        root = os.path.join(os.path.dirname(__file__), "..")
        vdir = os.path.join(root, "images", "notebook", "versions")
        assert os.path.isdir(vdir), "run render_versions to materialize"
        expected = len(NOTEBOOK_JAX_VERSIONS) * 2 + len(CONTRIB_STACKS)
        assert len(os.listdir(vdir)) == expected
