"""Driver-contract dryrun at the n=16 tier (slow).

The driver itself validates dryrun_multichip(8); this covers the larger
tier the driver does not run: a 16-device virtual mesh where the composed
4-factor config G (dcn x dp x pp x tp, pp >= 2 guaranteed) exists. The
wrapper's partitioner-warning gate applies, so this also asserts every
config compiles without GSPMD involuntary rematerialization/replication
(VERDICT r3 #7). Runs in a subprocess (the wrapper re-execs with
JAX_PLATFORMS=cpu and the 16-device flag before jax initializes).
"""

import pytest

import __graft_entry__ as graft


@pytest.mark.slow
def test_dryrun_multichip_16_green_and_warning_clean():
    graft.dryrun_multichip(16)


def test_spmd_equivalence_parity():
    """The self-certifying SPMD statement (VERDICT r4 weak #6): one
    model/seed/batch reaches the same loss under dp, dp·tp·sp and
    fsdp·accum layouts — forward parity at step 1, gradient-path parity
    at step 2."""
    graft.assert_spmd_parity(graft.spmd_equivalence_losses(8))


def test_moe_dispatch_equivalence_parity():
    """EP contract: the sparse sort+all_to_all dispatch must match the
    dense one-hot-einsum oracle on the same model/seed/batch — logits,
    post-update params and losses (measured spread ~6e-8 in f32)."""
    graft.assert_spmd_parity(graft.moe_equivalence_losses(8))


def test_moe_equivalence_catches_dropped_all_to_all(monkeypatch):
    """Neutering the expert all_to_all (each shard silently keeps its
    own capacity buffers — shapes intact, tokens routed to the wrong
    experts' weights) must trip the parity assertion."""
    import jax

    monkeypatch.setattr(
        jax.lax, "all_to_all",
        lambda x, axis_name, split_axis, concat_axis, tiled=False: x)
    losses = graft.moe_equivalence_losses(8)
    with pytest.raises(AssertionError, match="SPMD parity violated"):
        graft.assert_spmd_parity(losses)


def test_spmd_equivalence_catches_dropped_collective(monkeypatch):
    """The contract must FAIL when a sharding bug is injected: neutering
    ring attention's ppermute (each shard silently attends only its local
    K/V — shapes intact, numbers wrong) has to trip the parity
    assertion. Guards against the contract degenerating into
    'execution succeeded'."""
    import jax

    monkeypatch.setattr(jax.lax, "ppermute",
                        lambda x, axis_name, perm: x)
    losses = graft.spmd_equivalence_losses(8)
    with pytest.raises(AssertionError, match="SPMD parity violated"):
        graft.assert_spmd_parity(losses)
