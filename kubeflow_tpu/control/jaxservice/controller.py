"""JAXService controller: replicated model serving with queue-driven
autoscaling and drain-before-delete scale-down.

The serving analogue of the JAXJob controller (ROADMAP #2). One
reconcile loop owns four responsibilities:

- **Provisioning**: keep exactly ``status.targetReplicas`` replica pods
  (``<svc>-replica-<i>``) running the model server
  (``serving/__main__.py``), each a gang of ONE for the gang scheduler
  when ``spec.schedulerName`` opts in — replicas admit independently
  (a fleet takes every replica it can get; all-or-nothing is a
  training-world law), but inherit slice placement, priority and
  spot-pool preference. A replica that dies (node loss, eviction,
  crash) is reaped and re-provisioned at the same index.
- **Endpoints**: the READY replica set is published on the JAXService's
  ``ANNOTATION_ENDPOINTS`` annotation — the downward-style feed the
  token router consumes (``serving/router.py``, the ONE spelling).
  Cordoned replicas stay listed as ``state=cordoned`` so the router
  keeps draining them without admitting new work.
- **Autoscaling**: ``status.targetReplicas`` moves between
  ``spec.replicas.min`` and ``.max`` on two router-exported signals
  read back from the MetricsRegistry exposition (PR 4):
  ``router_queue_depth`` (queued requests per replica the service
  tolerates) and the ``router_tokens_total`` rate (tokens/sec vs the
  per-replica throughput target). Both directions are HYSTERETIC: a
  scale-up needs the demand to persist for
  ``scaleUpStabilizationSeconds``, a scale-down for the (longer)
  ``scaleDownStabilizationSeconds`` — and scale-down steps ONE replica
  at a time, so a demand lull never mass-cordons the fleet. The target
  is durable in status before any pod is touched (the _gang_restart
  record-FIRST discipline), so interrupted scale operations re-enter
  idempotently.
- **Drain state machine** (scale-down): active → cordoned (the pod is
  annotated, the endpoints entry flips to ``cordoned``, the router
  stops new dispatch) → drained (the router's
  ``router_tokens_inflight{replica}`` gauge reads zero) → deleted.
  In-flight requests always finish; docs/serving.md draws the diagram.

Every reconcile wraps its decision pass in a ``jaxservice.reconcile``
span under the service's minted traceparent; the router's
``router.dispatch`` spans ride each request's own traceparent — one
timeline from client request through dispatch to the replica.
"""

from __future__ import annotations

import logging
import math
import time

import prometheus_client as prom

from kubeflow_tpu.control import reconcilehelper as rh
from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxservice import types as T
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.runtime import Controller, Reconciler, Request, Result
from kubeflow_tpu.control.scheduler import (
    ANNOTATION_GANG_SIZE, ANNOTATION_PRIORITY, GATE_GANG, SCHEDULER_NAME,
)
from kubeflow_tpu.control.scheduler.topology import parse_topology
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.runtime.metrics import (
    REGISTRY,
    MetricsRegistry,
    prom_metric as _metric,
)
from kubeflow_tpu.serving.router import render_endpoints

log = logging.getLogger("kubeflow_tpu.jaxservice")

# Re-provision pacing: deletes need their names freed before recreation
_REQUEUE_FAST = 0.05
# Steady-state autoscale poll (the registry signals are pull-only)
_REQUEUE_POLL = 0.5

REPLICA_STATES = ("desired", "ready", "pending", "cordoned")


def replicas_gauge():
    return _metric("jaxservice_replicas", prom.Gauge,
                   "replica counts by state (desired/ready/pending/"
                   "cordoned) per service",
                   labelnames=("service", "state"))


def scales_total():
    return _metric("jaxservice_scale_total", prom.Counter,
                   "autoscaler target moves by direction",
                   labelnames=("direction",))


def replica_restarts_total():
    return _metric("jaxservice_replica_restarts_total", prom.Counter,
                   "replicas reaped and re-provisioned after dying")


# numeric encoding for the jaxservice_rollout_phase gauge
ROLLOUT_PHASE_VALUE = {p: i for i, p in enumerate(T.ROLLOUT_PHASES)}


def rollouts_total():
    return _metric("jaxservice_rollouts_total", prom.Counter,
                   "rollouts finished by outcome "
                   "(promoted/rolled_back/aborted)",
                   labelnames=("service", "outcome"))


def rollout_phase_gauge():
    return _metric("jaxservice_rollout_phase", prom.Gauge,
                   "rollout state-machine position "
                   "(0=idle 1=surge 2=analyze 3=promote 4=rollback)",
                   labelnames=("service",))


class JAXServiceReconciler(Reconciler):
    def __init__(self, record_events: bool = True,
                 registry: MetricsRegistry | None = None,
                 signals=None, clock=time.monotonic, cache=None,
                 store=None, rollout_analysis=None):
        self.record_events = record_events
        self.registry = registry if registry is not None else REGISTRY
        # autoscaling signal source (serving.router.RegistrySignals
        # shape); None = no signal plane wired -> the service holds at
        # status.targetReplicas (still min/max-clamped) and a Running
        # cordoned replica is held for spec.drainSeconds before delete
        # (the router routes to the fleet whether or not the controller
        # can read its gauges — "nothing wired = drained" would delete
        # replicas with live decodes in flight)
        self.signals = signals
        self.clock = clock
        self.cache = cache
        # optional obs TimeSeriesStore for PREDICTIVE autoscaling: when
        # wired (and on the same clock), the scale-up demand projects
        # the queue-depth trend over the stabilization window instead
        # of reading only the instantaneous depth — killing the lag
        # where a steadily-growing queue waits a full window before the
        # first move. None (the default, and every pre-existing caller)
        # keeps the instantaneous behavior bit-for-bit: BENCH_SERVE_r01
        # replays identically.
        self.store = store
        # per-service autoscaler memory: tokens-rate sample and the
        # hysteresis pending-direction window. In-memory on purpose — a
        # controller restart just re-observes demand for one window.
        self._scale_state: dict[tuple[str, str], dict] = {}
        # the canary-analysis gate: callable(namespace, service,
        # baseline_rev, canary_rev, now) -> bool (healthy). None =
        # rollouts advance on the time ladder alone (no analysis
        # plane wired). obs/rules.py CanaryAnalysis matches the shape.
        self.rollout_analysis = rollout_analysis
        # cordon observation times for the signal-less drain grace,
        # keyed (namespace, pod) — the LEGACY fallback: the durable
        # path persists the deadline as a pod annotation
        # (ANNOTATION_DRAIN_DEADLINE), so controller restarts resume
        # the countdown instead of restarting it.
        self._drain_started: dict[tuple[str, str], float] = {}
        # services whose jaxservice_rollouts_total outcome labels are
        # pre-registered at 0 (the first-failure tripwire discipline)
        self._rollout_registered: set[tuple[str, str]] = set()

    # -- trace propagation (the jaxjob discipline) --------------------------

    def _ensure_traceparent(self, client, svc: dict) -> dict:
        m = ob.meta(svc)
        if (m.get("annotations") or {}).get(obs_trace.TRACEPARENT_ANNOTATION):
            return svc
        ctx = obs_trace.SpanContext(
            obs_trace.new_trace_id(), obs_trace.new_span_id())
        # rv precondition: two racing first reconciles must not both
        # mint a context (jaxjob controller: the loser 409s, benign)
        return client.patch(
            T.API_VERSION, T.KIND, m["name"],
            {"metadata": {
                "resourceVersion": m["resourceVersion"],
                "annotations": {
                    obs_trace.TRACEPARENT_ANNOTATION: ctx.to_traceparent()}}},
            m["namespace"])

    def _svc_context(self, svc: dict) -> obs_trace.SpanContext | None:
        return obs_trace.parse_traceparent(
            (ob.meta(svc).get("annotations") or {})
            .get(obs_trace.TRACEPARENT_ANNOTATION))

    # -- generate* ----------------------------------------------------------

    def generate_service(self, svc: dict) -> dict:
        """Headless service: stable per-replica DNS
        (<pod>.<svc>.<ns>.svc) — the router's endpoint addresses."""
        m = ob.meta(svc)
        port = (svc.get("spec") or {}).get("port", T.DEFAULT_PORT)
        return ob.new_object(
            "v1", "Service", m["name"], m["namespace"],
            labels={T.LABEL_SERVICE_NAME: m["name"]},
            spec={
                "clusterIP": "None",
                "selector": {T.LABEL_SERVICE_NAME: m["name"]},
                "ports": [{"name": "http-serving", "port": port}],
            },
        )

    def _model_command(self, spec: dict) -> list[str]:
        model = T.model_spec(spec)
        cmd = ["python", "-m", "kubeflow_tpu.serving",
               "--port", str(spec.get("port", T.DEFAULT_PORT)),
               "--lm", f"{model['name']}={model['ref']}",
               "--prompt-len", str(model["promptLen"]),
               "--max-new-tokens", str(model["maxNewTokens"])]
        if model["continuousBatching"]:
            cmd += ["--continuous-batching",
                    "--decode-slots", str(model["decodeSlots"])]
        if model["paramDtype"]:
            cmd += ["--param-dtype", model["paramDtype"]]
        res = T.resilience_spec(spec)
        if res["maxInflight"]:
            # replica-side overload gate: beyond this many concurrent
            # requests the server 429s with Retry-After instead of
            # queueing unboundedly (docs/robustness.md)
            cmd += ["--max-inflight", str(res["maxInflight"])]
        return cmd

    def generate_pod(self, svc: dict, index: int,
                     revision: str | None = None) -> dict:
        m = ob.meta(svc)
        spec = svc.get("spec") or {}
        # revision pinning: a rollout provisions pods for a SPECIFIC
        # revision — when it is not the live spec's (surge pods while
        # the base still runs the old revision, or a rollback after the
        # spec moved on), generate from the status snapshot that minted
        # it. Default (None) shapes from the live spec.
        rev = revision if revision is not None else T.revision_hash(spec)
        if revision is not None and T.revision_hash(spec) != revision:
            snap = T.revisions_status(svc)["snapshots"].get(revision)
            if isinstance(snap, dict):
                spec = snap
        name = T.replica_name(m["name"], index)
        tmpl = ob.deep_copy(spec.get("template") or {"spec": {"containers": [
            {"name": "serving", "image": spec.get(
                "image", "kubeflow-tpu/platform:latest")}]}})
        pod_spec = tmpl.setdefault("spec", {})
        pod_spec.setdefault("restartPolicy", "Never")
        pod_spec["hostname"] = name
        pod_spec["subdomain"] = m["name"]
        env = [
            {"name": T.ENV_SERVICE, "value": m["name"]},
            {"name": T.ENV_REPLICA, "value": str(index)},
            {"name": T.ENV_NAMESPACE, "value": m["namespace"]},
        ]
        traceparent = (m.get("annotations") or {}).get(
            obs_trace.TRACEPARENT_ANNOTATION)
        if traceparent:
            env.append({"name": obs_trace.TRACEPARENT_ENV,
                        "value": traceparent})
        tpu = spec.get("tpu") or {}
        for c in pod_spec.get("containers", []):
            c.setdefault("command", self._model_command(spec))
            have = {e["name"] for e in c.get("env", [])}
            c.setdefault("env", []).extend(
                e for e in env if e["name"] not in have)
            if tpu.get("chipsPerWorker"):
                res = c.setdefault("resources", {}).setdefault("limits", {})
                res.setdefault(JT.RESOURCE_TPU, tpu["chipsPerWorker"])
        if tpu.get("accelerator"):
            sel = pod_spec.setdefault("nodeSelector", {})
            sel.setdefault(JT.NODESELECTOR_ACCEL, tpu["accelerator"])
            if tpu.get("topology"):
                try:
                    topo = str(parse_topology(tpu["topology"]))
                except ValueError:
                    topo = tpu["topology"]  # validate() reports this
                sel.setdefault(JT.NODESELECTOR_TOPOLOGY, topo)
        labels = {
            **(tmpl.get("metadata", {}).get("labels") or {}),
            T.LABEL_SERVICE_NAME: m["name"],
            T.LABEL_REPLICA_INDEX: str(index),
            T.LABEL_REVISION: rev,
        }
        annotations = dict(tmpl.get("metadata", {}).get("annotations") or {})
        if spec.get("schedulerName"):
            pod_spec["schedulerName"] = spec["schedulerName"]
        if spec.get("schedulerName") == SCHEDULER_NAME:
            # each replica is its own gang of ONE: the scheduler keys
            # gangs on the jaxjob gang label, so the pod's own name is
            # the gang — independent admission per replica, topology
            # feasibility and priority still enforced. Gate appended,
            # never setdefault (the jaxjob lesson: a template gate must
            # not displace ours).
            labels[JT.LABEL_JOB_NAME] = name
            gates = list(pod_spec.get("schedulingGates") or [])
            if not any(g.get("name") == GATE_GANG for g in gates):
                gates.append({"name": GATE_GANG})
            pod_spec["schedulingGates"] = gates
            annotations[ANNOTATION_GANG_SIZE] = "1"
            annotations[ANNOTATION_PRIORITY] = str(spec.get("priority", 0))
        if traceparent:
            annotations[obs_trace.TRACEPARENT_ANNOTATION] = traceparent
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": m["namespace"],
                "labels": labels,
                "annotations": annotations,
            },
            "spec": pod_spec,
        }

    # -- pod reads ----------------------------------------------------------

    @staticmethod
    def _write_status(client, svc: dict) -> None:
        """update_status + rv rebind: a reconcile writes status more
        than once (scale move, restart count, final publish) and the
        fake apiserver 409s any write carrying a stale rv."""
        resp = client.update_status(svc)
        ob.meta(svc)["resourceVersion"] = ob.meta(resp)["resourceVersion"]

    def _pods(self, client, namespace: str, name: str) -> list[dict]:
        if self.cache is not None:
            return self.cache.pods_by_label(
                T.LABEL_SERVICE_NAME, namespace, name)
        return client.list(
            "v1", "Pod", namespace=namespace,
            label_selector={"matchLabels": {T.LABEL_SERVICE_NAME: name}})

    @staticmethod
    def _cordoned(pod: dict) -> bool:
        return ob.annotations_of(pod).get(T.ANNOTATION_CORDON) == "true"

    @staticmethod
    def _pod_revision(pod: dict) -> str:
        return ((ob.meta(pod).get("labels") or {})
                .get(T.LABEL_REVISION, ""))

    def _cordon_pod(self, client, req, name: str, drain_s: float) -> dict:
        """Cordon a pod AND stamp its drain DEADLINE (now + grace, on
        the controller clock) as an annotation — durable drain grace:
        a restarted controller resumes the countdown from the pod
        instead of restarting its in-memory timer. Raises NotFound
        like a bare patch would."""
        deadline = self.clock() + drain_s
        return client.patch(
            "v1", "Pod", name,
            {"metadata": {"annotations": {
                T.ANNOTATION_CORDON: "true",
                T.ANNOTATION_DRAIN_DEADLINE: f"{deadline:.6f}"}}},
            req.namespace)

    def _replica_drained(self, namespace: str, service: str,
                         pod: dict, drain_s: float) -> bool:
        """Delete gate for a cordoned replica: a pod that is not
        Running holds no connections; a Running one must read zero on
        the router's in-flight gauge, or — when no signal plane is
        wired (the production run_controller default) — outlive the
        spec.drainSeconds grace. The grace is read from the pod's
        persisted deadline annotation when present (controller
        restarts RESUME the countdown); legacy cordons without one
        fall back to the in-memory timer, which a restart restarts —
        only ever draining LONGER. The router keeps routing regardless
        of the controller's gauge access, so signal-less can never
        mean "nothing in flight"."""
        if (pod.get("status") or {}).get("phase") != "Running":
            return True
        name = ob.meta(pod)["name"]
        if self.signals is not None:
            return self.signals.replica_drained(namespace, service, name)
        now = self.clock()
        raw = ob.annotations_of(pod).get(T.ANNOTATION_DRAIN_DEADLINE)
        if raw is not None:
            try:
                deadline = float(raw)
            except (TypeError, ValueError):
                deadline = None
            # a deadline further out than one full grace means the
            # clock rebased under the annotation (the controller moved
            # hosts; monotonic clocks are boot-relative) — fall through
            # to the in-memory grace rather than holding forever
            if deadline is not None and deadline - now <= drain_s:
                return now >= deadline
        key = (namespace, name)
        started = self._drain_started.setdefault(key, now)
        return now - started >= drain_s

    # -- rollout state machine ----------------------------------------------

    def _register_rollout_metrics(self, req) -> None:
        """Pre-register every rollout outcome at 0 on first sight of a
        service, so ``rate()``/``increase()`` have a zero sample BEFORE
        the first abort (the first-failure tripwire discipline)."""
        key = (req.namespace, req.name)
        if key in self._rollout_registered:
            return
        self._rollout_registered.add(key)
        for outcome in T.ROLLOUT_OUTCOMES:
            self.registry.counter_inc(
                "jaxservice_rollouts_total", by=0.0,
                help_="rollouts finished by outcome "
                      "(promoted/rolled_back/aborted)",
                namespace=req.namespace, service=req.name,
                tenant=req.namespace, outcome=outcome)
            rollouts_total().labels(req.name, outcome).inc(0)

    def _rollout_outcome(self, req, outcome: str) -> None:
        self.registry.counter_inc(
            "jaxservice_rollouts_total",
            help_="rollouts finished by outcome "
                  "(promoted/rolled_back/aborted)",
            namespace=req.namespace, service=req.name,
            tenant=req.namespace, outcome=outcome)
        rollouts_total().labels(req.name, outcome).inc()

    def _abort_rollout(self, client, svc, req, rev, now: float) -> None:
        """Failed analysis with autoRollback: flip the machine to
        Rollback toward the previous revision, pin the bad revision as
        ``aborted`` (sticky — not re-attempted until the spec changes
        again), record-FIRST."""
        bad = rev["target"]
        rev.update(aborted=bad, target=rev["previous"] or rev["current"],
                   phase=T.PHASE_ROLLBACK, step=0, stepStartedAt=now,
                   held=False)
        if (svc["status"].get("revisions") or {}) != rev:
            svc["status"]["revisions"] = rev
            self._write_status(client, svc)
        self._rollout_outcome(req, "aborted")
        if self.record_events:
            client.record_event(
                svc, "RolloutAborted",
                f"canary revision {bad} failed analysis; rolling back "
                f"to {rev['target']}", "Warning")

    def _replace_mismatched(self, client, svc, req, by_name, phases,
                            indices, want_rev: str, batch: int,
                            drain_s: float) -> int:
        """Walk the index range; cordon -> drain -> delete pods whose
        revision label differs from ``want_rev``, keeping at most
        ``batch`` slots disrupted at once (capacity never
        oversubscribed). Deleted slots are re-provisioned at
        ``want_rev`` by the provisioning loop later this same
        reconcile. Pod labels ARE the migration state — an interrupted
        walk (controller crash mid-rollout) resumes for free. Returns
        the number of slots currently disrupted."""
        busy = 0
        for i in indices:
            name = T.replica_name(req.name, i)
            pod = by_name.get(name)
            if pod is None or phases.get(name) != "Running" \
                    or self._cordoned(pod):
                busy += 1
        for i in indices:
            name = T.replica_name(req.name, i)
            pod = by_name.get(name)
            if pod is None or self._pod_revision(pod) == want_rev:
                continue
            if not self._cordoned(pod):
                if busy >= batch:
                    continue
                try:
                    patched = self._cordon_pod(client, req, name, drain_s)
                    by_name[name] = patched
                    if self.cache is not None:
                        self.cache.note_write(patched)
                except ob.NotFound:
                    by_name.pop(name, None)
                    continue
                busy += 1
                if self.record_events:
                    client.record_event(
                        svc, "ReplicaCordoned",
                        f"{name} cordoned for rollout replacement "
                        f"(-> {want_rev})")
            elif self._replica_drained(req.namespace, req.name, pod,
                                       drain_s):
                try:
                    client.delete("v1", "Pod", name, req.namespace)
                except (ob.NotFound, ob.ApiError):
                    pass
                if self.cache is not None:
                    self.cache.note_delete(pod)
                self._drain_started.pop((req.namespace, name), None)
                by_name.pop(name, None)
                phases.pop(name, None)
                if self.record_events:
                    client.record_event(
                        svc, "ReplicaRemoved",
                        f"{name} drained and replaced (-> {want_rev})")
        return busy

    def _reconcile_rollout(self, client, svc, req, target: int,
                           by_name, phases) -> dict:
        """Drive the surge -> canary-analyze -> promote | rollback
        machine. Every transition lands in status.revisions BEFORE any
        pod is touched (record-FIRST), so an interrupted rollout
        re-enters idempotently from status. Returns the provisioning
        plan for the rest of the reconcile: how many slots to keep
        ({provision_upto}), which revision each slot runs
        ({revision_for}), and the canary split the endpoints should
        publish ({canary})."""
        spec = svc.get("spec") or {}
        status = svc["status"]
        roll = T.rollout_spec(spec)
        rev = T.revisions_status(svc)
        spec_rev = T.revision_hash(spec)
        surge = max(int(roll["maxSurge"]), 1)
        drain_s = T.drain_seconds(spec)
        now = self.clock()
        self._register_rollout_metrics(req)

        if not rev["current"]:
            # first sight: adopt the live spec as the current revision
            # (no rollout — existing unlabeled pods are grandfathered)
            rev["current"] = rev["target"] = spec_rev
            rev["snapshots"] = {spec_rev: ob.deep_copy(spec)}
            status["revisions"] = rev
            self._write_status(client, svc)

        # keep the idle snapshot fresh: hash-equal spec edits (replica
        # bounds, autoscaling windows) must not leave a stale rollback
        # source. Rides the final status write — any snapshot that
        # hashes to current generates equivalent pods.
        if rev["phase"] == T.PHASE_IDLE and rev["current"] == spec_rev \
                and rev["snapshots"].get(spec_rev) != spec:
            rev["snapshots"] = {spec_rev: ob.deep_copy(spec)}
            status["revisions"] = rev

        # a new shaping revision starts a rollout — unless it is the
        # sticky aborted one (a failed canary is not retried until the
        # spec moves again). A mid-rollout spec revert re-targets the
        # machine the same way: rollback IS a rollout whose target is
        # the previous revision.
        if spec_rev != rev["target"] and spec_rev != rev["aborted"]:
            snaps = dict(rev["snapshots"])
            snaps[spec_rev] = ob.deep_copy(spec)
            keep = {rev["current"], spec_rev}
            old = rev["current"]
            rev.update(
                snapshots={r: s for r, s in snaps.items() if r in keep},
                previous=rev["current"], target=spec_rev,
                phase=T.PHASE_SURGE, step=0, stepStartedAt=now,
                aborted="", held=False)
            status["revisions"] = rev
            self._write_status(client, svc)  # record-FIRST
            if self.record_events:
                client.record_event(
                    svc, "RolloutStarted",
                    f"rolling out revision {spec_rev} (from {old})")

        steps = [float(w) for w in roll["canarySteps"]]
        canary: tuple[str, float] | None = None

        if rev["phase"] == T.PHASE_SURGE:
            # surge replicas run the incoming revision at weight 0 (in
            # membership, taking no preferred traffic) until all are
            # Running — then analysis opens
            canary = (rev["target"], 0.0)
            names = [T.replica_name(req.name, i)
                     for i in range(target, target + surge)]
            stale = [n for n in names if n in by_name
                     and self._pod_revision(by_name[n]) != rev["target"]]
            if stale:
                # leftovers from an interrupted earlier rollout: replace
                self._replace_mismatched(
                    client, svc, req, by_name, phases,
                    range(target, target + surge), rev["target"],
                    surge, drain_s)
            elif all(n in by_name and phases.get(n) == "Running"
                     and not self._cordoned(by_name[n]) for n in names):
                rev.update(phase=T.PHASE_ANALYZE, stepStartedAt=now)
                status["revisions"] = rev
                self._write_status(client, svc)
                canary = (rev["target"], steps[0])
                if self.record_events:
                    client.record_event(
                        svc, "RolloutAnalyzing",
                        f"canary {rev['target']} serving at weight "
                        f"{steps[0]:g}")

        elif rev["phase"] == T.PHASE_ANALYZE:
            step = min(rev["step"], len(steps) - 1)
            weight = steps[step]
            canary = (rev["target"], weight)
            healthy = True
            if self.rollout_analysis is not None:
                healthy = bool(self.rollout_analysis(
                    req.namespace, req.name, rev["current"],
                    rev["target"], now))
            if not healthy:
                if roll["autoRollback"]:
                    self._abort_rollout(client, svc, req, rev, now)
                    canary = ((rev["aborted"], 0.0)
                              if rev["aborted"] else None)
                elif not rev["held"]:
                    # autoRollback off: freeze at this weight until the
                    # spec changes; fire the audit trail exactly once
                    rev["held"] = True
                    status["revisions"] = rev
                    self._write_status(client, svc)
                    self._rollout_outcome(req, "aborted")
                    if self.record_events:
                        client.record_event(
                            svc, "RolloutAborted",
                            f"canary revision {rev['target']} failed "
                            f"analysis at weight {weight:g}; "
                            "autoRollback off — holding", "Warning")
            elif not rev["held"] and \
                    now - rev["stepStartedAt"] >= \
                    roll["analysisWindowSeconds"]:
                rev["step"] = step + 1
                rev["stepStartedAt"] = now
                if rev["step"] >= len(steps):
                    rev["phase"] = T.PHASE_PROMOTE
                    canary = None
                else:
                    canary = (rev["target"], steps[rev["step"]])
                status["revisions"] = rev
                self._write_status(client, svc)
                if self.record_events:
                    if rev["phase"] == T.PHASE_PROMOTE:
                        client.record_event(
                            svc, "RolloutPromoting",
                            f"canary {rev['target']} healthy through "
                            "the ladder; replacing the base fleet")
                    else:
                        client.record_event(
                            svc, "RolloutStepAdvanced",
                            f"canary {rev['target']} weight -> "
                            f"{steps[rev['step']]:g}")

        if rev["phase"] in (T.PHASE_PROMOTE, T.PHASE_ROLLBACK):
            if rev["phase"] == T.PHASE_ROLLBACK and rev["aborted"]:
                # steer traffic off the aborted revision while its
                # replicas are replaced (availability still beats it)
                canary = (rev["aborted"], 0.0)
            span_count = target + (surge if rev["phase"]
                                   == T.PHASE_PROMOTE else 0)
            batch = max(1, surge + max(int(roll["maxUnavailable"]), 0))
            self._replace_mismatched(
                client, svc, req, by_name, phases, range(span_count),
                rev["target"], batch, drain_s)
            base = [T.replica_name(req.name, i) for i in range(target)]
            base_ok = all(
                n in by_name and phases.get(n) == "Running"
                and self._pod_revision(by_name[n]) == rev["target"]
                and not self._cordoned(by_name[n]) for n in base)
            extras = [n for n, p in by_name.items()
                      if self._pod_revision(p) != rev["target"]]
            if base_ok and not extras:
                outcome = ("promoted" if rev["phase"] == T.PHASE_PROMOTE
                           else "rolled_back")
                if outcome == "promoted":
                    rev["previous"] = rev["current"]
                    rev["current"] = rev["target"]
                snap = rev["snapshots"].get(rev["current"])
                rev.update(
                    snapshots={rev["current"]:
                               (snap if snap is not None
                                else ob.deep_copy(spec))},
                    phase=T.PHASE_IDLE, step=0, stepStartedAt=now,
                    held=False)
                status["revisions"] = rev
                self._write_status(client, svc)
                self._rollout_outcome(req, outcome)
                canary = None
                if self.record_events:
                    if outcome == "promoted":
                        client.record_event(
                            svc, "RolloutPromoted",
                            f"revision {rev['current']} promoted to "
                            "the full fleet")
                    else:
                        client.record_event(
                            svc, "RolloutRolledBack",
                            f"fleet back on revision {rev['current']} "
                            f"(rolled back from {rev['aborted']})",
                            "Warning")

        phase = rev["phase"]
        self.registry.gauge(
            "jaxservice_rollout_phase", ROLLOUT_PHASE_VALUE[phase],
            help_="rollout state-machine position "
                  "(0=idle 1=surge 2=analyze 3=promote 4=rollback)",
            namespace=req.namespace, service=req.name)
        rollout_phase_gauge().labels(req.name).set(
            ROLLOUT_PHASE_VALUE[phase])

        upto = (target + surge
                if phase in (T.PHASE_SURGE, T.PHASE_ANALYZE,
                             T.PHASE_PROMOTE) else target)
        cur_rev, target_rev = rev["current"], rev["target"]

        def revision_for(i: int) -> str:
            if phase in (T.PHASE_PROMOTE, T.PHASE_ROLLBACK):
                return target_rev
            if i >= target:  # surge slots run the incoming revision
                return target_rev
            return cur_rev

        return {"active": phase != T.PHASE_IDLE, "phase": phase,
                "target_rev": target_rev, "provision_upto": upto,
                "revision_for": revision_for, "canary": canary}

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, client, req: Request) -> Result | None:
        if self.cache is not None:
            self.cache.refresh()
        svc = client.get_or_none(T.API_VERSION, T.KIND, req.name,
                                 req.namespace)
        if svc is None:
            # deleted; ownerRef GC reaps replicas. Drop autoscaler and
            # drain-grace memory
            self._scale_state.pop((req.namespace, req.name), None)
            self._rollout_registered.discard((req.namespace, req.name))
            prefix = req.name + "-replica-"
            for k in [k for k in self._drain_started
                      if k[0] == req.namespace and k[1].startswith(prefix)]:
                del self._drain_started[k]
            return None
        if ob.meta(svc).get("deletionTimestamp"):
            return None

        errs = T.validate(svc)
        if errs:
            changed = ob.cond_set(svc, T.COND_DEGRADED, "True",
                                  "ValidationFailed", "; ".join(errs))
            if changed:
                client.update_status(svc)
            return None

        if not ob.cond_get(svc, T.COND_CREATED):
            svc = self._ensure_traceparent(client, svc)
            ob.cond_set(svc, T.COND_CREATED, "True", "JAXServiceCreated",
                        "replica set is being provisioned")
            svc = client.update_status(svc)
            if self.record_events:
                client.record_event(svc, "JAXServiceCreated",
                                    "provisioning serving replicas")

        rh.reconcile_child(client, svc, self.generate_service(svc))

        with obs_trace.TRACER.span(
                "jaxservice.reconcile", parent=self._svc_context(svc),
                namespace=req.namespace, service=req.name) as span:
            return self._reconcile_replicas(client, svc, req, span)

    def _reconcile_replicas(self, client, svc: dict, req: Request,
                            span) -> Result | None:
        spec = svc.get("spec") or {}
        reps = T.replicas_spec(spec)
        status = svc["status"] = svc.get("status") or {}
        prev_status = ob.deep_copy(status)
        target = min(max(status.get("targetReplicas") or reps["min"],
                         reps["min"]), reps["max"])

        pods = self._pods(client, req.namespace, req.name)
        by_name = {ob.meta(p)["name"]: p for p in pods}
        phases = {n: (p.get("status") or {}).get("phase", "Pending")
                  for n, p in by_name.items()}

        # -- autoscale decision (durable target move, record-FIRST) --------
        new_target = self._autoscale(svc, target)
        # remediation nudge: a one-shot floor from obs/remediate.py,
        # consumed (cleared) here so it can only act once — and flows
        # through the same record-first write as any scale decision
        nudge = self._consume_nudge(client, svc)
        if nudge is not None and nudge > new_target:
            new_target = min(nudge, reps["max"])
        if new_target != target:
            direction = "up" if new_target > target else "down"
            status["targetReplicas"] = new_target
            status["scales"] = status.get("scales", 0) + 1
            # target lands in status BEFORE any pod is touched: an
            # interrupted scale re-enters here idempotently
            self._write_status(client, svc)
            scales_total().labels(direction=direction).inc()
            self.registry.counter_inc(
                "jaxservice_scale_total",
                help_="autoscaler target moves by direction",
                namespace=req.namespace, service=req.name,
                tenant=req.namespace, direction=direction)
            if self.record_events:
                client.record_event(
                    svc, "ScaledUp" if direction == "up" else "ScaledDown",
                    f"target replicas {target} -> {new_target}",
                    "Normal")
            target = new_target
        span.attrs["target"] = target

        # -- rollout state machine (surge/canary/promote/rollback):
        # transitions are status-durable record-FIRST; the returned
        # plan tells the loops below how many slots to keep and which
        # revision each runs ------------------------------------------
        rollout = self._reconcile_rollout(client, svc, req, target,
                                          by_name, phases)
        upto = rollout["provision_upto"]
        span.attrs["rollout_phase"] = rollout["phase"]

        # -- grow-back: a replica cordoned for a scale-down that was
        # reversed before its drain completed returns to service (the
        # uncordon arrow in docs/serving.md) — otherwise nothing ever
        # clears the annotation and the service wedges below target
        # (not reaped, not re-provisioned, endpoints stuck cordoned)
        for i in range(target):
            name = T.replica_name(req.name, i)
            pod = by_name.get(name)
            if pod is None or not self._cordoned(pod):
                continue
            if rollout["active"] and \
                    self._pod_revision(pod) != rollout["revision_for"](i):
                # cordoned for rollout REPLACEMENT, not scale-down:
                # let it drain out
                continue
            try:
                patched = client.patch(
                    "v1", "Pod", name,
                    {"metadata": {"annotations": {
                        T.ANNOTATION_CORDON: "false",
                        T.ANNOTATION_DRAIN_DEADLINE: None}}},
                    req.namespace)
                by_name[name] = patched
                if self.cache is not None:
                    self.cache.note_write(patched)
            except ob.NotFound:
                by_name.pop(name, None)
                continue
            self._drain_started.pop((req.namespace, name), None)
            if self.record_events:
                client.record_event(
                    svc, "ReplicaUncordoned",
                    f"{name} returned to service (scale-down reversed)")

        # -- reap dead replicas below the provisioning line (surge
        # slots included) — re-provision at same index ----------------
        restarted = 0
        for i in range(upto):
            name = T.replica_name(req.name, i)
            pod = by_name.get(name)
            if pod is not None and phases[name] in ("Failed", "Succeeded") \
                    and not self._cordoned(pod):
                try:
                    client.delete("v1", "Pod", name, req.namespace)
                except (ob.NotFound, ob.ApiError):
                    pass
                if self.cache is not None:
                    # fold the delete in (the note_write discipline): a
                    # stale snapshot would keep showing the dead pod and
                    # stall its re-provision until the watch catches up
                    self.cache.note_delete(pod)
                by_name.pop(name, None)
                restarted += 1
        if restarted:
            status["restarts"] = status.get("restarts", 0) + restarted
            self._write_status(client, svc)
            replica_restarts_total().inc(restarted)
            self.registry.counter_inc(
                "jaxservice_replica_restarts_total", by=float(restarted),
                help_="replicas reaped and re-provisioned after dying",
                namespace=req.namespace, service=req.name,
                tenant=req.namespace)
            if self.record_events:
                client.record_event(
                    svc, "ReplicaRestarted",
                    f"{restarted} dead replica(s) re-provisioned",
                    "Warning")
            # names must free before recreation — poll again shortly
            self._publish_status(client, svc, req, by_name, phases,
                                 target, prev_status, rollout)
            return Result(requeue_after=_REQUEUE_FAST)

        # -- provision missing replicas below the line (surge slots
        # run the incoming revision; a rollback re-pins the slot to
        # the snapshot of the revision it is converging to) ----------
        for i in range(upto):
            name = T.replica_name(req.name, i)
            if name in by_name:
                continue
            pod = self.generate_pod(svc, i,
                                    revision=rollout["revision_for"](i))
            ob.set_owner(pod, svc)
            try:
                created = client.create(pod)
            except ob.Conflict:
                continue  # old name still releasing; next pass recreates
            by_name[name] = created
            phases[name] = (created.get("status") or {}).get(
                "phase", "Pending")
            if self.cache is not None:
                self.cache.note_write(created)

        # -- scale-down drain: indices >= the provisioning line (the
        # replica_index sort sentinel puts malformed leftovers here too
        # — drained away, not aliased to a real slot). Surge replicas
        # retire through this same path once a rollout completes (or
        # rolls back) and the line drops back to target ----------------
        draining = 0
        for name in sorted(by_name, key=T.replica_index):
            if T.replica_index(name) < upto:
                continue
            pod = by_name[name]
            if not self._cordoned(pod):
                try:
                    patched = self._cordon_pod(
                        client, req, name,
                        T.drain_seconds(svc.get("spec") or {}))
                    by_name[name] = patched
                    if self.cache is not None:
                        self.cache.note_write(patched)
                except ob.NotFound:
                    by_name.pop(name, None)
                    continue
                if self.record_events:
                    client.record_event(
                        svc, "ReplicaCordoned",
                        f"{name} cordoned for scale-down (draining)")
                draining += 1
            elif self._replica_drained(req.namespace, req.name, pod,
                                       T.drain_seconds(svc.get("spec")
                                                       or {})):
                try:
                    client.delete("v1", "Pod", name, req.namespace)
                except (ob.NotFound, ob.ApiError):
                    pass
                if self.cache is not None:
                    self.cache.note_delete(pod)
                self._drain_started.pop((req.namespace, name), None)
                by_name.pop(name, None)
                phases.pop(name, None)
                if self.record_events:
                    client.record_event(
                        svc, "ReplicaRemoved",
                        f"{name} drained and removed")
            else:
                draining += 1
        span.attrs["draining"] = draining

        res = self._publish_status(client, svc, req, by_name, phases,
                                   target, prev_status, rollout)
        span.attrs["ready"] = (status.get("replicas") or {}).get("ready", 0)
        return res

    # -- status + endpoints --------------------------------------------------

    def _publish_status(self, client, svc, req, by_name, phases, target,
                        prev_status, rollout=None) -> Result | None:
        status = svc["status"]
        ready, pending, cordoned = [], [], []
        for name in sorted(by_name, key=T.replica_index):
            pod = by_name[name]
            if self._cordoned(pod):
                cordoned.append(name)
            elif phases.get(name) == "Running":
                ready.append(name)
            else:
                pending.append(name)
        status["targetReplicas"] = target
        status["replicas"] = {
            "desired": target,
            "ready": len(ready),
            "pending": len(pending),
            "cordoned": len(cordoned),
        }
        status["replicaStatuses"] = {
            n: ("Cordoned" if n in cordoned
                else phases.get(n, "Pending")) for n in sorted(
                by_name, key=T.replica_index)}
        # surge replicas count toward ready during a rollout: >= not ==
        all_ready = len(ready) >= target and not pending
        ob.cond_set(svc, T.COND_READY,
                    "True" if all_ready else "False",
                    "AllReplicasReady" if all_ready else "ReplicasPending",
                    f"{len(ready)}/{target} replicas ready")
        if ob.cond_is_true(svc, T.COND_DEGRADED):
            ob.cond_set(svc, T.COND_DEGRADED, "False", "Recovered", "")

        self._publish_endpoints(
            client, svc, req, ready, cordoned, by_name,
            canary=(rollout or {}).get("canary"))
        self._publish_gauges(req, target, ready, pending, cordoned)

        if svc.get("status") != prev_status:
            self._write_status(client, svc)
        if pending or cordoned:
            return Result(requeue_after=_REQUEUE_FAST)
        if rollout is not None and rollout["active"]:
            # an analysis window only elapses if someone re-looks: an
            # active rollout keeps the reconcile scheduled even when
            # the replica set is momentarily steady
            return Result(requeue_after=_REQUEUE_POLL)
        if self.signals is not None:
            # the signal plane is pull-only: keep sampling for the
            # autoscaler even when the replica set is steady
            return Result(requeue_after=_REQUEUE_POLL)
        return None

    def _publish_endpoints(self, client, svc, req, ready, cordoned,
                           by_name, canary=None) -> None:
        """Stamp the router-consumed endpoint list; no-op when the
        rendered JSON is byte-identical (every write is a watch event —
        the PR 5 status-storm lesson). Entries carry the pod's revision
        label; while a rollout analyzes, the canaried revision's ACTIVE
        entries also carry the ladder weight — the router derives its
        deterministic split from them."""
        port = (svc.get("spec") or {}).get("port", T.DEFAULT_PORT)
        eps = []
        for name in ready:
            ep = {"name": name,
                  "addr": f"http://{name}.{req.name}."
                          f"{req.namespace}.svc:{port}",
                  "state": T.STATE_ACTIVE}
            rev = self._pod_revision(by_name[name])
            if rev:
                ep["revision"] = rev
                if canary is not None and rev == canary[0]:
                    ep["canary"] = canary[1]
            eps.append(ep)
        for name in cordoned:
            # only a live cordoned replica still drains; terminal ones
            # are awaiting deletion and must leave membership entirely
            if (by_name[name].get("status") or {}).get("phase") \
                    == "Running":
                ep = {"name": name,
                      "addr": f"http://{name}.{req.name}."
                              f"{req.namespace}.svc:{port}",
                      "state": T.STATE_CORDONED}
                rev = self._pod_revision(by_name[name])
                if rev:
                    ep["revision"] = rev
                eps.append(ep)
        rendered = render_endpoints(eps)
        m = ob.meta(svc)
        if (m.get("annotations") or {}).get(T.ANNOTATION_ENDPOINTS) \
                == rendered:
            return
        try:
            patched = client.patch(
                T.API_VERSION, T.KIND, req.name,
                {"metadata": {"annotations": {
                    T.ANNOTATION_ENDPOINTS: rendered}}},
                req.namespace)
            m.setdefault("annotations", {})[T.ANNOTATION_ENDPOINTS] = \
                rendered
            m["resourceVersion"] = ob.meta(patched)["resourceVersion"]
        except ob.ApiError:
            log.exception("endpoints annotation patch failed for %s/%s",
                          req.namespace, req.name)

    def _publish_gauges(self, req, target, ready, pending,
                        cordoned) -> None:
        counts = {"desired": target, "ready": len(ready),
                  "pending": len(pending), "cordoned": len(cordoned)}
        for state in REPLICA_STATES:
            self.registry.gauge(
                "jaxservice_replicas", counts[state],
                help_="replica counts by state per service",
                namespace=req.namespace, service=req.name, state=state)
            replicas_gauge().labels(req.name, state).set(counts[state])

    # -- autoscaler ----------------------------------------------------------

    def _consume_nudge(self, client, svc: dict) -> int | None:
        """Read-and-clear the remediation scale nudge annotation.
        Returns the requested floor (un-clamped), or None. The clear is
        a merge patch deleting the key; clear failures leave the nudge
        for the next reconcile (idempotent: it is a floor, not an
        increment)."""
        m = ob.meta(svc)
        raw = (m.get("annotations") or {}).get(T.ANNOTATION_SCALE_NUDGE)
        if raw is None:
            return None
        try:
            resp = client.patch(
                T.API_VERSION, T.KIND, m["name"],
                {"metadata": {"annotations": {
                    T.ANNOTATION_SCALE_NUDGE: None}}},
                m["namespace"])
            # rebind rv (and annotations) so the record-first status
            # write later this reconcile doesn't 409 on the stale rv
            m["resourceVersion"] = ob.meta(resp)["resourceVersion"]
            m["annotations"] = dict(ob.meta(resp).get("annotations") or {})
        except Exception:
            log.warning("scale-nudge clear failed for %s/%s; will retry",
                        m["namespace"], m["name"])
        try:
            return int(raw)
        except (TypeError, ValueError):
            log.warning("ignoring malformed scale nudge %r on %s/%s",
                        raw, m["namespace"], m["name"])
            return None

    def _queue_slope(self, namespace: str, name: str,
                     start: float, end: float) -> float:
        """Summed least-squares slope (queue items/s) of every
        ``router_queue_depth`` series for the service over the window —
        the TSDB trend read behind predictive scale-up."""
        total = 0.0
        for _labels, pts in self.store.window(
                "router_queue_depth",
                {"namespace": namespace, "service": name}, start, end):
            if len(pts) < 2:
                continue
            n = len(pts)
            mt = sum(t for t, _ in pts) / n
            mv = sum(v for _, v in pts) / n
            denom = sum((t - mt) ** 2 for t, _ in pts)
            if denom <= 0:
                continue
            total += sum((t - mt) * (v - mv) for t, v in pts) / denom
        return total

    def _autoscale(self, svc: dict, target: int) -> int:
        """Demand-driven target with hysteresis. Deterministic given
        the clock and signal sequence — the serve_bench replay law."""
        spec = svc.get("spec") or {}
        reps = T.replicas_spec(spec)
        mn, mx = reps["min"], reps["max"]
        target = min(max(target, mn), mx)
        if self.signals is None or mn == mx:
            return target
        m = ob.meta(svc)
        key = (m["namespace"], m["name"])
        st = self._scale_state.setdefault(key, {})
        auto = T.autoscaling_spec(spec)
        now = self.clock()

        queue = self.signals.queue_depth(m["namespace"], m["name"])
        total = self.signals.tokens_total(m["namespace"], m["name"])
        prev = st.get("sample")
        if prev is not None and now > prev[0]:
            st["rate"] = max(0.0, (total - prev[1]) / (now - prev[0]))
            st["sample"] = (now, total)
        elif prev is None:
            st["sample"] = (now, total)
        rate = st.get("rate", 0.0)

        if self.store is not None:
            # predictive scale-up: project the queue along its TSDB
            # trend over the stabilization window. A positive slope
            # raises effective demand NOW (the hysteresis window then
            # confirms it); a negative slope never shrinks the signal —
            # prediction accelerates scale-up only, scale-down keeps
            # its observe-then-step gentleness.
            window = auto["scaleUpStabilizationSeconds"]
            slope = self._queue_slope(m["namespace"], m["name"],
                                      now - window, now)
            if slope > 0:
                queue = max(queue, queue + slope * window)

        by_queue = math.ceil(queue / auto["targetQueueDepth"])
        by_rate = math.ceil(rate / auto["targetTokensPerSec"])
        demand = min(max(by_queue, by_rate, mn), mx)

        if demand == target:
            st.pop("pending", None)
            return target
        direction = "up" if demand > target else "down"
        pending = st.get("pending")
        if not pending or pending[0] != direction:
            st["pending"] = (direction, now)
            return target
        window = (auto["scaleUpStabilizationSeconds"] if direction == "up"
                  else auto["scaleDownStabilizationSeconds"])
        if now - pending[1] < window:
            return target
        st.pop("pending", None)
        if direction == "up":
            return demand  # jump to demand: a queue spike wants capacity NOW
        return target - 1  # step down one: lulls release capacity gently


def build_controller(client, record_events: bool = True, registry=None,
                     signals=None, clock=time.monotonic,
                     cache: bool = True, store=None,
                     rollout_analysis=None) -> Controller:
    """``cache=True`` (default) reads replica pods from an indexed
    ``ClusterCache`` keyed on the service label — zero per-reconcile
    list calls (the ISSUE 7 discipline, pinned in tests)."""
    cluster_cache = None
    if cache:
        from kubeflow_tpu.control.cache import ClusterCache

        cluster_cache = ClusterCache(
            client, kinds=(("v1", "Pod"),),
            pod_labels=(T.LABEL_SERVICE_NAME,)).connect()
    rec = JAXServiceReconciler(record_events=record_events,
                               registry=registry, signals=signals,
                               clock=clock, cache=cluster_cache,
                               store=store,
                               rollout_analysis=rollout_analysis)
    ctl = Controller("jaxservice", client, rec, registry=registry)
    if cluster_cache is not None:
        ctl.uses(cluster_cache)
    ctl.watches_primary(T.API_VERSION, T.KIND)
    ctl.owns("v1", "Pod").owns("v1", "Service")
    return ctl
