"""Readiness/condition waiters.

Mirrors the reference's poll-with-timeout utilities:
- wait_for_deployment.py / kf_is_ready_test.py:76 (Deployments ready),
- katib_studyjob_test.py:128-194 wait_for_condition (CRD status
  conditions with timeout and per-poll logging).

A `clock`/`sleep` injection point keeps hermetic tests instant.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

log = logging.getLogger("kubeflow_tpu.testing")


class WaitTimeout(TimeoutError):
    pass


def wait_for(predicate: Callable[[], bool], *, timeout_s: float = 300.0,
             poll_s: float = 2.0, desc: str = "condition",
             clock=time.monotonic, sleep=time.sleep) -> None:
    deadline = clock() + timeout_s
    while True:
        if predicate():
            return
        if clock() >= deadline:
            raise WaitTimeout(f"timed out after {timeout_s}s waiting for {desc}")
        sleep(poll_s)


def wait_for_condition(client, api_version: str, kind: str, name: str,
                       namespace: str | None, expected: tuple[str, ...],
                       *, timeout_s: float = 300.0, poll_s: float = 2.0,
                       clock=time.monotonic, sleep=time.sleep) -> dict:
    """Wait until the object's status.conditions contains any `expected`
    type with status True; returns the object (katib shape)."""
    found: dict = {}

    def check() -> bool:
        nonlocal found
        obj = client.get_or_none(api_version, kind, name, namespace)
        if obj is None:
            return False
        for cond in (obj.get("status") or {}).get("conditions") or []:
            if cond.get("type") in expected and str(cond.get("status")) == "True":
                found = obj
                return True
        return False

    wait_for(check, timeout_s=timeout_s, poll_s=poll_s,
             desc=f"{kind} {name} condition in {expected}",
             clock=clock, sleep=sleep)
    return found


def wait_for_deployments_ready(client, namespace: str, names: list[str] | None = None,
                               *, timeout_s: float = 300.0, poll_s: float = 2.0,
                               clock=time.monotonic, sleep=time.sleep) -> None:
    """kf_is_ready_test.py:76 equivalent: every (named) Deployment in the
    namespace has readyReplicas == spec.replicas."""

    def ready() -> bool:
        deps = client.list("apps/v1", "Deployment", namespace=namespace)
        if names is not None:
            have = {d["metadata"]["name"] for d in deps}
            if not set(names) <= have:
                return False
            deps = [d for d in deps if d["metadata"]["name"] in names]
        if not deps:
            return False
        for d in deps:
            want = (d.get("spec") or {}).get("replicas", 1)
            got = (d.get("status") or {}).get("readyReplicas", 0)
            if got < want:
                return False
        return True

    wait_for(ready, timeout_s=timeout_s, poll_s=poll_s,
             desc=f"deployments ready in {namespace}",
             clock=clock, sleep=sleep)
