"""ClusterCache — an informer-style indexed cluster cache.

Every controller so far re-listed its world from the apiserver on each
pass: the gang scheduler alone called ``client.list("v1", "Pod")`` per
admission attempt, per health pass, and per victim scan — O(store) deep
copies each time, fine at 4 nodes, quadratic death at 5k (ISSUE 7).
This is the client-go informer/kube-scheduler-snapshot analogue: ONE
initial list per kind, then the cache maintains itself incrementally
from watch events, exposing snapshot reads with secondary indexes:

- nodes by name → ``NodeView`` plus per-``(accelerator, topology)``
  sorted free-capacity buckets (``scheduler/capacity.py``), free chips
  kept current on every pod bind/unbind/terminal transition;
- pods by ``nodeName`` (bound, non-terminal — the set that holds
  chips) and by gang label (``LABEL_JOB_NAME``), so gang and health
  reads are O(bucket) instead of O(cluster).

Consistency model (the informer contract, not linearizability):

- ``refresh()`` drains pending watch events from pollable streams —
  the hermetic FakeCluster delivers events synchronously at write
  time, so a refresh at reconcile start observes everything the
  triggering event did (read-your-watches);
- ``note_write()`` folds a write RESPONSE into the cache immediately
  (kube-scheduler's assumed-pod cache): against a real apiserver the
  watch is asynchronous, and a scheduler must see its own binds before
  the next admission in the same pass;
- a dropped or erroring watch resubscribes from the last seen
  resourceVersion; 410 Expired (or a backend without watch-cache
  resume) falls back to a full relist — the PR 5 hardening, reused;
- stale deliveries are resourceVersion-guarded: an out-of-order
  MODIFIED older than the cached object is dropped, so replayed
  events (chaos relists re-yield live objects) cannot roll state back.

All state lives behind one lock, mutated only in locked methods — the
fresh-container idiom LOCK201 proves and the dyntrace happens-before
validator (TPU_RACE_TRACE=1) observes. Snapshot reads return internal
object references without copying (the whole point); callers treat
them as READ-ONLY and mutate only through the client.
"""

from __future__ import annotations

import logging
import threading

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.scheduler import capacity as C
from kubeflow_tpu.control.scheduler import nodes as N

log = logging.getLogger("kubeflow_tpu.cache")

NODE = ("v1", "Node")
POD = ("v1", "Pod")
DEFAULT_KINDS = (NODE, POD)

# Deleted-object memory (see _apply): bounded — entries only need to
# outlive the assume-note window of the pass that raced the delete.
TOMBSTONE_CAP = 2048


def _rv_of(obj: dict) -> int | None:
    try:
        return int(ob.meta(obj).get("resourceVersion", ""))
    except (TypeError, ValueError):
        return None


class _Sub:
    """One kind's watch subscription (single consumer: either the
    owning controller's reconcile-time refresh() or one pump thread)."""

    __slots__ = ("api_version", "kind", "stream", "last_rv")

    def __init__(self, api_version: str, kind: str):
        self.api_version = api_version
        self.kind = kind
        self.stream = None
        self.last_rv = ""

    @property
    def key(self) -> tuple[str, str]:
        return (self.api_version, self.kind)


class ClusterCache:
    def __init__(self, client, kinds=DEFAULT_KINDS,
                 pod_labels: tuple[str, ...] = (JT.LABEL_JOB_NAME,)):
        # ``pod_labels``: label keys to maintain secondary pod indexes
        # for. The gang label is the scheduler's; other controllers
        # (jaxservice, notebook) pass their own grouping label so their
        # per-reconcile "pods of X" reads stay O(bucket).
        self._client = client
        self._lock = threading.RLock()
        # Stream management (teardown + resubscribe) is serialized
        # separately: a pump thread and a reconcile-time refresh()
        # discovering the same dead stream must not both resubscribe —
        # the loser's stream would leak, subscribed but never consumed.
        self._mgmt = threading.Lock()
        self._subs = [_Sub(api, kind) for api, kind in kinds]
        self._objects: dict[tuple[str, str], dict[tuple[str, str], dict]] = \
            {s.key: {} for s in self._subs}
        self._dirty: dict[tuple[str, str], None] = {}  # kinds needing relist
        # node-derived state
        self._views: dict[str, N.NodeView] = {}
        self._used: dict[str, int] = {}    # chips held per node (any node
        #                                    name a bound pod references)
        self._free: dict[str, int] = {}    # per KNOWN node: alloc - used
        self._buckets: dict[tuple | None, C.Bucket] = {C.ALL_NODES: C.Bucket()}
        # pod-derived indexes
        self._pod_use: dict[tuple[str, str], tuple[str, int]] = {}
        self._by_node: dict[str, dict[tuple[str, str], None]] = {}
        # generic per-kind namespace buckets: kind key -> ns -> okey set
        # (a namespaced read over a high-cardinality kind — the notebook
        # Event forward — must be O(namespace), not O(cluster))
        self._by_ns: dict[tuple[str, str],
                          dict[str, dict[tuple[str, str], None]]] = \
            {s.key: {} for s in self._subs}
        # label key -> (namespace, value) -> ordered okey set
        self._pod_labels = tuple(pod_labels)
        self._by_label: dict[str, dict[tuple[str, str],
                                       dict[tuple[str, str], None]]] = \
            {lbl: {} for lbl in self._pod_labels}
        # (kind key, object key) -> highest rv seen at deletion. A
        # note_write racing a pump-applied DELETED would otherwise
        # re-insert the dead object (the rv guard below only compares
        # against a CACHED old); rvs are globally monotonic, so a
        # genuine recreation carries a higher rv and passes.
        self._tombstones: dict[tuple, int] = {}
        self._stats: dict[str, int] = {
            "events": 0, "stale_events": 0, "relists": 0,
            "resubscribes": 0, "refreshes": 0, "reads": 0,
        }
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    def connect(self) -> "ClusterCache":
        """Subscribe the watches, then take the ONE initial list per
        kind. Failures (a chaotic or absent apiserver) mark the kind
        dirty; refresh() keeps retrying — a cache that cannot list yet
        serves an empty snapshot and the level-triggered reconciles
        converge once it can."""
        for sub in self._subs:
            self._ensure_stream(sub)
            self._try_relist(sub)
        return self

    def start(self) -> "ClusterCache":
        """Production mode: pump each watch stream on a daemon thread
        (streams without poll() — a real apiserver — cannot be drained
        at reconcile time). Hermetic tests skip this and rely on
        refresh()'s synchronous poll-drain."""
        with self._lock:
            if self._threads:
                return self
            self._stop.clear()
            threads = [
                threading.Thread(target=self._pump, args=(sub,),
                                 daemon=True,
                                 name=f"cache-{sub.kind.lower()}")
                for sub in self._subs
            ]
            self._threads = threads
        for t in threads:
            t.start()
        return self

    @property
    def pumped(self) -> bool:
        """True when pump threads own the streams — refresh() cannot
        drain them, so snapshots may trail the event that triggered the
        current reconcile (the scheduler confirms destructive decisions
        against the apiserver in this mode)."""
        with self._lock:
            return bool(self._threads)

    def stop(self) -> None:
        self._stop.set()
        for sub in self._subs:
            stream = sub.stream
            if stream is not None:
                try:
                    stream.stop()
                except Exception:
                    pass
        with self._lock:
            self._threads = []

    def _pump(self, sub: _Sub) -> None:
        """Pump one stream and OUTLIVE it (control/runtime.py's
        _watch_loop discipline): a raising stream resubscribes and
        relists rather than silently killing the thread."""
        while not self._stop.is_set():
            stream = sub.stream
            try:
                if stream is not None:
                    for ev in stream:
                        if self._stop.is_set():
                            return
                        self._ingest(sub, ev)
            except Exception:
                log.exception("cache: watch stream for %s failed; "
                              "resubscribing", sub.kind)
            if self._stop.is_set():
                return
            self._stop.wait(0.2)
            self._resubscribe(sub)

    # -- feeding -------------------------------------------------------------

    def refresh(self) -> int:
        """Catch the snapshot up: retry any dirty kind's relist, then
        drain every pollable stream. Returns events applied. Errors
        never propagate — the cache serves its last consistent
        snapshot and retries on the next refresh (informer semantics;
        the reconcile that called us stays level-triggered)."""
        applied = 0
        with self._lock:
            self._stats["refreshes"] += 1
            dirty = set(self._dirty)
            pumped = bool(self._threads)
        for sub in self._subs:
            if pumped:
                # pump threads own the streams (consumption AND
                # resubscription); draining or resubscribing here would
                # race their stream management. Dirty relists are safe:
                # idempotent wholesale replacement under the lock.
                if sub.key in dirty:
                    self._try_relist(sub)
                continue
            if sub.stream is None:
                self._resubscribe(sub)
            elif sub.key in dirty:
                self._try_relist(sub)
            stream = sub.stream
            if stream is None or not hasattr(stream, "poll"):
                continue
            while True:
                try:
                    ev = stream.poll()
                except Exception:
                    log.exception("cache: poll on %s watch failed; "
                                  "resubscribing", sub.kind)
                    self._resubscribe(sub)
                    break
                if ev is None:
                    break
                self._ingest(sub, ev)
                applied += 1
        return applied

    def note_write(self, obj: dict) -> None:
        """Fold a write response in immediately (assume-cache): the rv
        guard makes it idempotent against the watch's later delivery
        of the same change."""
        if obj and obj.get("kind"):
            self._apply("MODIFIED", obj)

    def note_delete(self, obj: dict) -> None:
        if obj and obj.get("kind"):
            self._apply("DELETED", obj)

    def mark_dirty(self, kinds=None) -> int:
        """Force a relist of ``kinds`` (``[(api_version, kind), ...]``;
        None = every subscribed kind) on the next ``refresh()`` — the
        cache's own watch-gap repair path, exposed for operators and
        the remediation engine (a slow scheduler pass with a healthy
        fleet usually means a drifted index). Returns how many kinds
        were marked."""
        wanted = None if kinds is None else {tuple(k) for k in kinds}
        marked = 0
        with self._lock:
            for sub in self._subs:
                if wanted is None or sub.key in wanted:
                    self._dirty[sub.key] = None
                    marked += 1
        return marked

    def _ingest(self, sub: _Sub, ev) -> None:
        rv = ob.meta(ev.object).get("resourceVersion")
        if rv:
            sub.last_rv = rv
        self._apply(ev.type, ev.object)

    def _ensure_stream(self, sub: _Sub) -> bool:
        if sub.stream is not None:
            return True
        try:
            sub.stream = self._client.watch(sub.api_version, sub.kind)
        except Exception:
            log.exception("cache: watch subscribe for %s failed; will "
                          "retry", sub.kind)
            with self._lock:
                self._dirty[sub.key] = None
            return False
        return True

    def _resubscribe(self, sub: _Sub) -> None:
        with self._mgmt:
            old = sub.stream
            if old is not None:
                try:
                    old.stop()
                except Exception:
                    pass
                sub.stream = None
                with self._lock:
                    self._stats["resubscribes"] += 1
            stream = None
            if sub.last_rv:
                # resume from the last seen rv: replays the gap, no
                # relist
                try:
                    stream = self._client.watch(sub.api_version, sub.kind,
                                                since_rv=sub.last_rv)
                except ob.Expired:
                    log.info("cache: %s resume rv=%s expired (410) -> "
                             "relist", sub.kind, sub.last_rv)
                except TypeError:
                    pass  # backend without watch-cache resume: relist
                except Exception:
                    log.exception("cache: %s watch resume failed; will "
                                  "relist", sub.kind)
            if stream is not None:
                sub.stream = stream
                if old is not None:
                    return  # resumed exactly: the replay covers the gap
            else:
                # subscribe FIRST, then relist: changes landing between
                # the two are replayed by the fresh stream, never lost
                if not self._ensure_stream(sub):
                    return
        self._try_relist(sub)

    def _try_relist(self, sub: _Sub) -> bool:
        """One full list for this kind, replacing its slice of the
        snapshot. Prefers the backend's no-copy read-only snapshot path
        (``FakeCluster.list_snapshot``) — the cache never mutates what
        it ingests, so copying every object only to index it is waste."""
        snap = getattr(self._client, "list_snapshot", None)
        try:
            if snap is not None:
                items, rv = snap(sub.api_version, sub.kind)
            else:
                items = self._client.list(sub.api_version, sub.kind)
                rv = ""
        except Exception:
            log.exception("cache: relist of %s failed; serving the last "
                          "snapshot", sub.kind)
            with self._lock:
                self._dirty[sub.key] = None
            return False
        with self._lock:
            self._objects[sub.key] = {
                (ob.meta(o).get("namespace") or "", ob.meta(o)["name"]): o
                for o in items
            }
            self._dirty.pop(sub.key, None)
            self._stats["relists"] += 1
            self._rebuild_locked()
        if rv:
            sub.last_rv = rv
        elif items:
            sub.last_rv = max(
                (ob.meta(o).get("resourceVersion", "") for o in items),
                key=lambda s: int(s) if s.isdigit() else 0)
        return True

    # -- applying ------------------------------------------------------------

    def _apply(self, etype: str, obj: dict) -> None:
        key = (obj.get("apiVersion", ""), obj.get("kind", ""))
        if key not in self._objects:
            return
        m = ob.meta(obj)
        okey = (m.get("namespace") or "", m.get("name") or "")
        with self._lock:
            store = self._objects[key]
            old = store.get(okey)
            if etype == "DELETED":
                rv_new = _rv_of(obj)
                rv_old = _rv_of(old) if old is not None else None
                if old is not None and rv_new is not None \
                        and rv_old is not None and rv_new < rv_old:
                    # late/replayed DELETED for an OLDER incarnation:
                    # the cached object is a same-name recreation (e.g.
                    # folded in by a reconciler's note_write before the
                    # old incarnation's watch DELETED arrived). Evicting
                    # it — and tombstoning at ITS rv, as the max() below
                    # would — makes the live object unresurrectable when
                    # its own ADDED is delivered. Tombstone only the
                    # dead incarnation's rv; keep the live object.
                    self._tombstone_locked((key, okey), rv_new)
                    self._stats["stale_events"] += 1
                    return
                tomb = max((r for r in (rv_new, rv_old)
                            if r is not None), default=None)
                if tomb is not None:
                    self._tombstone_locked((key, okey), tomb)
                if old is None:
                    return
                del store[okey]
                bucket = self._by_ns[key].get(okey[0])
                if bucket is not None:
                    bucket.pop(okey, None)
                    if not bucket:
                        del self._by_ns[key][okey[0]]
                new = None
            else:
                # rv guard: never let an out-of-order or replayed event
                # roll an object backwards
                rv_new, rv_old = _rv_of(obj), _rv_of(old) if old else None
                if old is not None and rv_new is not None \
                        and rv_old is not None and rv_new <= rv_old:
                    self._stats["stale_events"] += 1
                    return
                if old is None:
                    # delete-then-note race: the pump applied DELETED,
                    # then an older write response (or replayed event)
                    # arrives — without a cached old the rv guard above
                    # cannot catch it, the tombstone does
                    tomb = self._tombstones.get((key, okey))
                    if tomb is not None and (rv_new is None
                                             or rv_new <= tomb):
                        self._stats["stale_events"] += 1
                        return
                store[okey] = new = obj
                self._by_ns[key].setdefault(okey[0], {})[okey] = None
                self._tombstones.pop((key, okey), None)
            self._stats["events"] += 1
            if key == NODE:
                self._apply_node_locked(okey[1], old, new)
            elif key == POD:
                self._apply_pod_locked(okey, old, new)

    def _tombstone_locked(self, tkey: tuple, rv: int) -> None:
        rv = max(rv, self._tombstones.pop(tkey, 0))  # re-add: keep FIFO fresh
        self._tombstones[tkey] = rv
        while len(self._tombstones) > TOMBSTONE_CAP:
            self._tombstones.pop(next(iter(self._tombstones)))

    def _apply_node_locked(self, name: str, old: dict | None,
                           new: dict | None) -> None:
        old_view = self._views.get(name)
        if old_view is not None:
            old_free = self._free.get(name, 0)
            self._bucket_remove_locked(old_view, old_free)
            del self._views[name]
            self._free.pop(name, None)
        if new is None:
            return
        view = N.node_view(new)
        free = view.allocatable_chips - self._used.get(name, 0)
        self._views[name] = view
        self._free[name] = free
        self._bucket_add_locked(view, free)

    def _bucket_add_locked(self, view: N.NodeView, free: int) -> None:
        self._buckets[C.ALL_NODES].add(free, view.name, view.spot)
        key = C.node_bucket_key(view.labels)
        if key is not C.ALL_NODES:
            self._buckets.setdefault(key, C.Bucket()).add(
                free, view.name, view.spot)

    def _bucket_remove_locked(self, view: N.NodeView, free: int) -> None:
        self._buckets[C.ALL_NODES].remove(free, view.name, view.spot)
        key = C.node_bucket_key(view.labels)
        if key is not C.ALL_NODES:
            b = self._buckets.get(key)
            if b is not None:
                b.remove(free, view.name, view.spot)

    @staticmethod
    def _pod_contrib(pod: dict | None) -> tuple[str, int] | None:
        """(node, chips) a pod holds: bound and non-terminal, else None."""
        if pod is None:
            return None
        node = (pod.get("spec") or {}).get("nodeName")
        if not node:
            return None
        if (pod.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
            return None
        return (node, N.pod_tpu_request(pod))

    def _apply_pod_locked(self, okey: tuple[str, str], old: dict | None,
                          new: dict | None) -> None:
        # label indexes (gang label + any controller-configured keys)
        for lbl in self._pod_labels:
            old_val = ob.labels_of(old).get(lbl) if old else None
            new_val = ob.labels_of(new).get(lbl) if new else None
            if old_val == new_val:
                continue
            index = self._by_label[lbl]
            if old_val:
                bucket = index.get((okey[0], old_val))
                if bucket is not None:
                    bucket.pop(okey, None)
                    if not bucket:
                        del index[(okey[0], old_val)]
            if new_val:
                index.setdefault((okey[0], new_val), {})[okey] = None
        # chip accounting + by-node index
        old_use = self._pod_use.get(okey)
        new_use = self._pod_contrib(new)
        if old_use == new_use:
            return
        if old_use is not None:
            node, chips = old_use
            del self._pod_use[okey]
            bucket = self._by_node.get(node)
            if bucket is not None:
                bucket.pop(okey, None)
                if not bucket:
                    del self._by_node[node]
            self._shift_node_locked(node, chips)
        if new_use is not None:
            node, chips = new_use
            self._pod_use[okey] = new_use
            self._by_node.setdefault(node, {})[okey] = None
            self._shift_node_locked(node, -chips)

    def _shift_node_locked(self, node: str, by: int) -> None:
        self._used[node] = self._used.get(node, 0) - by
        if not self._used[node]:
            del self._used[node]
        view = self._views.get(node)
        if view is None:
            return
        old_free = self._free.get(node, 0)
        new_free = old_free + by
        self._free[node] = new_free
        self._buckets[C.ALL_NODES].adjust(old_free, new_free, node,
                                          view.spot)
        key = C.node_bucket_key(view.labels)
        if key is not C.ALL_NODES:
            b = self._buckets.get(key)
            if b is not None:
                b.adjust(old_free, new_free, node, view.spot)

    def _rebuild_locked(self) -> None:
        """Rebuild every derived index from the raw object maps (after
        a relist replaced a kind's slice wholesale)."""
        self._views = {}
        self._used = {}
        self._free = {}
        self._buckets = {C.ALL_NODES: C.Bucket()}
        self._pod_use = {}
        self._by_node = {}
        self._by_label = {lbl: {} for lbl in self._pod_labels}
        self._by_ns = {k: {} for k in self._objects}
        for k, kind_store in self._objects.items():
            for okey in kind_store:
                self._by_ns[k].setdefault(okey[0], {})[okey] = None
        for okey, pod in self._objects.get(POD, {}).items():
            labels = ob.labels_of(pod)
            for lbl in self._pod_labels:
                val = labels.get(lbl)
                if val:
                    self._by_label[lbl].setdefault(
                        (okey[0], val), {})[okey] = None
            use = self._pod_contrib(pod)
            if use is not None:
                node, chips = use
                self._pod_use[okey] = use
                self._by_node.setdefault(node, {})[okey] = None
                self._used[node] = self._used.get(node, 0) + chips
        for okey, node_obj in self._objects.get(NODE, {}).items():
            view = N.node_view(node_obj)
            free = view.allocatable_chips - self._used.get(view.name, 0)
            self._views[view.name] = view
            self._free[view.name] = free
            self._bucket_add_locked(view, free)

    # -- snapshot reads (read-only references; never mutate) -----------------

    def objects(self, api_version: str, kind: str) -> dict:
        """{(namespace, name): object} for one kind — diffable against
        a fresh relist (the cache-correctness property tests)."""
        with self._lock:
            return dict(self._objects.get((api_version, kind), {}))

    def objects_ns(self, api_version: str, kind: str,
                   namespace: str) -> list[dict]:
        """One kind's objects in one namespace — O(namespace bucket),
        the namespaced-list analogue for snapshot reads."""
        key = (api_version, kind)
        with self._lock:
            self._stats["reads"] += 1
            store = self._objects.get(key, {})
            keys = self._by_ns.get(key, {}).get(namespace, ())
            return [store[k] for k in keys if k in store]

    def gang_pods(self, namespace: str, job: str) -> list[dict]:
        """Pods carrying the gang label, name-sorted (O(gang))."""
        return self.pods_by_label(JT.LABEL_JOB_NAME, namespace, job)

    def pods_by_label(self, label: str, namespace: str,
                      value: str) -> list[dict]:
        """Pods carrying ``label == value``, name-sorted (O(bucket)).
        The label must be in this cache's ``pod_labels`` — an unindexed
        key is a wiring bug, not a slow path."""
        with self._lock:
            self._stats["reads"] += 1
            store = self._objects[POD]
            keys = self._by_label[label].get((namespace, value), ())
            pods = [store[k] for k in keys if k in store]
        return sorted(pods, key=lambda p: ob.meta(p)["name"])

    def pods_on_node(self, node: str) -> list[dict]:
        """Bound, non-terminal pods holding this node's chips."""
        with self._lock:
            self._stats["reads"] += 1
            store = self._objects[POD]
            return [store[k] for k in self._by_node.get(node, ())
                    if k in store]

    def bound_pods(self) -> list[dict]:
        """Every bound, non-terminal pod (the preemption victim scan)."""
        with self._lock:
            self._stats["reads"] += 1
            store = self._objects[POD]
            return [store[k] for keys in self._by_node.values()
                    for k in keys if k in store]

    def node_views(self) -> dict[str, N.NodeView]:
        with self._lock:
            self._stats["reads"] += 1
            return dict(self._views)

    def node(self, name: str) -> dict | None:
        """The raw cached Node object (read-only reference) — for
        callers whose health semantics need more than a NodeView (e.g.
        the jaxjob slice-health check distinguishes 'no Ready condition
        yet' from 'Ready False')."""
        with self._lock:
            self._stats["reads"] += 1
            return self._objects[NODE].get(("", name))

    def unhealthy_bound_nodes(self) -> dict[str, str]:
        """Nodes that hold bound pods but are gone or NotReady —
        empty in the healthy steady state, which is what lets the
        health pass short-circuit without listing a single pod."""
        with self._lock:
            self._stats["reads"] += 1
            out: dict[str, str] = {}
            for node in self._by_node:
                v = self._views.get(node)
                if v is None:
                    out[node] = "deleted"
                elif not v.ready:
                    out[node] = "NotReady"
            return out

    def capacity(self) -> C.Capacity:
        """A placement snapshot: O(nodes) primitive copies (no object
        deep-copies, no relist) — the admission pass trials against it
        via CapacityTxn overlays."""
        with self._lock:
            self._stats["reads"] += 1
            return C.Capacity(
                dict(self._views), dict(self._free),
                {k: b.clone() for k, b in self._buckets.items()})

    def stats(self) -> dict[str, int]:
        with self._lock:
            return dict(self._stats)
