"""Graceful preemption: SIGTERM -> checkpoint -> EX_TEMPFAIL -> resume.

SURVEY.md §5's slice-preemption hard part: the reference has no story
beyond per-replica restart; here the interrupted step is persisted so
the gang restart loses no progress.
"""

import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time

import jax
import pytest

from kubeflow_tpu.parallel.mesh import MeshSpec
from kubeflow_tpu.runtime.checkpoint import Checkpointer
from kubeflow_tpu.runtime.preemption import EX_TEMPFAIL, PreemptionNotice
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


def lm_cfg(tmp, **over):
    cfg = dict(
        model="transformer-test",
        task="lm",
        global_batch=8,
        seq_len=16,
        vocab_size=64,
        mesh=MeshSpec(data=8),
        optimizer="adamw",
        learning_rate=1e-3,
        total_steps=50,
        warmup_steps=1,
        checkpoint_dir=str(tmp),
        checkpoint_every=1000,  # periodic saves far away: the preemption
        log_every=10**9,        # save must come from the stop path
    )
    cfg.update(over)
    return TrainConfig.from_dict(cfg)


def test_stop_flag_checkpoints_and_returns_early(tmp_path, devices8):
    notice = PreemptionNotice()  # not installed: no signal handler needed
    fired = {"at": None}

    def cb(i, m):
        if i == 3:
            notice.trigger()
            fired["at"] = i

    trainer = Trainer(lm_cfg(tmp_path))
    state, summary = trainer.fit(callback=cb, stop=notice)
    assert summary["preempted"] is True
    assert fired["at"] == 3
    step = int(state.step)
    assert 0 < step < 50
    # the interrupted step is durable and resumable
    ck = Checkpointer(str(tmp_path))
    assert ck.latest_step() == step
    ck.close()
    trainer2 = Trainer(lm_cfg(tmp_path))
    state2, summary2 = trainer2.fit(steps=step + 2)
    assert summary2["start_step"] == step
    assert "preempted" not in summary2
    assert int(state2.step) == step + 2


@pytest.mark.slow
def test_sigterm_in_launcher_exits_tempfail(tmp_path):
    """Real process contract: SIGTERM mid-run => checkpoint + exit 75.
    Slow tier: spawns a real training subprocess (cold compile)."""
    cfg = {
        "model": "transformer-test", "task": "lm", "global_batch": 4,
        "seq_len": 16, "vocab_size": 64, "mesh": {"data": 1},
        "optimizer": "adamw", "learning_rate": 1e-3,
        "total_steps": 2000, "warmup_steps": 1,
        "checkpoint_dir": str(tmp_path / "ckpt"), "checkpoint_every": 1000,
        "log_every": 1,
    }
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAXRT_METRICS_PORT="0")
    env.pop("XLA_FLAGS", None)  # single-device run
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.runtime.launcher",
         "--config", str(cfg_path)],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    # wait for training to actually progress (a step-log line), then
    # TERM. Lines come through a reader thread so a wedged subprocess
    # fails the deadline instead of hanging the test on readline.
    lines: "queue.Queue[str | None]" = queue.Queue()

    def reader():
        for ln in proc.stdout:
            lines.put(ln)
        lines.put(None)

    threading.Thread(target=reader, daemon=True).start()
    deadline = time.monotonic() + 240
    collected = []
    while True:
        assert time.monotonic() < deadline, \
            f"no training progress seen; output so far: {collected[-20:]}"
        try:
            line = lines.get(timeout=5.0)
        except queue.Empty:
            continue
        assert line is not None, f"launcher exited early: {collected[-20:]}"
        collected.append(line)
        if "step " in line or "first step" in line:
            break
    time.sleep(1.0)
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=120)
    while True:  # drain the reader
        ln = lines.get(timeout=10.0)
        if ln is None:
            break
        collected.append(ln)
    out = "".join(collected)
    assert rc == EX_TEMPFAIL, (rc, out[-2000:])
    [summary_line] = [ln for ln in out.splitlines() if '"summary"' in ln]
    summary = json.loads(summary_line)["summary"]
    assert summary["preempted"] is True
    # a checkpoint exists at the preempted step
    ck = Checkpointer(str(tmp_path / "ckpt"))
    assert ck.latest_step() is not None and ck.latest_step() > 0
    ck.close()


def test_preempt_before_first_step_yields_valid_json_summary(tmp_path, devices8):
    """Preemption can land before any step completes; the summary must
    still be json.dumps-able with strict parsers (no bare NaN)."""
    notice = PreemptionNotice()
    notice.trigger()  # already preempted at loop entry
    trainer = Trainer(lm_cfg(tmp_path))
    state, summary = trainer.fit(stop=notice)
    assert summary["preempted"] is True
    parsed = json.loads(json.dumps({"summary": summary}, allow_nan=False))
    assert parsed["summary"]["step_time_s"] is None


def test_resume_then_preempt_keeps_existing_checkpoint(tmp_path, devices8):
    """A second preemption before the first post-resume step must not
    delete-and-rewrite the checkpoint it resumed from (force=True's
    delete-then-save window would leave zero durable checkpoints if the
    grace period expired mid-save)."""
    trainer = Trainer(lm_cfg(tmp_path))
    notice = PreemptionNotice()

    def cb(i, m):
        if i == 2:
            notice.trigger()

    state, summary = trainer.fit(callback=cb, stop=notice)
    step = int(state.step)
    ck = Checkpointer(str(tmp_path))
    assert ck.latest_step() == step
    ck.close()
    # fingerprint the finalized checkpoint: a delete-then-rewrite would
    # change the metadata file's mtime even if latest_step() ends up equal
    meta = next(p for p in tmp_path.glob("*/_CHECKPOINT_METADATA"))
    before = (meta.stat().st_mtime_ns, meta.stat().st_ino)

    # gang restart resumes at `step`, preempted again immediately
    notice2 = PreemptionNotice()
    notice2.trigger()
    trainer2 = Trainer(lm_cfg(tmp_path))
    state2, summary2 = trainer2.fit(stop=notice2)
    assert summary2["preempted"] is True
    assert int(state2.step) == step
    ck2 = Checkpointer(str(tmp_path))
    assert ck2.latest_step() == step
    ck2.close()
    assert (meta.stat().st_mtime_ns, meta.stat().st_ino) == before, \
        "checkpoint was rewritten, not kept untouched"
