"""Pluggable collectives backends: the ONE entry point for world I/O.

The reference platform had exactly one way to wire processes together —
the TFJob operator's TF_CONFIG plus gRPC parameter servers. On TPU the
transport is layered: chips inside a slice talk over ICI, slices talk
over DCN (libtpu's MEGASCALE transport), and the scaling recipe for both
("Scale MLPerf-0.6 models on Google TPU-v3 Pods", PAPERS.md) is a
HIERARCHICAL reduction — reduce-scatter inside the fast level, a single
all-reduce across the slow level, all-gather back out.

This module makes that layering a swappable policy instead of env-var
folklore spread across the tree:

- ``CollectivesBackend``: ``form(env) -> Mesh`` / ``reshape`` /
  ``teardown`` world lifecycle, a mesh-axes→levels map (which logical
  axes ride ICI vs DCN), and ``hierarchical_reduce(tree, axis)``.
- ``TpuIciDcnBackend``: the real path — ``jax.distributed`` +
  MEGASCALE env via ``slice_env``, a 2-level ``(dcn, ici, ...)`` hybrid
  mesh, and the MLPerf-pod reduce shape.
- ``LoopbackBackend``: hermetic — multi-process worlds join over a
  plain TCP barrier (no multiprocess jax, which this image's CPU
  backend cannot run — CHANGES PR 3) and multislice worlds partition
  the local CPU device set into N in-process "slices". Formation,
  resharding and teardown all run for real, which is what makes the
  multi-slice plane tier-1-testable.
- ``SingleBackend``: today's behavior, the default, byte-compatible.

Selection: env ``JAXJOB_COLLECTIVES_BACKEND`` ∈ {single, loopback, tpu}.
``dist.initialize_from_env``/``shutdown`` route through the selected
backend; no other module may call ``jax.distributed.initialize``/
``shutdown`` or spell a MEGASCALE key (tpulint COLL401 enforces this —
the exemption list is exactly this module).

Import-light: jax is deferred inside methods so the control plane can
import the contract pieces (via dist) without pulling in jax.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
from typing import Sequence

log = logging.getLogger("kubeflow_tpu.backends")

ENV_BACKEND = "JAXJOB_COLLECTIVES_BACKEND"
BACKEND_SINGLE = "single"
BACKEND_LOOPBACK = "loopback"
BACKEND_TPU = "tpu"

# Mesh-axes→backend-levels map values: which transport a logical mesh
# axis rides. Axes mapped to LEVEL_DCN are laid OUTERMOST over the slice
# boundary (slices are contiguous-rank, so outermost == cross-slice);
# everything else stays ICI-contiguous inside a slice.
LEVEL_ICI = "ici"
LEVEL_DCN = "dcn"
# Extra axes to lay over DCN (comma-separated), e.g. "pipe" to span
# pipeline stages across slices. The `dcn` axis itself is always DCN.
ENV_DCN_AXES = "JAXJOB_MESH_DCN_AXES"

# Loopback join-barrier tuning (tests shrink these).
ENV_LOOPBACK_JOIN_TIMEOUT = "JAXJOB_LOOPBACK_JOIN_TIMEOUT_S"

# The libtpu DCN transport's env contract. This module is the ONE place
# these keys are spelled (COLL401).
_MS_PREFIX = "MEGASCALE_"
MS_NUM_SLICES = "MEGASCALE_NUM_SLICES"
MS_SLICE_ID = "MEGASCALE_SLICE_ID"
MS_PORT = "MEGASCALE_PORT"
MS_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"


def slice_env(num_slices: int, slice_id: int,
              coordinator_address: str | None) -> dict[str, str]:
    """Multislice env block: the JAXJOB_* contract plus the MEGASCALE_*
    vars libtpu's DCN transport reads at backend init. The megascale
    coordinator rides the same host as the jax.distributed one."""
    from kubeflow_tpu.parallel import dist as D

    env = {
        D.ENV_NUM_SLICES: str(num_slices),
        D.ENV_SLICE_ID: str(slice_id),
        MS_NUM_SLICES: str(num_slices),
        MS_SLICE_ID: str(slice_id),
        MS_PORT: str(D.MEGASCALE_PORT),
    }
    host = (coordinator_address or "").partition(":")[0]
    if host:
        env[MS_COORDINATOR] = f"{host}:{D.MEGASCALE_PORT}"
    return env


def _raw_jax_initialize(cfg) -> None:
    """The repo's ONLY jax.distributed.initialize call site (COLL401).
    Reached through dist._jax_initialize so tests can monkeypatch the
    seam without touching backend internals."""
    import jax  # deferred: must happen before any backend init

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )


def _raw_jax_shutdown() -> None:
    import jax

    jax.distributed.shutdown()


# -- level-mapped mesh construction ------------------------------------------


def dcn_axes_from_env(env: dict[str, str] | None = None) -> tuple[str, ...]:
    src = os.environ if env is None else env
    extra = [a.strip() for a in src.get(ENV_DCN_AXES, "").split(",")
             if a.strip()]
    return tuple(extra)


def build_level_mesh(spec=None, devices=None,
                     levels: dict[str, str] | None = None,
                     hybrid: bool = False):
    """Build a Mesh honoring a mesh-axes→levels map.

    ONE code path for every placement: axes mapped to LEVEL_DCN are laid
    outermost (over the slice boundary, matching the controller's
    contiguous-rank slice assignment), the rest keep the canonical
    inner order. The default map ({dcn: dcn}) reproduces
    ``mesh.build_mesh`` exactly — byte-compatible. ``hybrid=True`` (the
    real-TPU path) places the DCN-level axes with
    ``create_hybrid_device_mesh`` so intra-slice axes stay
    ICI-contiguous."""
    import jax
    import numpy as np

    from kubeflow_tpu.parallel import mesh as M

    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = M.MeshSpec()
    if not isinstance(spec, M.MeshSpec):
        spec = M.MeshSpec.from_dict(spec)
    spec = spec.resolve(len(devices))
    sizes = spec.axis_sizes()
    lv = {M.AXIS_DCN: LEVEL_DCN}
    lv.update(levels or {})
    lv[M.AXIS_DCN] = LEVEL_DCN  # the dcn axis is DCN by definition
    dcn_axes = [a for a in M.AXIS_NAMES
                if lv.get(a) == LEVEL_DCN and sizes[a] > 1]
    if not hybrid and dcn_axes in ([], [M.AXIS_DCN]):
        # degenerate map: identical placement, identical code
        return M.build_mesh(spec, devices)
    dev_np = np.asarray(devices, dtype=object)
    if hybrid and dcn_axes and all(
            getattr(d, "slice_index", None) is not None for d in devices):
        from jax.experimental import mesh_utils

        ici_shape = tuple(1 if a in dcn_axes else sizes[a]
                          for a in M.AXIS_NAMES)
        dcn_shape = tuple(sizes[a] if a in dcn_axes else 1
                          for a in M.AXIS_NAMES)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=dev_np)
        return jax.sharding.Mesh(dev_array, M.AXIS_NAMES)
    # reshape path (CPU / in-process slices): DCN-level axes lead so they
    # fall on slice boundaries, then transpose back to canonical order
    order = dcn_axes + [a for a in M.AXIS_NAMES if a not in dcn_axes]
    arr = dev_np.reshape(tuple(sizes[a] for a in order))
    perm = tuple(order.index(a) for a in M.AXIS_NAMES)
    return jax.sharding.Mesh(arr.transpose(perm), M.AXIS_NAMES)


# -- the backend protocol ----------------------------------------------------


class CollectivesBackend:
    """World lifecycle + hierarchical reduction policy.

    ``join``/``leave`` are the process-level halves called by
    ``dist.initialize_from_env``/``shutdown`` under the world lock;
    ``form``/``reshape``/``teardown`` are the full-surface protocol
    (world + mesh) the elastic coordinator and tests drive."""

    name = "abstract"

    def __init__(self) -> None:
        self._mesh = None
        self._lock = threading.Lock()

    # -- process-level world lifecycle (dist.* routes here) ------------------

    def join(self, cfg, *, wait: bool = True) -> bool:
        """Join the world described by ``cfg``. Returns True when this
        backend now holds live state that ``leave`` must tear down."""
        raise NotImplementedError

    def leave(self) -> None:
        """Tear down the state ``join`` formed (idempotent)."""
        raise NotImplementedError

    # -- mesh-level (the axes→levels map is the single placement story) -----

    def level_map(self, env: dict[str, str] | None = None) -> dict[str, str]:
        from kubeflow_tpu.parallel import mesh as M

        lv = {M.AXIS_DCN: LEVEL_DCN}
        for a in dcn_axes_from_env(env):
            lv[a] = LEVEL_DCN
        return lv

    def mesh(self, spec=None, devices=None,
             levels: dict[str, str] | None = None):
        m = build_level_mesh(spec, devices,
                             levels if levels is not None
                             else self.level_map(),
                             hybrid=False)
        self._mesh = m
        return m

    def form(self, env: dict[str, str] | None = None, *, spec=None,
             devices=None, wait: bool = True):
        """Form the world from ``env`` (via dist, so re-entrancy and
        teardown-on-change hold) and build its mesh. Returns the Mesh."""
        from kubeflow_tpu.parallel import dist as D

        e = dict(os.environ if env is None else env)
        e[ENV_BACKEND] = self.name  # form() pins the selection
        D.initialize_from_env(e, wait=wait)
        return self.mesh(spec, devices)

    def reshape(self, env: dict[str, str] | None = None, *, spec=None,
                devices=None, wait: bool = True):
        """Re-form at a CHANGED world: teardown then form — the elastic
        resize path, through the same code as first formation."""
        self.teardown()
        return self.form(env, spec=spec, devices=devices, wait=wait)

    def teardown(self) -> None:
        from kubeflow_tpu.parallel import dist as D

        self._mesh = None
        D.shutdown()

    # -- reduction policy ----------------------------------------------------

    def _axis_extent(self, axes: Sequence[str]) -> int | None:
        from kubeflow_tpu.parallel import mesh as M

        m = M.current_mesh() or self._mesh
        if m is None:
            return None
        n = 1
        for a in axes:
            n *= m.shape[a]
        return n

    def hierarchical_reduce(self, tree, axis: str | None = None,
                            ici_axes: Sequence[str] | None = None):
        """Sum ``tree`` across ``ici_axes`` (fast level) and ``axis``
        (slow level). Single-level backends reduce flat; see
        TpuIciDcnBackend for the hierarchical shape."""
        import jax

        from kubeflow_tpu.parallel import mesh as M

        axis = axis or M.AXIS_DCN
        ici = tuple(ici_axes) if ici_axes is not None else (M.AXIS_DATA,)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, ici + (axis,)), tree)


class SingleBackend(CollectivesBackend):
    """Today's behavior, byte-compatible: jax.distributed for multi-host
    worlds, MEGASCALE env derived (setdefault) for multislice, flat
    reduction. The default when JAXJOB_COLLECTIVES_BACKEND is unset."""

    name = BACKEND_SINGLE

    def join(self, cfg, *, wait: bool = True) -> bool:
        from kubeflow_tpu.parallel import dist as D

        if cfg.multislice:
            # libtpu reads MEGASCALE_* at backend init; when only the
            # JAXJOB_* contract is present (bare launch, tests) derive
            # them here so the DCN transport still configures itself
            # before jax imports
            for k, v in slice_env(cfg.num_slices, cfg.slice_id,
                                  cfg.coordinator_address).items():
                if k.startswith(_MS_PREFIX):
                    os.environ.setdefault(k, v)
        if not cfg.distributed:
            return False
        if wait and cfg.process_id != 0:
            D.wait_for_coordinator(cfg.coordinator_address)
        log.info(
            "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
            cfg.coordinator_address, cfg.num_processes, cfg.process_id,
        )
        D._jax_initialize(cfg)  # the monkeypatchable seam (test contract)
        return True

    def leave(self) -> None:
        from kubeflow_tpu.parallel import dist as D

        D._jax_shutdown()


class TpuIciDcnBackend(SingleBackend):
    """The real multislice path: jax.distributed + MEGASCALE env (OVERWRITTEN
    on re-formation — a resized slice set must not keep stale counts), a
    2-level (dcn, ici, ...) hybrid mesh, and the MLPerf-pod hierarchical
    reduce: reduce-scatter over ICI, one all-reduce over DCN, all-gather
    back."""

    name = BACKEND_TPU

    def join(self, cfg, *, wait: bool = True) -> bool:
        if cfg.multislice:
            # overwrite, not setdefault: an elastic slice resize re-forms
            # with a different num_slices/slice_id and libtpu must see
            # the NEW values
            for k, v in slice_env(cfg.num_slices, cfg.slice_id,
                                  cfg.coordinator_address).items():
                if k.startswith(_MS_PREFIX):
                    os.environ[k] = v
        return super().join(cfg, wait=wait)

    def mesh(self, spec=None, devices=None,
             levels: dict[str, str] | None = None):
        m = build_level_mesh(spec, devices,
                             levels if levels is not None
                             else self.level_map(),
                             hybrid=True)
        self._mesh = m
        return m

    def hierarchical_reduce(self, tree, axis: str | None = None,
                            ici_axes: Sequence[str] | None = None):
        """reduce-scatter(ici) → all-reduce(dcn) → all-gather(ici): the
        DCN hop moves 1/ici_size of the tensor instead of all of it.
        Falls back to a flat psum when the leading dim does not tile
        over the ICI extent (numerically both are plain sums)."""
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.parallel import mesh as M

        axis = axis or M.AXIS_DCN
        ici = tuple(ici_axes) if ici_axes is not None else (M.AXIS_DATA,)
        n_ici = self._axis_extent(ici)

        def red(x):
            x = jnp.asarray(x)
            if (n_ici and n_ici > 1 and x.ndim >= 1
                    and x.shape[0] % n_ici == 0):
                y = jax.lax.psum_scatter(x, ici, scatter_dimension=0,
                                         tiled=True)
                y = jax.lax.psum(y, axis)
                return jax.lax.all_gather(y, ici, axis=0, tiled=True)
            return jax.lax.psum(x, ici + (axis,))

        return jax.tree_util.tree_map(red, tree)


class LoopbackBackend(CollectivesBackend):
    """Hermetic formation without multiprocess jax.

    Multi-process worlds join over a plain TCP barrier: rank 0 binds the
    coordinator port and admits exactly num_processes-1 distinct peers
    before releasing anyone — real world formation and teardown
    semantics (a missing peer blocks the gang; teardown closes the
    sockets) with each rank then training on its own local device set.

    Multislice worlds (num_slices > 1, one process) partition the local
    CPU device set into N in-process "slices": the dcn mesh axis falls
    on the partition boundary, so cross-slice reduction, resharding and
    slice-shrink all execute for real on one host."""

    name = BACKEND_LOOPBACK

    def __init__(self) -> None:
        super().__init__()
        self._server: socket.socket | None = None
        self._conns: list[socket.socket] = []
        self._formed = False

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("loopback peer closed during join")
            buf += chunk
        return buf

    def _join_timeout(self) -> float:
        return float(os.environ.get(ENV_LOOPBACK_JOIN_TIMEOUT, "120"))

    def join(self, cfg, *, wait: bool = True) -> bool:
        from kubeflow_tpu.parallel import dist as D

        with self._lock:
            if cfg.distributed:
                host, _, port = (cfg.coordinator_address or "").partition(":")
                port = int(port or D.DEFAULT_COORD_PORT)
                timeout = self._join_timeout()
                if cfg.process_id == 0:
                    self._serve_barrier(host, port, cfg.num_processes,
                                        timeout)
                else:
                    if wait:
                        D.wait_for_coordinator(cfg.coordinator_address,
                                               timeout_s=timeout)
                    conn = socket.create_connection((host or "127.0.0.1",
                                                     port), timeout=timeout)
                    conn.settimeout(timeout)
                    conn.sendall(cfg.process_id.to_bytes(4, "big"))
                    if self._recv_exact(conn, 2) != b"go":
                        raise ConnectionError("loopback barrier refused")
                    self._conns.append(conn)
                log.info("loopback world formed: rank %d/%d",
                         cfg.process_id, cfg.num_processes)
            self._formed = cfg.distributed or cfg.multislice
            return self._formed

    def _serve_barrier(self, host: str, port: int, nproc: int,
                       timeout: float) -> None:
        import time as _time

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host or "", port))
        srv.listen(nproc)
        srv.settimeout(0.5)
        peers: dict[int, socket.socket] = {}
        deadline = _time.monotonic() + timeout
        try:
            while len(peers) < nproc - 1:
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"loopback barrier: {len(peers)}/{nproc - 1} peers "
                        f"after {timeout}s")
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                conn.settimeout(timeout)
                try:
                    rank = int.from_bytes(self._recv_exact(conn, 4), "big")
                except ConnectionError:
                    # a wait_for_coordinator readiness probe: it connects
                    # and closes without a handshake — not a peer
                    conn.close()
                    continue
                peers[rank] = conn
            for conn in peers.values():
                conn.sendall(b"go")
        except BaseException:
            for conn in peers.values():
                conn.close()
            srv.close()
            raise
        self._server = srv
        self._conns = list(peers.values())

    def leave(self) -> None:
        with self._lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns = []
            if self._server is not None:
                try:
                    self._server.close()
                except OSError:
                    pass
                self._server = None
            self._formed = False

    # -- in-process slices ---------------------------------------------------

    @staticmethod
    def slice_groups(devices, num_slices: int):
        """Partition the local device list into num_slices contiguous
        in-process "slices" (the dcn axis falls on group boundaries)."""
        if num_slices < 1 or len(devices) % num_slices:
            raise ValueError(
                f"{len(devices)} devices do not partition into "
                f"{num_slices} slices")
        per = len(devices) // num_slices
        return [list(devices[i * per:(i + 1) * per])
                for i in range(num_slices)]

    def mesh(self, spec=None, devices=None,
             levels: dict[str, str] | None = None):
        import jax

        from kubeflow_tpu.parallel import dist as D
        from kubeflow_tpu.parallel import mesh as M

        if devices is None:
            devices = jax.devices()
        cfg = D.active_world()
        if spec is None and cfg is not None and cfg.multislice:
            # default spec for an in-process multislice world: dcn over
            # the slice partition, data over the rest
            self.slice_groups(devices, cfg.num_slices)  # validates
            spec = M.MeshSpec(dcn=cfg.num_slices)
        m = build_level_mesh(spec, devices,
                             levels if levels is not None
                             else self.level_map(),
                             hybrid=False)
        self._mesh = m
        return m

    def hierarchical_reduce(self, tree, axis: str | None = None,
                            ici_axes: Sequence[str] | None = None):
        # in-process slices reduce exactly like the real 2-level path
        # (the dcn axis is a real mesh axis here) — share its shape
        return TpuIciDcnBackend.hierarchical_reduce(self, tree, axis,
                                                    ici_axes)


_REGISTRY = {
    BACKEND_SINGLE: SingleBackend,
    BACKEND_LOOPBACK: LoopbackBackend,
    BACKEND_TPU: TpuIciDcnBackend,
}
_instances: dict[str, CollectivesBackend] = {}
_instances_lock = threading.Lock()


def get_backend(name: str | None = None,
                env: dict[str, str] | None = None) -> CollectivesBackend:
    """The selected backend (module singleton). Explicit name wins, then
    the caller's env, then the process env, then the byte-compatible
    default (single)."""
    if name is None:
        name = ((env or {}).get(ENV_BACKEND)
                or os.environ.get(ENV_BACKEND) or BACKEND_SINGLE)
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown collectives backend {name!r}; "
            f"known: {sorted(_REGISTRY)}") from None
    with _instances_lock:
        if name not in _instances:
            _instances[name] = cls()
        return _instances[name]
