#!/usr/bin/env bash
# Probe the axon TPU tunnel every ~10 min; when it answers, run the queued
# LM sweep (tools/lm_sweep.py) exactly once and exit. Writes status lines to
# tools/tunnel_watch.log so the foreground session can see what happened.
set -u
cd "$(dirname "$0")/.."
LOG=tools/tunnel_watch.log
echo "watch start $(date -u +%H:%M:%S)" >> "$LOG"
while true; do
  if timeout 120 python -c "import jax; assert jax.devices()[0].platform=='tpu'" 2>/dev/null; then
    echo "tunnel UP $(date -u +%H:%M:%S) — launching lm_sweep" >> "$LOG"
    python tools/lm_sweep.py >> "$LOG" 2>&1
    echo "sweep finished $(date -u +%H:%M:%S) — validating promoted bench" >> "$LOG"
    # full headline run at the (possibly promoted) defaults: proves the
    # promotion end-to-end on hardware and leaves a fresh JSON in the log
    timeout 1600 python bench.py >> "$LOG" 2>&1
    echo "bench validation done $(date -u +%H:%M:%S)" >> "$LOG"
    exit 0
  fi
  echo "tunnel down $(date -u +%H:%M:%S)" >> "$LOG"
  sleep 600
done
