"""Orbax checkpoint/resume for the training runtime.

The reference has NO training checkpointing — it delegates all model state
to the TF code inside its payload images, and its only "resume" story is
per-replica `restartPolicy: OnFailure` with a sleep-forever guard
(tf-controller-examples/tf-cnn/launcher.py:90-93). The platform-level
state persistence it does have is git-pushing app dirs to Cloud Source
Repos (bootstrap/cmd/bootstrap/app/ksServer.go:239-267).

On TPU, gang restart is the *only* sane failure policy (a partially
restarted jax.distributed world can never re-form a mesh), which makes
training checkpointing a platform concern: the JAXJob controller tears
down and recreates the whole pod set on any worker failure, and every
worker resumes from the latest persisted step. This module is that
mechanism — async orbax saves off the critical path, sharding-aware
restore onto the live mesh.

Design:
- `Checkpointer` wraps `orbax.checkpoint.CheckpointManager` (async saves,
  max_to_keep retention, atomic finalize so a preempted save is never
  visible as "latest").
- The persisted payload is the pure-array subset of `TrainState`
  ({step, params, batch_stats, opt_state}); the optimizer *transform* is
  rebuilt from config on restore (it is code, not state).
- Restore takes a live template state and restores onto the template's
  shardings, so a resumed job lands arrays directly on the mesh with zero
  reshard traffic when the topology is unchanged — and orbax reshards
  automatically when it isn't (elastic resume onto a different slice).
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax

log = logging.getLogger("kubeflow_tpu.checkpoint")


# re-export: the jax-free implementation lives in utils/fsatomic.py so
# obs/trace.py (which must not import this jax-importing module) shares
# the exact same crash-consistency code
from kubeflow_tpu.utils.fsatomic import atomic_write_text  # noqa: F401


def _payload(state) -> dict:
    """The persisted pytree: everything in TrainState that is data."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


def _abstract(tree) -> Any:
    """Map a live pytree to ShapeDtypeStruct leaves carrying shardings,
    the restore target orbax needs to place arrays on the mesh."""

    def one(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x

    return jax.tree.map(one, tree)


def _normalize_dir(directory: str) -> str:
    """Local paths become absolute and are created; remote URIs
    (gs://, s3://, ...) pass through untouched — orbax handles them via
    epath, and abspath would mangle the scheme into a pod-local path
    (silently defeating gang-restart resume)."""
    if "://" in directory:
        return directory
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    return directory


def _match_commitment(template, restored):
    """Orbax returns every leaf *committed* to its restore device. Leaves
    whose template was an uncommitted single-device array (optimizer state,
    the step counter — anything jit normally re-places freely) must come
    back uncommitted too, or the next jitted step rejects the mix of
    committed single-device and committed mesh-sharded arguments."""
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    def one(t, r):
        if isinstance(t, jax.Array) and not isinstance(t.sharding, NamedSharding):
            return jnp.asarray(np.asarray(r))
        return r

    return jax.tree.map(one, template, restored)


class Checkpointer:
    """Async orbax checkpointing with resume-from-latest.

    Usage (what Trainer.fit does):
        ckpt = Checkpointer(cfg.checkpoint_dir, keep=cfg.checkpoint_keep)
        state = ckpt.restore_latest(state) or state   # gang-restart resume
        ...
        ckpt.save(step, state)                        # async, non-blocking
        ...
        ckpt.close()                                  # wait + release
    """

    def __init__(self, directory: str, keep: int = 3, async_save: bool = True,
                 world_size: int | None = None, num_slices: int | None = None):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = _normalize_dir(directory)
        # Pre-register the failure counter at 0 (both ops): the
        # CheckpointFailures alert reads increase(), which needs a
        # 0-sample BEFORE the first failure to see a delta — a counter
        # born at 1 and flat thereafter never alerts on the very first
        # failed save, the rare event the alert exists for.
        from kubeflow_tpu.runtime import metrics as rt_metrics

        for op in ("save", "restore"):
            rt_metrics.REGISTRY.counter_inc(
                "checkpoint_failures_total",
                help_="checkpoint saves/restores that raised",
                by=0.0, op=op)
        # elastic bookkeeping: the world size each step was SAVED at,
        # recorded into the manifest so dashboards/preflight can answer
        # "this resume reshards 8 -> 2" without opening orbax metadata.
        # Restore itself is world-agnostic (global shapes are
        # layout-independent; restore() reshards onto the template's
        # mesh) — this is provenance, not a restore precondition.
        self.world_size = world_size
        self._world_sizes: dict[int, int] = {}
        # multi-slice provenance (same contract as world_size): the
        # slice count each step was saved at, so "this resume reshards
        # 2 slices -> 1" reads from the manifest. Restore stays
        # slice-agnostic — resharding is the template-mesh path.
        self.num_slices = num_slices
        self._slice_counts: dict[int, int] = {}
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                enable_async_checkpointing=async_save,
                create=True,
            ),
        )

    # -- inspection --------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    # -- save / restore ----------------------------------------------------

    def save(self, step: int, state, force: bool = False) -> bool:
        """Queue an async save of `state` at `step`. Device->host transfer
        happens before return; the filesystem write is off-thread.
        A step that already exists in the directory (e.g. a resume=False
        rerun over a populated dir) is skipped unless force=True, which
        overwrites it."""
        if int(step) in self._mgr.all_steps():
            if not force:
                log.warning("checkpoint: step %d already exists in %s; skipping "
                            "(pass force=True to overwrite)", step, self.directory)
                return False
            # orbax raises StepAlreadyExistsError even with force=True;
            # delete-then-save is the overwrite.
            self._mgr.delete(int(step))
        # train.checkpoint span: the device->host + queue window this
        # call blocks the step loop for — the goodput ledger's
        # `checkpoint` bucket (obs/goodput.py) reads exactly this name.
        from kubeflow_tpu.obs import trace as obs_trace
        from kubeflow_tpu.runtime import metrics as rt_metrics

        try:
            with obs_trace.TRACER.span("train.checkpoint", step=int(step)):
                saved = self._mgr.save(
                    int(step),
                    args=self._ocp.args.StandardSave(_payload(state)),
                    force=force,
                )
        except Exception:
            # alertable (CheckpointFailures in the default rule pack):
            # a job silently failing to persist progress is the outage
            # an operator finds out about at the NEXT preemption
            rt_metrics.REGISTRY.counter_inc(
                "checkpoint_failures_total",
                help_="checkpoint saves/restores that raised", op="save")
            raise
        if saved:
            if self.world_size:
                self._world_sizes[int(step)] = self.world_size
            if self.num_slices:
                self._slice_counts[int(step)] = self.num_slices
            log.info("checkpoint: queued save at step %d -> %s", step, self.directory)
        return bool(saved)

    def restore(self, step: int, template_state):
        """Restore `step` onto the shardings of `template_state`, returning
        a new TrainState (the template's optimizer transform is reused)."""
        template = _payload(template_state)
        restored = self._mgr.restore(
            int(step), args=self._ocp.args.StandardRestore(_abstract(template))
        )
        restored = _match_commitment(template, restored)
        log.info("checkpoint: restored step %d from %s", step, self.directory)
        return template_state.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
        )

    def restore_latest(self, template_state):
        """Resume-from-latest: returns a restored state, or None when the
        directory has no restorable checkpoint (fresh start).

        Corruption-tolerant: a checkpoint that fails to restore (a node
        killed mid-save before orbax finalized, a truncated array file,
        bit rot on the shared volume) is SKIPPED and the previous good
        step is tried — raising here would wedge every gang restart in
        a crash loop on one bad file, which is exactly when resume
        matters most.

        But if EVERY step fails, the likely cause is systematic (the
        checkpoint volume unreachable, a sharding/template mismatch) —
        not three independently-corrupt files — so the LAST error is
        re-raised rather than silently starting fresh: a fresh start
        both discards all progress and lets max_to_keep GC delete the
        good checkpoints as new saves land, while crash-and-retry
        resumes correctly the moment the volume returns. None (fresh
        start) is returned only for a genuinely empty directory."""
        steps = sorted(self.all_steps(), reverse=True)
        last_error: Exception | None = None
        for i, step in enumerate(steps):
            try:
                return self.restore(step, template_state)
            except Exception as e:  # orbax raises backend-specific types
                from kubeflow_tpu.runtime import metrics as rt_metrics

                rt_metrics.REGISTRY.counter_inc(
                    "checkpoint_failures_total",
                    help_="checkpoint saves/restores that raised",
                    op="restore")
                last_error = e
                log.warning(
                    "checkpoint: step %d in %s is unrestorable (%s: %s); "
                    "falling back to %s", step, self.directory,
                    type(e).__name__, e,
                    f"step {steps[i + 1]}" if i + 1 < len(steps)
                    else "no remaining steps")
        if last_error is not None:
            raise last_error
        return None

    # -- lifecycle ---------------------------------------------------------

    def _write_manifest(self) -> None:
        """Crash-consistent resume manifest next to the checkpoints:
        dashboards and preflight tooling read "what step would this job
        resume from" without importing orbax. Written atomically (temp
        + fsync + rename) AFTER saves finalize, so it never names a
        step that is not durably on disk. Best-effort: remote URIs and
        I/O errors skip it (orbax metadata stays the source of truth)."""
        if "://" in self.directory:
            return
        import json

        path = os.path.join(self.directory, "manifest.json")
        try:
            steps = self.all_steps()
            # elastic provenance: merge world sizes recorded by PRIOR
            # incarnations (a resized worker reopens the same dir) with
            # this process's saves, pruned to steps still on disk
            sizes: dict[str, int] = {}
            try:
                with open(path) as f:
                    prior = json.load(f).get("world_sizes") or {}
                sizes = {k: v for k, v in prior.items()
                         if k.isdigit() and int(k) in steps}
            except (OSError, ValueError, AttributeError, TypeError):
                # a hand-edited/foreign manifest of the wrong SHAPE
                # (valid json, not our schema) degrades like corruption
                pass
            # getattr: harnesses stub Checkpointer past __init__
            mine = getattr(self, "_world_sizes", {})
            sizes.update({str(s): w for s, w in mine.items()
                          if s in steps})
            slice_counts: dict[str, int] = {}
            try:
                with open(path) as f:
                    prior = json.load(f).get("slice_counts") or {}
                slice_counts = {k: v for k, v in prior.items()
                                if k.isdigit() and int(k) in steps}
            except (OSError, ValueError, AttributeError, TypeError):
                pass
            slice_counts.update(
                {str(s): n for s, n in
                 getattr(self, "_slice_counts", {}).items() if s in steps})
            atomic_write_text(
                path,
                json.dumps({"latest_step": steps[-1] if steps else None,
                            "steps": steps,
                            "world_sizes": sizes,
                            "slice_counts": slice_counts},
                           sort_keys=True) + "\n")
        except OSError as e:
            log.warning("checkpoint: manifest write failed: %s", e)

    def wait(self) -> None:
        """Block until queued async saves are durably finalized."""
        self._mgr.wait_until_finished()
        self._write_manifest()

    def close(self) -> None:
        self._mgr.close()
        self._write_manifest()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore_variables(directory: str, step: int | None = None):
    """Inference-variable restore: the flax variables dict
    ({"params": ..., +"batch_stats" when present}) from a training
    checkpoint, for model.apply(..., train=False) in serving.

    Partial restore: opt_state (2x params for adamw) is skipped via
    ocp.PLACEHOLDER so serving pods sized for inference never pay the
    optimizer state's I/O or host memory."""
    import numpy as np
    import orbax.checkpoint as ocp

    directory = _normalize_dir(directory)
    with ocp.CheckpointManager(
        directory, item_handlers=ocp.PyTreeCheckpointHandler()
    ) as mgr:
        if step is None:
            step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        meta = mgr.item_metadata(int(step)).tree
        target = jax.tree.map(lambda _: ocp.PLACEHOLDER, meta)
        for key in ("step", "params", "batch_stats"):
            if key in meta:
                target[key] = jax.tree.map(
                    lambda _: ocp.type_handlers.RestoreArgs(restore_type=np.ndarray),
                    meta[key],
                )
        restored = mgr.restore(int(step), args=ocp.args.PyTreeRestore(item=target))
    variables = {"params": restored["params"]}
    if restored.get("batch_stats"):
        variables["batch_stats"] = restored["batch_stats"]
    return variables, int(step)


def restore_params(directory: str, step: int | None = None, shardings=None):
    """Params-only convenience wrapper over restore_variables; pass
    `shardings` (pytree of NamedSharding matching params) to place them
    on a mesh."""
    variables, step = restore_variables(directory, step)
    params = variables["params"]
    if shardings is not None:
        params = jax.tree.map(jax.device_put, params, shardings)
    return params, step
