"""Stdlib hygiene checks, pytest-free (the tests/test_lint.py gates as a
standalone pass for tools/lint_all.sh).

Three gates over .py files — parses, no debugger hooks
(``breakpoint()`` / ``set_trace()``), no merge-conflict markers — plus
the conflict-marker and parse gates over .yaml manifests (examples/).
Findings reuse the tpulint Finding type so the reporters and exit-code
logic apply unchanged.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable, Iterator

from kubeflow_tpu.analysis.core import Finding

HYGIENE_RULES = {
    "HYG001": "file does not parse",
    "HYG002": "debugger hook (breakpoint/set_trace)",
    "HYG003": "merge conflict marker",
    # HYG004 is emitted by core.scan_source/scan_paths full scans (it
    # audits tpulint suppressions against the findings that actually
    # fired), but is listed here so --list-rules and --select know it
    "HYG004": "stale tpulint suppression (rule gone or never fires)",
}

# split so the strings never match this file itself
_CONFLICT_MARKERS = ("<<" + "<<<<<", ">>" + ">>>>>", "==" + "=====")


def _conflict_findings(path: str, source: str) -> Iterator[Finding]:
    for i, line in enumerate(source.splitlines(), start=1):
        if any(line.startswith(m) for m in _CONFLICT_MARKERS):
            yield Finding("HYG003", path, i, 0,
                          "merge conflict marker shipped in source")


def check_py(path: str, source: str) -> list[Finding]:
    out = list(_conflict_findings(path, source))
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        out.append(Finding("HYG001", path, e.lineno or 1, e.offset or 0,
                           f"file does not parse: {e.msg}"))
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = getattr(fn, "id", getattr(fn, "attr", ""))
            if name in ("breakpoint", "set_trace"):
                out.append(Finding("HYG002", path, node.lineno,
                                   node.col_offset,
                                   f"debugger hook {name}() shipped"))
    return out


def check_yaml(path: str, source: str) -> list[Finding]:
    out = list(_conflict_findings(path, source))
    try:
        import yaml
    except ImportError:  # hygiene still useful without a yaml parser
        return out
    try:
        list(yaml.safe_load_all(source))
    except yaml.YAMLError as e:
        out.append(Finding("HYG001", path, 1, 0,
                           f"yaml does not parse: {e}"))
    return out


def run_hygiene(paths: Iterable[str]) -> list[Finding]:
    """Expand files/dirs into .py/.yaml targets and run the gates."""
    findings: list[Finding] = []
    targets: list[pathlib.Path] = []
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_dir():
            targets.extend(sorted(
                f for pat in ("*.py", "*.yaml", "*.yml") for f in p.rglob(pat)
                if "__pycache__" not in f.parts))
        else:
            targets.append(p)
    for f in targets:
        if f.suffix == ".py":
            findings.extend(check_py(str(f), f.read_text()))
        elif f.suffix in (".yaml", ".yml"):
            findings.extend(check_yaml(str(f), f.read_text()))
        # other suffixes (shell scripts, logs) are outside the gates —
        # skip rather than yaml-parse them into spurious findings
    return sorted(findings, key=lambda x: (x.path, x.line, x.rule))
