"""Small platform services: echo, https-redirect, static-config, kflogin,
and the shared crud_backend package (SURVEY.md §2.3)."""

import json

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.utils.httpd import HttpReq
from kubeflow_tpu.webapps import crud_backend as cb
from kubeflow_tpu.webapps import echo, https_redirect, kflogin, static_config

USER = "alice@example.com"


def mkreq(method, path, user=USER, body=None, query=None, headers=None):
    h = dict(headers or {})
    if user:
        h["kubeflow-userid"] = user
    b = json.dumps(body).encode() if body is not None else b""
    return HttpReq(method=method, path=path, params={}, query=query or {},
                   headers=h, body=b)


def J(resp):
    assert resp.status < 300, resp.body
    return json.loads(resp.body)


def test_echo_reflects_request():
    r = echo.router()
    out = J(r.dispatch(mkreq("POST", "/anything", body={"x": 1},
                             query={"q": ["v"]})))
    assert out["method"] == "POST"
    assert out["path"] == "/anything"
    assert out["query"] == {"q": ["v"]}
    assert json.loads(out["body"]) == {"x": 1}
    assert out["user"] == USER


def test_https_redirect_preserves_host_and_path():
    r = https_redirect.router()
    resp = r.dispatch(mkreq("GET", "/a", headers={"host": "kf.example.com:80"},
                            query={"x": ["1"]}))
    assert resp.status == 301
    assert resp.headers["Location"] == "https://kf.example.com/a?x=1"


def test_static_config_inline_and_file(tmp_path):
    s = static_config.StaticConfigServer(config={"platform": "tpu"})
    assert J(s.router().dispatch(mkreq("GET", "/config"))) == {"platform": "tpu"}

    p = tmp_path / "cfg.json"
    p.write_text('{"a": 1}')
    s2 = static_config.StaticConfigServer(path=str(p))
    assert J(s2.router().dispatch(mkreq("GET", "/config"))) == {"a": 1}
    with pytest.raises(ValueError):
        static_config.StaticConfigServer()


def test_kflogin_page_and_inprocess_login():
    from kubeflow_tpu.control.gatekeeper.auth import AuthServer, pwhash

    auth = AuthServer(username="admin", passhash=pwhash("pw", "s"), salt="s")
    app = kflogin.KfLogin(auth_server=auth)
    r = app.router()
    page = r.dispatch(mkreq("GET", "/kflogin", user=None))
    assert page.status == 200 and b"<form" in page.body

    ok = r.dispatch(mkreq("POST", "/apikflogin", user=None,
                          body={"username": "admin", "password": "pw"}))
    assert ok.status == 200 and "kubeflow-auth=" in ok.headers["Set-Cookie"]
    bad = r.dispatch(mkreq("POST", "/apikflogin", user=None,
                           body={"username": "admin", "password": "nope"}))
    assert bad.status == 401


class TestCrudBackend:
    @pytest.fixture()
    def cluster(self):
        from kubeflow_tpu.control.profile import types as PT

        c = FakeCluster()
        c.create(ob.new_object("v1", "Namespace", "team-a"))
        c.create(ob.new_object("kubeflow.org/v1", "Profile", "team-a",
                               spec={"owner": USER}))
        c.create(ob.new_object(
            "rbac.authorization.k8s.io/v1", "RoleBinding",
            "user-bob-clusterrole-view", namespace="team-a",
            annotations={PT.ANNO_USER: "bob", PT.ANNO_ROLE: "view"}))
        c.create(ob.new_object("v1", "PersistentVolumeClaim", "data",
                               namespace="team-a"))
        return c

    @pytest.fixture()
    def router(self, cluster):
        backend = cb.CrudBackend(cluster, cb.Authorizer(cluster))
        return backend.router()

    def test_owner_lists_and_creates(self, router):
        out = J(router.dispatch(mkreq("GET", "/api/namespaces/team-a/pvcs")))
        assert out["success"] and len(out["pvcs"]) == 1
        out = J(router.dispatch(mkreq(
            "POST", "/api/namespaces/team-a/pvcs",
            body={"metadata": {"name": "new"},
                  "spec": {"resources": {"requests": {"storage": "1Gi"}}}})))
        assert out["pvc"]["metadata"]["name"] == "new"

    def test_viewer_reads_but_cannot_write(self, router):
        out = J(router.dispatch(mkreq("GET", "/api/namespaces/team-a/pvcs",
                                      user="bob")))
        assert out["success"]
        resp = router.dispatch(mkreq(
            "DELETE", "/api/namespaces/team-a/pvcs/data", user="bob"))
        assert resp.status == 403

    def test_stranger_denied_and_anonymous_401(self, router):
        assert router.dispatch(
            mkreq("GET", "/api/namespaces/team-a/pvcs", user="eve")).status == 403
        assert router.dispatch(
            mkreq("GET", "/api/namespaces/team-a/pvcs", user=None)).status == 401

    def test_secret_names_only(self, cluster, router):
        secret = ob.new_object("v1", "Secret", "tok", namespace="team-a")
        secret["data"] = {"k": "dmFsdWU="}
        cluster.create(secret)
        out = J(router.dispatch(mkreq("GET", "/api/namespaces/team-a/secrets")))
        assert out["secrets"] == ["tok"]
        assert "dmFsdWU" not in json.dumps(out)

    def test_delete_pvc(self, router):
        out = J(router.dispatch(mkreq(
            "DELETE", "/api/namespaces/team-a/pvcs/data")))
        assert out["success"]
        resp = router.dispatch(mkreq(
            "DELETE", "/api/namespaces/team-a/pvcs/data"))
        assert resp.status == 404


def test_echo_and_redirect_multi_segment_paths():
    r = echo.router()
    out = J(r.dispatch(mkreq("GET", "/notebook/team-a/my-nb/")))
    assert out["path"] == "/notebook/team-a/my-nb/"
    # health endpoints are not swallowed by the catch-all
    assert J(r.dispatch(mkreq("GET", "/healthz"))) == {"status": "ok"}

    rr = https_redirect.router()
    resp = rr.dispatch(mkreq("GET", "/notebook/team-a/my-nb/",
                             headers={"host": "kf.example.com"}))
    assert resp.status == 301
    assert resp.headers["Location"].endswith("/notebook/team-a/my-nb/")


def test_redirect_reencodes_query_values():
    r = https_redirect.router()
    resp = r.dispatch(mkreq("GET", "/a", headers={"host": "kf.corp"},
                            query={"next": ["/x?y=1&z=2"]}))
    assert resp.status == 301
    assert resp.headers["Location"] == \
        "https://kf.corp/a?next=%2Fx%3Fy%3D1%26z%3D2"


def test_crud_cluster_scoped_routes_require_identity(cluster=None):
    c = FakeCluster()
    backend = cb.CrudBackend(c, cb.Authorizer(c))
    r = backend.router()
    assert r.dispatch(mkreq("GET", "/api/namespaces", user=None)).status == 401
    assert r.dispatch(mkreq("GET", "/api/storageclasses", user=None)).status == 401
    assert J(r.dispatch(mkreq("GET", "/api/namespaces")))["success"]
