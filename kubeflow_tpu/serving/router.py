"""Token-aware serving router — the JAXService front door.

A single replica server (``serving/server.py``) saturates at one
decoder's throughput (BENCH_r05: 1.07 req/s); the serving plane runs N
replicas behind this router. Replica choice is least-outstanding-TOKENS,
not least-connections: decode cost scales with tokens (prompt prefill +
requested continuation), so one 2k-token request weighs as much as
thirty short ones — balancing on request counts would pile long prompts
onto one replica while its neighbors idle.

Design mirrors the gang scheduler's split (``scheduler/queue.py``): a
DETERMINISTIC synchronous core (``TokenRouter`` — every transition
happens in an explicit call under one lock, clock injectable) with a
thin threaded/HTTP shell (``RouterFrontend``) for production. The core
is what the JAXService benchmark (``tools/serve_bench.py``) replays
decision-for-decision per seed, and what the drain/kill drills prove
zero-drop on:

- bounded admission queue: ``submit`` beyond ``max_queue`` raises
  ``RouterBusy`` (the HTTP shell's 429) — backpressure instead of an
  unbounded latency cliff;
- membership is CONTROLLER-FED through the JAXService endpoints
  annotation (``ANNOTATION_ENDPOINTS``, the ONE spelling — the
  jaxservice controller re-exports it): only replicas the controller
  reports Ready receive work, a cordoned replica finishes its in-flight
  tokens but admits nothing new (connection draining), and a replica
  REMOVED from membership (killed) has its in-flight requests shed back
  to the queue FRONT and re-dispatched to survivors — zero drops;
- every dispatch opens a ``router.dispatch`` span parented on the
  request's W3C traceparent, so a request timeline connects through the
  router hop to the replica's serving spans (docs/observability.md).

Metrics go to BOTH sinks (the PR 4 convention): the MetricsRegistry
(``router_queue_depth``, ``router_tokens_inflight{replica}``,
``router_request_seconds`` native histogram, ``router_tokens_total``)
that the JAXService autoscaler reads its signals from, and
prometheus_client for the scrape surface.

jax-free by design: the control plane imports this module (the
endpoints wire contract and ``RegistrySignals``) without pulling a jax
runtime in.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.runtime.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("kubeflow_tpu.serving.router")

# The controller -> router membership wire contract: a JSON list of
# {"name", "addr", "state"} stamped on the JAXService object. "active"
# members take new work; "cordoned" members only drain. The jaxservice
# controller writes it, the router consumes it — one spelling, here
# (control/jaxservice/types.py re-exports it, the dist.py pattern).
ANNOTATION_ENDPOINTS = "jaxservice.kubeflow.org/endpoints"
STATE_ACTIVE = "active"
STATE_CORDONED = "cordoned"

# Request-latency buckets: sub-second cache hits up to multi-minute
# long-context decodes under queueing.
REQUEST_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0, 300.0)

def _prom_metric(name, kind, doc, **kw):
    from kubeflow_tpu.runtime.metrics import prom_metric

    return prom_metric(name, kind, doc, **kw)


def prom_queue_depth():
    import prometheus_client as prom

    return _prom_metric("router_queue_depth", prom.Gauge,
                        "requests waiting in the router admission queue",
                        labelnames=("service",))


def prom_tokens_inflight():
    import prometheus_client as prom

    return _prom_metric("router_tokens_inflight", prom.Gauge,
                        "outstanding token estimate per replica",
                        labelnames=("service", "replica"))


def prom_request_seconds():
    import prometheus_client as prom

    return _prom_metric("router_request_seconds", prom.Histogram,
                        "submit -> completion latency through the router",
                        labelnames=("service",), buckets=REQUEST_BUCKETS)


def prom_requests_total():
    import prometheus_client as prom

    return _prom_metric("router_requests_total", prom.Counter,
                        "requests by outcome (completed/rejected/shed)",
                        labelnames=("service", "outcome"))


def prom_tokens_total():
    import prometheus_client as prom

    return _prom_metric("router_tokens_total", prom.Counter,
                        "tokens completed through the router "
                        "(rate = the autoscaler's tokens/sec signal)",
                        labelnames=("service",))


class RouterBusy(Exception):
    """Admission queue full — the HTTP shell's 429 Too Many Requests."""


@dataclass
class Member:
    """One routable replica. ``transport`` is whatever the shell uses
    to reach it (an HTTP base URL, an in-process callable, a bench
    stub) — the core never calls it, it only hands it back on
    dispatch."""

    name: str
    transport: Any = None
    state: str = STATE_ACTIVE


@dataclass
class Ticket:
    """One request's journey through the router. ``member`` is set at
    dispatch (None while queued); ``done`` fires on dispatch AND on
    completion so a blocking shell can wait on either transition.
    ``tried`` holds replicas whose transport already FAILED this
    ticket — re-dispatch prefers anyone else (the name-tie-break would
    otherwise send every retry straight back to the dead replica)."""

    tokens: int
    item: Any = None
    context: "obs_trace.SpanContext | None" = None
    member: Member | None = None
    done: threading.Event = field(default_factory=threading.Event,
                                  repr=False)
    tried: set = field(default_factory=set, repr=False)
    _t0: float = 0.0
    _span: "obs_trace.Span | None" = field(default=None, repr=False)
    _queued_at: float = 0.0


def estimate_tokens(instances: list, max_new_tokens: int) -> int:
    """The in-flight cost estimate for a predict body: prompt tokens
    (prefill) plus the full requested continuation per row. An estimate
    on purpose — the router needs relative weight, not billing."""
    total = 0
    for inst in instances or [None]:
        row = inst.get("tokens") if isinstance(inst, dict) else inst
        total += (len(row) if hasattr(row, "__len__") else 1)
        total += max_new_tokens
    return max(total, 1)


class TokenRouter:
    """Deterministic least-outstanding-tokens dispatcher.

    All state lives under one lock and is mutated only in locked
    methods (the LOCK201-provable fresh-container idiom); transports
    are never invoked here, so no I/O happens under the lock.
    """

    def __init__(self, service: str = "default", namespace: str = "default",
                 max_queue: int = 256,
                 replica_token_budget: int | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: MetricsRegistry | None = None,
                 tracer=None, prom_sink: bool = True):
        self.service = service
        self.namespace = namespace
        self.max_queue = max_queue
        # max outstanding tokens a replica accepts before the router
        # queues instead (None = always eligible; the least-loaded
        # replica still wins). Roughly slots * (prompt + continuation).
        self.replica_token_budget = replica_token_budget
        self.clock = clock
        self.registry = registry if registry is not None else REGISTRY
        self.tracer = tracer if tracer is not None else obs_trace.TRACER
        # prometheus is process-global; the deterministic bench runs
        # many routers per process and opts out of the shared sink
        self._prom = prom_sink
        self._lock = threading.Lock()
        self._members: dict[str, Member] = {}
        self._inflight: dict[str, dict[int, Ticket]] = {}  # name -> tickets
        self._tokens: dict[str, int] = {}                  # name -> estimate
        self._queue: list[Ticket] = []
        self._closed = False

    # -- membership (controller-fed) ----------------------------------------

    def sync_endpoints(self, endpoints: list[dict],
                       transport_factory: Callable[[dict], Any] | None = None,
                       ) -> list[Ticket]:
        """Apply a controller-published endpoint list (the parsed
        ``ANNOTATION_ENDPOINTS`` value). Returns the tickets re-DISPATCHED
        after shedding removed members (see ``set_members``)."""
        members = []
        for ep in endpoints:
            name = ep.get("name")
            if not name:
                continue
            members.append(Member(
                name=name,
                transport=(transport_factory(ep) if transport_factory
                           else ep.get("addr")),
                state=(STATE_CORDONED if ep.get("state") == STATE_CORDONED
                       else STATE_ACTIVE)))
        return self.set_members(members)

    def sync_from_object(self, service_obj: dict,
                         transport_factory=None) -> list[Ticket]:
        """Membership straight from a JAXService object (a watch-driven
        shell calls this per event)."""
        return self.sync_endpoints(
            parse_endpoints(service_obj), transport_factory)

    def set_members(self, members: list[Member]) -> list[Ticket]:
        """Replace membership. A member that disappears sheds its
        in-flight tickets back to the queue FRONT (oldest first) and a
        drain pass re-dispatches to survivors — the zero-drop half of
        the replica-kill drill. Returns the newly dispatched tickets so
        a synchronous driver can start their work on the survivors."""
        with self._lock:
            now = self.clock()
            new = {m.name: m for m in members}
            shed: list[Ticket] = []
            for name in list(self._members):
                if name not in new:
                    shed.extend(self._shed_member_locked(name, now))
            for name, m in new.items():
                cur = self._members.get(name)
                if cur is None:
                    self._members[name] = m
                    self._inflight.setdefault(name, {})
                    self._tokens.setdefault(name, 0)
                    self._publish_inflight_locked(name)
                else:
                    cur.state = m.state
                    cur.transport = m.transport
            # requeue shed tickets at the FRONT, original order. done is
            # CLEARED: a blocking shell waiting on this ticket must park
            # until the re-dispatch below (or a later drain) fires it
            # again — a stale set() would busy-spin its retry loop
            for t in reversed(shed):
                t.member = None
                t.done.clear()
                self._queue.insert(0, t)
            dispatched = self._drain_locked(now)
            self._publish_queue_locked()
        for t in dispatched:
            t.done.set()
        return dispatched

    def cordon(self, name: str) -> None:
        """Stop NEW dispatch to a replica; in-flight work finishes
        (connection draining). The controller cordons before delete."""
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.state = STATE_CORDONED

    def uncordon(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.state = STATE_ACTIVE
        self.kick()

    def _shed_member_locked(self, name: str, now: float) -> list[Ticket]:
        """Remove a member; return its in-flight tickets oldest-first."""
        self._members.pop(name, None)
        tickets = sorted(self._inflight.pop(name, {}).values(),
                         key=lambda t: t._t0)
        self._tokens.pop(name, None)
        for t in tickets:
            if t._span is not None:
                # the dispatch to the dead replica exports as ERROR; the
                # re-dispatch below opens a fresh span in the same trace
                t._span.status = "ERROR"
                t._span.error = f"replica {name} lost; shed to survivors"
                self.tracer.finish(t._span)
                t._span = None
            self._count_locked("shed")
        self.registry.gauge(
            "router_tokens_inflight", 0,
            help_="outstanding token estimate per replica",
            namespace=self.namespace, service=self.service, replica=name)
        if self._prom:
            prom_tokens_inflight().labels(self.service, name).set(0)
        return tickets

    # -- admission -----------------------------------------------------------

    def submit(self, tokens: int, item: Any = None,
               context: "obs_trace.SpanContext | None" = None) -> Ticket:
        """Admit one request of ``tokens`` estimated cost. Dispatches
        immediately to the least-loaded eligible replica, else queues;
        raises ``RouterBusy`` (429) when the bounded queue is full."""
        t = Ticket(tokens=int(tokens), item=item, context=context)
        with self._lock:
            if self._closed:
                raise RouterBusy("router is shut down")
            now = self.clock()
            t._t0 = t._queued_at = now
            member = self._pick_locked(t.tokens)
            if member is not None:
                self._dispatch_locked(t, member, now)
            elif len(self._queue) >= self.max_queue:
                self._count_locked("rejected")
                raise RouterBusy(
                    f"admission queue full ({self.max_queue})")
            else:
                self._queue.append(t)
            self._publish_queue_locked()
        if t.member is not None:
            t.done.set()
        return t

    def complete(self, ticket: Ticket, tokens_done: int | None = None,
                 ) -> list[Ticket]:
        """Mark a dispatched ticket finished; drain the queue into the
        freed capacity. Returns newly dispatched tickets (their
        ``member`` set) for synchronous drivers.

        Shed-race safe, symmetric to ``fail``: if a concurrent
        membership sync shed this ticket back into the queue while its
        transport call was succeeding, the queued copy is removed here
        — the handler thread has already returned the response, so a
        re-dispatch would permanently inflate the survivor's in-flight
        accounting (nobody is left to complete it) and wedge its drain
        gate."""
        with self._lock:
            now = self.clock()
            if ticket.member is None:
                self._queue = [t for t in self._queue if t is not ticket]
            self._finish_locked(ticket, now, tokens_done)
            dispatched = self._drain_locked(now)
            self._publish_queue_locked()
        for t in dispatched:
            t.done.set()
        return dispatched

    def fail(self, ticket: Ticket, requeue: bool = True) -> list[Ticket]:
        """A transport-level failure for one dispatched ticket: take it
        off its replica and (by default) requeue it at the FRONT for a
        retry on whoever is least loaded now. ``requeue=False`` drops
        it (the caller is surfacing the error to its client).

        Safe against the shed race: if a concurrent membership sync
        already shed this ticket back into the queue (``member`` is
        None), a requeue is a no-op — inserting it AGAIN would have it
        dispatched twice and permanently inflate a replica's in-flight
        accounting — and a drop removes it from the queue so nothing
        ghost-dispatches a request whose handler thread has given up."""
        with self._lock:
            now = self.clock()
            member = ticket.member
            if member is not None:
                # remember the failed transport: the retry must prefer
                # any OTHER replica (least-loaded + name-tie would
                # otherwise re-pick the dead one forever)
                ticket.tried.add(member.name)
                bucket = self._inflight.get(member.name)
                if bucket is not None and bucket.pop(id(ticket), None) \
                        is not None:
                    self._tokens[member.name] = max(
                        0, self._tokens.get(member.name, 0) - ticket.tokens)
                    self._publish_inflight_locked(member.name)
            if ticket._span is not None:
                ticket._span.status = "ERROR"
                ticket._span.error = "transport failure"
                self.tracer.finish(ticket._span)
                ticket._span = None
            ticket.member = None
            queued = any(t is ticket for t in self._queue)
            if requeue:
                ticket.done.clear()
                if not queued:
                    self._queue.insert(0, ticket)
                    self._count_locked("shed")
            else:
                if queued:
                    self._queue = [t for t in self._queue
                                   if t is not ticket]
                self._count_locked("failed")
            dispatched = self._drain_locked(now)
            self._publish_queue_locked()
        for t in dispatched:
            t.done.set()
        return dispatched

    def kick(self) -> list[Ticket]:
        """Re-try queued dispatch (capacity may have appeared through a
        membership edit rather than a completion)."""
        with self._lock:
            dispatched = self._drain_locked(self.clock())
            self._publish_queue_locked()
        for t in dispatched:
            t.done.set()
        return dispatched

    def close(self) -> list[Ticket]:
        """Reject everything still queued (shell shutdown)."""
        with self._lock:
            self._closed = True
            orphans, self._queue = self._queue, []
            self._publish_queue_locked()
        for t in orphans:
            t.done.set()
        return orphans

    # -- introspection (the controller's drain checks ride on these) ---------

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def inflight_tokens(self, name: str | None = None) -> int:
        with self._lock:
            if name is not None:
                return self._tokens.get(name, 0)
            return sum(self._tokens.values())

    def drained(self, name: str) -> bool:
        """True when a cordoned replica holds no in-flight work — the
        controller's delete gate."""
        with self._lock:
            return not self._inflight.get(name)

    def members(self) -> dict[str, str]:
        with self._lock:
            return {n: m.state for n, m in self._members.items()}

    # -- locked internals ----------------------------------------------------

    def _pick_locked(self, tokens: int,
                     exclude: set | frozenset = frozenset(),
                     ) -> Member | None:
        """Least-outstanding-tokens over ACTIVE members; name breaks
        ties so replays are order-independent. Budget-full replicas are
        skipped (the request queues for the next completion). Members
        in ``exclude`` (a retrying ticket's failed transports) are
        avoided — unless they are ALL that's left, in which case a
        retry beats starvation."""
        best = None
        best_key = None
        for name, m in self._members.items():
            if m.state != STATE_ACTIVE:
                continue
            load = self._tokens.get(name, 0)
            if self.replica_token_budget is not None and load > 0 \
                    and load + tokens > self.replica_token_budget:
                continue
            key = (name in exclude, load, name)
            if best_key is None or key < best_key:
                best, best_key = m, key
        return best

    def _dispatch_locked(self, t: Ticket, member: Member,
                         now: float) -> None:
        t.member = member
        self._inflight.setdefault(member.name, {})[id(t)] = t
        self._tokens[member.name] = \
            self._tokens.get(member.name, 0) + t.tokens
        # detached: finish() runs in a LATER call (complete/fail/shed),
        # so this span must never install itself as the ambient parent —
        # an out-of-order reset would pollute the caller's contextvar
        t._span = self.tracer.begin(
            "router.dispatch", parent=t.context, detached=True,
            service=self.service, namespace=self.namespace,
            replica=member.name, tokens=t.tokens,
            queue_wait_s=round(max(now - t._queued_at, 0.0), 6))
        self._publish_inflight_locked(member.name)

    def _finish_locked(self, t: Ticket, now: float,
                       tokens_done: int | None) -> None:
        member = t.member
        if member is not None:
            bucket = self._inflight.get(member.name)
            if bucket is not None:
                bucket.pop(id(t), None)
            self._tokens[member.name] = max(
                0, self._tokens.get(member.name, 0) - t.tokens)
            self._publish_inflight_locked(member.name)
        if t._span is not None:
            self.tracer.finish(t._span)
            t._span = None
        latency = max(now - t._t0, 0.0)
        done = t.tokens if tokens_done is None else int(tokens_done)
        self.registry.histogram(
            "router_request_seconds", latency,
            help_="submit -> completion latency through the router",
            buckets=REQUEST_BUCKETS,
            namespace=self.namespace, service=self.service)
        self.registry.counter_inc(
            "router_tokens_total",
            help_="tokens completed through the router (rate = the "
                  "autoscaler's tokens/sec signal)",
            by=float(done), namespace=self.namespace, service=self.service)
        self._count_locked("completed")
        if self._prom:
            prom_request_seconds().labels(self.service).observe(latency)
            prom_tokens_total().labels(self.service).inc(done)

    def _drain_locked(self, now: float) -> list[Ticket]:
        """FIFO-drain the queue into whatever capacity exists."""
        dispatched: list[Ticket] = []
        remaining: list[Ticket] = []
        for t in self._queue:
            member = self._pick_locked(t.tokens, exclude=t.tried)
            if member is None:
                remaining.append(t)
                continue
            self._dispatch_locked(t, member, now)
            dispatched.append(t)
        self._queue = remaining
        return dispatched

    def _publish_queue_locked(self) -> None:
        self.registry.gauge(
            "router_queue_depth", len(self._queue),
            help_="requests waiting in the router admission queue",
            namespace=self.namespace, service=self.service)
        if self._prom:
            prom_queue_depth().labels(self.service).set(len(self._queue))

    def _publish_inflight_locked(self, name: str) -> None:
        self.registry.gauge(
            "router_tokens_inflight", self._tokens.get(name, 0),
            help_="outstanding token estimate per replica",
            namespace=self.namespace, service=self.service, replica=name)
        if self._prom:
            prom_tokens_inflight().labels(self.service, name).set(
                self._tokens.get(name, 0))

    def _count_locked(self, outcome: str) -> None:
        self.registry.counter_inc(
            "router_requests_total",
            help_="requests by outcome (completed/rejected/shed/failed)",
            namespace=self.namespace, service=self.service, outcome=outcome)
        if self._prom:
            prom_requests_total().labels(self.service, outcome).inc()


# -- endpoints annotation helpers -------------------------------------------


def render_endpoints(endpoints: list[dict]) -> str:
    """Canonical JSON for the annotation (sorted, compact) so an
    unchanged endpoint set patches to an identical string — the
    controller's no-op write guard compares it byte-for-byte."""
    return json.dumps(sorted(endpoints, key=lambda e: e.get("name", "")),
                      separators=(",", ":"), sort_keys=True)


def parse_endpoints(service_obj: dict) -> list[dict]:
    """The endpoint list a JAXService object currently publishes."""
    raw = ((service_obj.get("metadata") or {}).get("annotations") or {}) \
        .get(ANNOTATION_ENDPOINTS)
    if not raw:
        return []
    try:
        eps = json.loads(raw)
    except ValueError:
        log.warning("malformed %s annotation ignored", ANNOTATION_ENDPOINTS)
        return []
    return [e for e in eps if isinstance(e, dict) and e.get("name")]


# -- autoscaler signal source -----------------------------------------------


class RegistrySignals:
    """The JAXService autoscaler's signal reader: parses the router- and
    replica-exported series back out of a MetricsRegistry's text
    exposition (the PR 4 histograms ARE the wire — in production the
    same text arrives by scraping the router's /metrics; hermetically
    the registry is shared in-process). Series names are the catalog in
    docs/observability.md."""

    def __init__(self, registry):
        # a MetricsRegistry (shared-process fast path), or a zero-arg
        # callable returning an exposition body — the scraped-/metrics
        # source for a controller running out-of-process from the router
        self.registry = registry

    def _series(self, name: str) -> list[tuple[dict, float]]:
        # in-process fast path: structured samples straight off the
        # registry (O(metric) instead of rendering + parsing the whole
        # exposition per signal read). Scraped bodies go through the
        # ONE exposition parser (obs/expofmt.py) shared with the fleet
        # scrape plane — no second spelling.
        reader = getattr(self.registry, "series", None)
        if reader is not None:
            return reader(name)
        from kubeflow_tpu.obs import expofmt

        text = self.registry() if callable(self.registry) \
            else self.registry.render()
        return expofmt.samples(text, name)

    def _sum(self, name: str, **match) -> float:
        total = 0.0
        for labels, value in self._series(name):
            if all(labels.get(k) == v for k, v in match.items()):
                total += value
        return total

    def queue_depth(self, namespace: str, service: str) -> float:
        return self._sum("router_queue_depth",
                         namespace=namespace, service=service)

    def tokens_total(self, namespace: str, service: str) -> float:
        return self._sum("router_tokens_total",
                         namespace=namespace, service=service)

    def inflight_tokens(self, namespace: str, service: str,
                        replica: str | None = None) -> float:
        match = {"namespace": namespace, "service": service}
        if replica is not None:
            match["replica"] = replica
        return self._sum("router_tokens_inflight", **match)

    def replica_drained(self, namespace: str, service: str,
                        replica: str) -> bool:
        return self.inflight_tokens(namespace, service, replica) <= 0


# -- threaded/HTTP shell ----------------------------------------------------


class HttpTransport:
    """POST a predict body to a replica server (urllib; stdlib-only,
    the RestClient discipline)."""

    def __init__(self, base_url: str, timeout: float = 300.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def predict(self, model: str, body: bytes,
                headers: dict | None = None) -> bytes:
        import urllib.request

        req = urllib.request.Request(
            f"{self.base_url}/v1/models/{model}:predict", data=body,
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()


class RouterFrontend:
    """The blocking HTTP face over the deterministic core: one handler
    thread carries its request end-to-end (submit -> wait for dispatch
    -> call the replica transport -> complete), so the router itself
    never blocks under its lock."""

    def __init__(self, router: TokenRouter, max_new_tokens: int = 32,
                 dispatch_timeout: float = 120.0):
        self.router = router
        self.max_new_tokens = max_new_tokens
        self.dispatch_timeout = dispatch_timeout

    def predict(self, req):
        from kubeflow_tpu.utils.httpd import ApiHttpError

        model = req.params["model"]
        body = req.json() or {}
        instances = body.get("instances")
        if instances is None:
            raise ApiHttpError(400, 'request body must contain "instances"')
        ctx = obs_trace.parse_traceparent(req.header("traceparent"))
        tokens = estimate_tokens(instances, self.max_new_tokens)
        try:
            ticket = self.router.submit(tokens, item=model, context=ctx)
        except RouterBusy as e:
            raise ApiHttpError(429, str(e))
        last_err: Exception | None = None
        failures = 0
        while failures < 3:
            if ticket.member is None:
                if not ticket.done.wait(self.dispatch_timeout):
                    self.router.fail(ticket, requeue=False)
                    raise ApiHttpError(503, "no replica capacity")
            member = ticket.member
            if member is None:  # shed mid-wait; loop waits again
                continue
            try:
                raw = member.transport.predict(
                    model, req.body,
                    headers={"traceparent": req.header("traceparent")}
                    if req.header("traceparent") else None)
            except Exception as e:  # replica died mid-request: retry
                last_err = e
                failures += 1
                self.router.fail(ticket, requeue=True)
                continue
            self.router.complete(ticket)
            return json.loads(raw)
        self.router.fail(ticket, requeue=False)
        raise ApiHttpError(502, f"replica transport failed: {last_err}")

    def build(self):
        from kubeflow_tpu.utils import httpd

        r = httpd.Router("jaxservice-router")
        r.route("POST", "/v1/models/{model}:predict", self.predict)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 8600):
        from kubeflow_tpu.utils import httpd

        return httpd.HttpService(self.build(), host, port)


def main() -> None:  # pragma: no cover - container entry
    import argparse
    import os

    p = argparse.ArgumentParser("kubeflow-tpu-router")
    p.add_argument("--port", type=int, default=8600)
    p.add_argument("--service", default=os.environ.get("JAXSERVICE_NAME",
                                                       "default"))
    p.add_argument("--namespace", default=os.environ.get("POD_NAMESPACE",
                                                         "default"))
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--endpoints", default="",
                   help="static bootstrap: name=url[,name=url...] "
                        "(the controller watch takes over in-cluster)")
    p.add_argument("--apiserver", default="",
                   help="watch the JAXService endpoints annotation")
    args = p.parse_args()
    router = TokenRouter(service=args.service, namespace=args.namespace,
                         max_queue=args.max_queue)
    if args.endpoints:
        eps = [{"name": n, "addr": u, "state": STATE_ACTIVE}
               for n, _, u in (e.partition("=")
                               for e in args.endpoints.split(","))]
        router.sync_endpoints(
            eps, transport_factory=lambda ep: HttpTransport(ep["addr"]))
    if args.apiserver:
        from kubeflow_tpu.control.jaxservice import watch_endpoints

        threading.Thread(
            target=watch_endpoints,
            args=(args.apiserver, args.namespace, args.service, router),
            daemon=True, name="router-endpoints-watch").start()
    frontend = RouterFrontend(router, max_new_tokens=args.max_new_tokens)
    svc = frontend.serve(port=args.port)
    log.info("jaxservice router %s/%s on :%d", args.namespace,
             args.service, svc.port)
    svc.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
