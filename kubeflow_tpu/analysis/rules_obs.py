"""tpulint observability rule (OBS301): wall-clock duration math.

``time.time()`` is wall clock: NTP slew/step can make consecutive
readings go backwards or jump, so a latency computed as
``time.time() - t0`` can be negative or wildly wrong — and those are
exactly the numbers the span pipeline and the Prometheus histograms
publish. Duration math must use ``time.perf_counter()`` (monotonic,
high resolution); ``obs/trace.py`` converts perf_counter readings to
epoch timestamps through a single module-level wall anchor.

What fires: a subtraction whose operand is a ``time.time()`` call, or a
name bound to one in the same scope. What stays silent (FP pins in
tests/test_tpulint.py): deadline arithmetic (``time.time() + ttl``),
expiry comparisons (``exp < time.time()``), plain timestamping, and all
``perf_counter``/``monotonic`` math.
"""

from __future__ import annotations

import ast
from typing import Iterator

from kubeflow_tpu.analysis.core import (
    Finding, Module, Rule, dotted, register,
)


def _time_time_aliases(module: Module) -> set[str]:
    """Dotted spellings that resolve to time.time in this module."""
    aliases = {"time.time"}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    aliases.add(a.asname or "time")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time" and a.asname:
                    aliases.add(f"{a.asname}.time")
    return aliases


@register
class WallClockDuration(Rule):
    id = "OBS301"
    name = "wall-clock-duration"
    short = "time.time() used to measure a duration; use time.perf_counter()"

    def check(self, module: Module) -> Iterator[Finding]:
        aliases = _time_time_aliases(module)

        def is_time_time(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and dotted(node.func) in aliases)

        # names bound to a time.time() reading, keyed by enclosing
        # function (None = module level) so an unrelated local called
        # `t0` in another function never taints this one
        tainted: dict[ast.AST | None, set[str]] = {}
        for node in ast.walk(module.tree):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign) and is_time_time(node.value):
                targets = node.targets
            elif (isinstance(node, ast.AnnAssign) and node.value is not None
                    and is_time_time(node.value)):
                targets = [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    scope = module.enclosing_function(node)
                    tainted.setdefault(scope, set()).add(tgt.id)

        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            scope = module.enclosing_function(node)
            names = tainted.get(scope, set()) | tainted.get(None, set())

            def wallish(operand: ast.AST) -> bool:
                return is_time_time(operand) or (
                    isinstance(operand, ast.Name) and operand.id in names)

            if wallish(node.left) or wallish(node.right):
                yield self.finding(
                    module, node,
                    "duration computed from time.time(); wall clock can "
                    "step/slew under NTP — use time.perf_counter()")
