"""FleetPlane — the assembled observability plane, one handle.

Bundles the three ISSUE-10 layers (``tsdb`` scrape plane, ``rules``
engine, ``goodput`` accounting) behind the object the dashboard routes
(``GET /api/alerts`` / ``/api/query`` / ``/api/goodput``) and
``run_controller``-style mains wire up. Hermetic harnesses build their
own with fake clocks; a process that just wants "the plane" uses the
module-level ``default_plane()`` singleton (the REGISTRY/COLLECTOR/
TRACER convention from runtime/metrics.py and obs/trace.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from kubeflow_tpu.obs import goodput as gp
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.obs.rules import RuleEngine, default_rule_pack
from kubeflow_tpu.obs.tsdb import ScrapeLoop, Target, TimeSeriesStore


class FleetPlane:
    """store + scraper + rule engine + goodput reads, one lifecycle.

    ``tick()`` is the deterministic unit (one scrape cycle + one rule
    pass at the shared clock) — drills, tests and the bench drive it on
    virtual time; ``start()``/``stop()`` run it on wall time."""

    def __init__(self, registry=None, recorder=None,
                 targets: list[Target] = (),
                 discover: Callable[[], list[Target]] | None = None,
                 rules: list | None = None,
                 interval_s: float = 15.0,
                 clock: Callable[[], float] = time.time,
                 collector: "obs_trace.TraceCollector | None" = None,
                 max_points: int = 512, max_series: int = 50000,
                 lookback_s: float | None = None):
        from kubeflow_tpu.runtime.metrics import REGISTRY

        self.registry = registry if registry is not None else REGISTRY
        self.clock = clock
        self.collector = collector if collector is not None \
            else obs_trace.COLLECTOR
        self.store = TimeSeriesStore(max_points=max_points,
                                     max_series=max_series)
        self.scraper = ScrapeLoop(
            self.store, targets=targets, discover=discover,
            interval_s=interval_s, clock=clock, registry=self.registry)
        # instant-selector lookback tracks the scrape interval: a
        # series is "current" while it misses fewer than ~4 scrapes
        self.engine = RuleEngine(
            self.store,
            rules=default_rule_pack() if rules is None else rules,
            recorder=recorder, registry=self.registry, clock=clock,
            lookback_s=(lookback_s if lookback_s is not None
                        else max(interval_s * 4, 60.0)))
        self.slos = [gp.ServingSLO()]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- deterministic core --------------------------------------------------

    def tick(self, at: float | None = None) -> dict:
        """One scrape + rule pass; returns {'scrape': ..., 'transitions':
        [...]} — the unit the bench fingerprints."""
        scrape = self.scraper.scrape_once()
        transitions = self.engine.evaluate_once(at=at)
        return {"scrape": scrape, "transitions": transitions}

    # -- dashboard reads -----------------------------------------------------

    def alerts(self) -> dict:
        return {"alerts": self.engine.active_alerts()}

    def query(self, text: str, at: float | None = None) -> dict:
        result = self.engine.query(text, at=at)
        return {"query": text,
                "result": [{"labels": labels, "value": value}
                           for labels, value in result]}

    def goodput(self, chips: int = 1, window_s: float | None = None,
                at: float | None = None) -> dict:
        """Training goodput from the span stream + serving SLO status
        from the TSDB — the /api/goodput body."""
        spans = self.collector.spans()
        report = gp.job_report(spans, chips=chips)
        now = self.clock() if at is None else at
        slos = [slo.from_store(self.store, now,
                               window_s=window_s or 300.0)
                for slo in self.slos]
        return {"training": report.check().to_dict(), "serving": slos}

    # -- thread shell --------------------------------------------------------

    def start(self) -> "FleetPlane":  # pragma: no cover - thread shell
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="obs-plane", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:  # pragma: no cover - thread shell
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.scraper.interval_s + 5)
            self._thread = None

    def _run(self) -> None:  # pragma: no cover - thread shell
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # the plane must outlive a bad pass
                import logging

                logging.getLogger("kubeflow_tpu.obs.plane").exception(
                    "plane tick failed")
            self._stop.wait(self.scraper.interval_s)


_default: FleetPlane | None = None
_default_lock = threading.Lock()


def default_plane() -> FleetPlane:
    """The process-wide plane (lazily built, self-scraping the global
    MetricsRegistry). The dashboard serves this one unless handed
    another. STARTED on first build — a plane that is never ticked
    would serve a permanently empty store and a silent alert surface,
    which is worse than no plane at all."""
    global _default
    with _default_lock:
        if _default is None:
            from kubeflow_tpu.obs.tsdb import RegistryTarget
            from kubeflow_tpu.runtime.metrics import REGISTRY

            _default = FleetPlane(
                targets=[RegistryTarget("self", REGISTRY)]).start()
        return _default
