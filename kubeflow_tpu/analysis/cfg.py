"""Per-function control-flow graphs with exception edges, plus a
worklist dataflow solver — the path-sensitive layer under the RES7xx
resource-lifecycle rules (rules_resource.py).

tpulint v1–v3 reason about statements and call graphs; they cannot see
*paths*. The bugs that motivated this layer (the router shed-race, the
KV over-admission ``fail_all``) all lived on exceptional paths: a
resource acquired, then a ``raise`` between the acquire and the
release. This module makes those paths first-class:

- ``build_cfg(fn)``: one graph per function. Every statement is a
  node; compound statements contribute a *header* node (the test /
  iterator / context managers) and their bodies flow through it.
  Synthetic ``entry``/``exit`` nodes bracket the graph, loops get back
  edges, and — the point of the exercise — **every statement that can
  throw gets an exception edge** to the enclosing handler or, when
  nothing catches, to the function exit.
- ``try/finally`` is modelled by *inlining*: each distinct
  continuation through a ``finally`` (normal fall-through, uncaught
  exception, early ``return``, ``break``/``continue``) gets its own
  copy of the finally body, so a release inside ``finally`` provably
  covers the exception path without smearing facts between
  continuations. Exception routing within one ``try`` is funnelled
  through a per-frame collector node, so the exception copy of a
  finally is emitted once per ``try``, not once per throwing
  statement.
- ``solve_forward``: a classic may-analysis worklist solver over
  frozensets with union join. The transfer is per *edge*: exceptional
  out-edges skip the source node's GEN (the acquire itself may be
  what threw — no resource exists on that path) but still apply KILL
  (a release that throws has still released — the kill-before-throw
  law the RES corpus pins).

Throw classification is deliberately conservative-but-useful: calls,
``raise``/``assert``, ``yield``/``await`` and imports can throw;
plain name/attribute/subscript reads, stores and arithmetic do not
(an ``AttributeError`` or ``KeyError`` between an acquire and its
release is real in theory and pure noise in practice — and nearly
every such statement neighbors a call that already carries the edge).
Nested ``def``/``class`` bodies are opaque single nodes — they
execute at call time, not here.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque
from typing import Callable, Iterable

ENTRY = 0
EXIT = 1

# Edge kinds. Solver-exceptional kinds (GEN suppressed at the source):
EXC_KINDS = frozenset({"exc", "raise"})
# Kinds that terminate the function (edges into EXIT carry one of
# these; anything else into EXIT is the implicit end-of-body fall-off).
EXIT_EXC = frozenset({"exc", "raise"})


@dataclasses.dataclass
class Node:
    """One CFG node: a statement (header, for compounds) or synthetic."""

    idx: int
    stmt: ast.stmt | None          # None for entry/exit/join/collector
    kind: str                      # entry|exit|stmt|handler|join|collect
    line: int


@dataclasses.dataclass(frozen=True)
class Edge:
    src: int
    dst: int
    kind: str  # norm|true|false|loop|exc|raise|return|break|continue|end


@dataclasses.dataclass
class CFG:
    func: ast.AST
    nodes: list[Node]
    edges: list[Edge]

    def __post_init__(self) -> None:
        self._succ: dict[int, list[Edge]] = {}
        self._pred: dict[int, list[Edge]] = {}
        for e in self.edges:
            self._succ.setdefault(e.src, []).append(e)
            self._pred.setdefault(e.dst, []).append(e)

    def succ(self, idx: int) -> list[Edge]:
        return self._succ.get(idx, [])

    def pred(self, idx: int) -> list[Edge]:
        return self._pred.get(idx, [])

    def stmt_nodes(self) -> Iterable[Node]:
        return (n for n in self.nodes if n.stmt is not None)


# -- throw classification ----------------------------------------------------

_THROWING_EXPRS = (ast.Call, ast.Await, ast.Yield, ast.YieldFrom)


def _header_exprs(stmt: ast.stmt) -> list[ast.expr]:
    """The expressions evaluated by the statement ITSELF (for compound
    statements: the header only — bodies are separate nodes)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value else []
    if isinstance(stmt, ast.Assign):
        return [stmt.value, *stmt.targets]
    if isinstance(stmt, ast.AugAssign):
        return [stmt.target, stmt.value]
    if isinstance(stmt, ast.AnnAssign):
        return ([stmt.value, stmt.target] if stmt.value else [stmt.target])
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, ast.Delete):
        return list(stmt.targets)
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []  # the def itself; its body runs elsewhere
    return []


def can_raise(stmt: ast.stmt) -> bool:
    """May this statement (its header, for compounds) throw?"""
    if isinstance(stmt, (ast.Raise, ast.Assert, ast.Import,
                         ast.ImportFrom)):
        return True
    for expr in _header_exprs(stmt):
        for sub in ast.walk(expr):
            if isinstance(sub, _THROWING_EXPRS):
                return True
    return False


# -- builder -----------------------------------------------------------------

# A dangling edge waiting for its destination: (source node, edge kind).
_Pred = tuple[int, str]


@dataclasses.dataclass
class _HandlerFrame:
    """Exception routing for one ``try`` with except clauses: throwing
    statements in the body edge to ``collector``; at pop time the
    collector fans out to the handler nodes and (unless a bare/
    BaseException handler catches everything) onward to the outer
    frame."""

    collector: int
    handlers: list[int]
    catch_all: bool
    final_body = None  # sentinel: not a finally frame


@dataclasses.dataclass
class _FinallyFrame:
    """A ``finally`` guard: the collector gathers uncaught exceptions
    from everything the finally protects; at pop time one copy of the
    finally body is inlined on that path before propagating outward."""

    collector: int
    final_body: list[ast.stmt]


@dataclasses.dataclass
class _Loop:
    head: int        # continue target
    after: int       # break target (join node)
    depth: int       # len(frames) at loop entry: break/continue must
                     # traverse finally frames pushed inside the loop


class _Builder:
    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.nodes: list[Node] = []
        self.edges: list[Edge] = []
        self.frames: list[_HandlerFrame | _FinallyFrame] = []
        self.loops: list[_Loop] = []
        self._new("entry", None, getattr(fn, "lineno", 1))   # ENTRY
        self._new("exit", None, getattr(fn, "lineno", 1))    # EXIT

    def _new(self, kind: str, stmt: ast.stmt | None, line: int) -> int:
        idx = len(self.nodes)
        self.nodes.append(Node(idx, stmt, kind, line))
        return idx

    def _connect(self, preds: list[_Pred], dst: int,
                 kind: str | None = None) -> None:
        for src, k in preds:
            self.edges.append(Edge(src, dst, kind if kind is not None else k))

    def _has_preds(self, idx: int) -> bool:
        return any(e.dst == idx for e in self.edges)

    def _exc_target(self) -> int:
        """Where an uncaught exception goes from here: the innermost
        frame's collector, or the function exit."""
        return self.frames[-1].collector if self.frames else EXIT

    def build(self) -> CFG:
        body = list(getattr(self.fn, "body", []))
        out = self._block(body, [(ENTRY, "norm")])
        self._connect(out, EXIT, "end")
        return CFG(self.fn, self.nodes, self.edges)

    # -- statement dispatch --------------------------------------------------

    def _block(self, stmts: list[ast.stmt],
               preds: list[_Pred]) -> list[_Pred]:
        for stmt in stmts:
            if not preds:
                break  # unreachable (after return/raise/break)
            preds = self._stmt(stmt, preds)
        return preds

    def _stmt(self, stmt: ast.stmt, preds: list[_Pred]) -> list[_Pred]:
        if isinstance(stmt, ast.If):
            return self._if(stmt, preds)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, preds)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, preds)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, preds)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, preds)
        if isinstance(stmt, ast.Return):
            return self._return(stmt, preds)
        if isinstance(stmt, ast.Raise):
            n = self._simple(stmt, preds, route_exc=False)
            self.edges.append(Edge(n, self._exc_target(), "raise"))
            return []
        if isinstance(stmt, ast.Break):
            return self._jump(stmt, preds, "break")
        if isinstance(stmt, ast.Continue):
            return self._jump(stmt, preds, "continue")
        n = self._simple(stmt, preds)
        return [(n, "norm")]

    def _simple(self, stmt: ast.stmt, preds: list[_Pred],
                route_exc: bool | None = None) -> int:
        n = self._new("stmt", stmt, stmt.lineno)
        self._connect(preds, n)
        if route_exc if route_exc is not None else can_raise(stmt):
            self.edges.append(Edge(n, self._exc_target(), "exc"))
        return n

    def _if(self, stmt: ast.If, preds: list[_Pred]) -> list[_Pred]:
        n = self._simple(stmt, preds)
        t_out = self._block(stmt.body, [(n, "true")])
        f_out = (self._block(stmt.orelse, [(n, "false")])
                 if stmt.orelse else [(n, "false")])
        return t_out + f_out

    def _loop(self, stmt, preds: list[_Pred]) -> list[_Pred]:
        head = self._simple(stmt, preds)
        after = self._new("join", None, stmt.lineno)
        self.loops.append(_Loop(head, after, len(self.frames)))
        b_out = self._block(stmt.body, [(head, "true")])
        self.loops.pop()
        self._connect(b_out, head, "loop")
        e_out = (self._block(stmt.orelse, [(head, "false")])
                 if stmt.orelse else [(head, "false")])
        self._connect(e_out, after, "norm")
        return [(after, "norm")] if self._has_preds(after) else []

    def _with(self, stmt, preds: list[_Pred]) -> list[_Pred]:
        n = self._simple(stmt, preds)
        return self._block(stmt.body, [(n, "norm")])

    def _match(self, stmt: ast.Match, preds: list[_Pred]) -> list[_Pred]:
        n = self._simple(stmt, preds)
        outs: list[_Pred] = []
        for case in stmt.cases:
            outs += self._block(case.body, [(n, "true")])
        outs.append((n, "false"))  # no case matched
        return outs

    def _return(self, stmt: ast.Return, preds: list[_Pred]) -> list[_Pred]:
        n = self._simple(stmt, preds)
        self._unwind([(n, "norm")], 0, EXIT, "return")
        return []

    def _jump(self, stmt, preds: list[_Pred], kind: str) -> list[_Pred]:
        n = self._simple(stmt, preds, route_exc=False)
        if not self.loops:
            return []  # malformed outside a loop; drop the path
        loop = self.loops[-1]
        target = loop.after if kind == "break" else loop.head
        self._unwind([(n, "norm")], loop.depth, target,
                     "break" if kind == "break" else "loop")
        return []

    def _unwind(self, p: list[_Pred], down_to: int, target: int,
                kind: str) -> None:
        """Route an early exit (return/break/continue) through every
        enclosing finally between here and frame depth ``down_to``,
        inlining a fresh copy of each finally body on this path."""
        saved = self.frames
        for i in range(len(saved) - 1, down_to - 1, -1):
            frame = saved[i]
            if isinstance(frame, _FinallyFrame) and p:
                self.frames = saved[:i]  # the finally's own exceptions
                p = self._block(frame.final_body, p)  # go outward
        self.frames = saved
        if p:
            self._connect(p, target, kind)

    # -- try/except/finally --------------------------------------------------

    def _try(self, stmt: ast.Try, preds: list[_Pred]) -> list[_Pred]:
        fin: _FinallyFrame | None = None
        if stmt.finalbody:
            fin = _FinallyFrame(
                self._new("collect", None, stmt.lineno), stmt.finalbody)
            self.frames.append(fin)

        hframe: _HandlerFrame | None = None
        handler_nodes: list[int] = []
        if stmt.handlers:
            catch_all = any(
                h.type is None
                or (isinstance(h.type, ast.Name)
                    and h.type.id in ("Exception", "BaseException"))
                for h in stmt.handlers)
            handler_nodes = [self._new("handler", h, h.lineno)
                             for h in stmt.handlers]
            hframe = _HandlerFrame(
                self._new("collect", None, stmt.lineno),
                handler_nodes, catch_all)
            self.frames.append(hframe)

        body_out = self._block(stmt.body, preds)

        if hframe is not None:
            self.frames.pop()
            if self._has_preds(hframe.collector):
                for h in handler_nodes:
                    self.edges.append(Edge(hframe.collector, h, "exc"))
                if not hframe.catch_all:
                    self.edges.append(Edge(
                        hframe.collector, self._exc_target(), "exc"))

        # else-clause: runs only when the body did not raise; its own
        # exceptions skip this try's handlers (outer frames + finally)
        if stmt.orelse:
            body_out = self._block(stmt.orelse, body_out)

        handler_out: list[_Pred] = []
        for h_node, handler in zip(handler_nodes, stmt.handlers):
            handler_out += self._block(handler.body, [(h_node, "norm")])

        norm_in = body_out + handler_out
        if fin is not None:
            self.frames.pop()
            norm_out = self._block(stmt.finalbody, norm_in)
            if self._has_preds(fin.collector):
                # the exception copy: finally runs, then the exception
                # keeps propagating outward
                p = self._block(stmt.finalbody, [(fin.collector, "exc")])
                self._connect(p, self._exc_target(), "exc")
            return norm_out
        return norm_in


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef (or any body-carrying
    node). Nested defs/classes are opaque single nodes."""
    return _Builder(fn).build()


# -- worklist solver ---------------------------------------------------------

def solve_forward(cfg: CFG,
                  gen: dict[int, frozenset],
                  kill: dict[int, frozenset],
                  entry_fact: frozenset = frozenset(),
                  ) -> dict[int, frozenset]:
    """Forward may-analysis: IN-facts per node, union join.

    Per-EDGE transfer: on a normal edge ``OUT = (IN | GEN) - KILL``; on
    an exceptional edge GEN is suppressed (``OUT = IN - KILL``) — if
    the generating statement itself threw, the fact was never created,
    while a kill (a release) that throws has still killed. Facts only
    grow over a finite universe, so the fixpoint terminates and is
    independent of worklist order.
    """
    empty: frozenset = frozenset()
    ins: dict[int, frozenset] = {n.idx: empty for n in cfg.nodes}
    ins[ENTRY] = entry_fact
    work: deque[int] = deque(sorted(ins))
    queued = set(work)
    while work:
        i = work.popleft()
        queued.discard(i)
        base = ins[i]
        k = kill.get(i, empty)
        norm_out = (base | gen.get(i, empty)) - k
        exc_out = base - k
        for e in cfg.succ(i):
            out = exc_out if e.kind in EXC_KINDS else norm_out
            if not out <= ins[e.dst]:
                ins[e.dst] = ins[e.dst] | out
                if e.dst not in queued:
                    queued.add(e.dst)
                    work.append(e.dst)
    return ins


def exit_edges(cfg: CFG) -> list[Edge]:
    """Every edge into the function exit."""
    return cfg.pred(EXIT)


def exit_facts(cfg: CFG, ins: dict[int, frozenset],
               gen: dict[int, frozenset], kill: dict[int, frozenset],
               ) -> list[tuple[Edge, frozenset]]:
    """(edge-into-exit, facts-live-across-it) pairs, recomputing the
    per-edge transfer so exceptional exits correctly exclude the
    throwing statement's own GEN."""
    empty: frozenset = frozenset()
    out: list[tuple[Edge, frozenset]] = []
    for e in exit_edges(cfg):
        base = ins.get(e.src, empty)
        k = kill.get(e.src, empty)
        fact = (base - k if e.kind in EXC_KINDS
                else (base | gen.get(e.src, empty)) - k)
        out.append((e, fact))
    return out
