"""Tenant attribution plane: pre-registration, the chargeback surface,
and the contention-bench contract.

Covers the ISSUE-19 satellites end to end:

- a FRESH tenant's counter families are pre-registered at 0 on first
  sight, so its very FIRST error produces a nonzero ``increase()`` and
  the ``TenantRequestFailures`` tripwire fires (the PR 10 lesson:
  ``rate()`` over a series born non-zero reports nothing);
- ``GET /api/chargeback`` validates its params (400 on garbage, never
  500) and serves the conservation-checked per-tenant bill;
- ``TenantLedger.check`` raises on a bill that does not add up to the
  fleet ledger — misattribution is an error, not a log line;
- ``tools/chargeback_bench.py`` replays byte-identically and its
  committed bank stays green.
"""

import json

import pytest

from kubeflow_tpu.obs import goodput as gp
from kubeflow_tpu.obs import trace as tr
from kubeflow_tpu.obs.plane import FleetPlane
from kubeflow_tpu.obs.rules import tenant_rule_pack
from kubeflow_tpu.obs.tsdb import RegistryTarget
from kubeflow_tpu.runtime.metrics import MetricsRegistry
from kubeflow_tpu.serving.router import Member, TokenRouter


class ManualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def mkspan(name, start, end, **attrs):
    s = tr.Span(name=name, trace_id="t" * 32, span_id=tr.new_span_id(),
                start=start, attrs=attrs)
    s.end = end
    return s


def _router(clock, reg):
    r = TokenRouter(service="chat", namespace="default", clock=clock,
                    registry=reg, tracer=tr.Tracer(tr.TraceCollector()),
                    prom_sink=False)
    r.set_members([Member(name="replica-0", transport=None)])
    return r


# -- satellite 1: pre-registration + first-error alert -----------------------


class TestTenantPreRegistration:
    def test_first_sight_registers_all_outcomes_at_zero(self):
        clock = ManualClock()
        reg = MetricsRegistry()
        router = _router(clock, reg)
        t = router.submit(10, tenant="team-alpha")
        router.complete(t)
        text = reg.render()
        # every outcome series exists the moment the tenant appears —
        # including the ones that have not happened yet
        for outcome in ("failed", "rejected", "deadline", "shed",
                        "shed_band"):
            assert (f'router_requests_total{{namespace="default",'
                    f'outcome="{outcome}",service="chat",'
                    f'tenant="team-alpha"}} 0') in text, outcome
        for kind in ("retry", "hedge"):
            assert (f'router_tenant_retry_tokens_total{{kind="{kind}",'
                    f'namespace="default",service="chat",'
                    f'tenant="team-alpha"}} 0') in text, kind
        assert ('router_tenant_queue_depth{namespace="default",'
                'service="chat",tenant="team-alpha"}') in text

    def test_fresh_tenants_first_error_fires_the_tripwire(self):
        """Regression for the zero-sample contract: the first FAILED
        request of a brand-new tenant must alert. Without the 0-valued
        pre-registration the failed series would be born at 1 and
        ``increase()`` would see a single point — no rate, no alert."""
        clock = ManualClock()
        reg = MetricsRegistry()
        router = _router(clock, reg)
        plane = FleetPlane(
            registry=MetricsRegistry(),
            targets=[RegistryTarget("router", reg)],
            rules=tenant_rule_pack(), interval_s=15.0, clock=clock,
            collector=tr.TraceCollector())
        # cycle 0: the tenant's first-ever request succeeds — the
        # scrape banks the pre-registered failed=0 sample
        t = router.submit(10, tenant="team-new")
        router.complete(t)
        fired = list(plane.tick(at=clock.t)["transitions"])
        clock.advance(15.0)
        # cycle 1: its very FIRST error
        t = router.submit(10, tenant="team-new")
        router.fail(t, requeue=False)
        fired += plane.tick(at=clock.t)["transitions"]
        hits = [x for x in fired
                if x["alert"] == "TenantRequestFailures"
                and x["to"] == "firing"]
        assert hits, fired
        assert hits[0]["labels"]["tenant"] == "team-new"


# -- the conservation-checked ledger cut -------------------------------------


class TestTenantLedger:
    def _spans(self):
        return [
            mkspan("train.step", 10.0, 40.0, tenant="team-a"),
            mkspan("train.checkpoint", 40.0, 50.0, tenant="team-a"),
            mkspan("train.step", 0.0, 60.0, tenant="team-b"),
        ]

    def test_buckets_conserve_per_tenant_and_fleet_wide(self):
        ledger = gp.tenant_report(
            self._spans(), 0.0, 100.0,
            chips_by_tenant={"team-a": 4, "team-b": 8}).check()
        assert set(ledger.reports) == {"team-a", "team-b"}
        assert ledger.chips == 12
        for report in ledger.reports.values():
            assert sum(report.buckets.values()) == pytest.approx(100.0)
        total = sum(sum(cs.values()) for cs in
                    ledger.chip_seconds_by_tenant().values())
        assert total == pytest.approx(100.0 * 12)

    def test_doctored_bucket_raises_not_warns(self):
        ledger = gp.tenant_report(self._spans(), 0.0, 100.0)
        ledger.reports["team-a"].buckets[gp.OTHER] += 1.0
        with pytest.raises(AssertionError, match="team-a"):
            ledger.check()

    def test_idle_tenant_listed_in_chips_is_billed_admission(self):
        ledger = gp.tenant_report(
            [], 0.0, 50.0, chips_by_tenant={"team-idle": 2}).check()
        report = ledger.reports["team-idle"]
        assert report.buckets[gp.ADMISSION] == pytest.approx(50.0)


# -- the /api/chargeback surface ---------------------------------------------


class TestChargebackApi:
    def _dash(self):
        from kubeflow_tpu.control.k8s.fake import FakeCluster
        from kubeflow_tpu.utils.httpd import HttpReq
        from kubeflow_tpu.webapps.dashboard import Dashboard

        clock = ManualClock(t=100.0)
        reg = MetricsRegistry()
        router = _router(clock, reg)
        for tenant in ("team-a", "team-b"):
            t = router.submit(10, tenant=tenant)
            router.complete(t)
        collector = tr.TraceCollector()
        collector.add(mkspan("train.step", 40.0, 90.0, tenant="team-a"))
        collector.add(mkspan("train.step", 20.0, 100.0, tenant="team-b"))
        plane = FleetPlane(
            registry=MetricsRegistry(),
            targets=[RegistryTarget("router", reg)],
            rules=tenant_rule_pack(), interval_s=15.0, clock=clock,
            collector=collector)
        plane.tick(at=clock.t)
        router_http = Dashboard(FakeCluster(), plane=plane).router()

        def get(path, query=None):
            resp = router_http.dispatch(HttpReq(
                method="GET", path=path, params={},
                query=query or {},
                headers={"kubeflow-userid": "alice@example.com"}))
            return resp.status, json.loads(resp.body)

        return get

    def test_malformed_params_are_400_not_500(self):
        get = self._dash()
        assert get("/api/chargeback", {"window_s": ["x"]})[0] == 400
        assert get("/api/chargeback", {"window_s": ["-5"]})[0] == 400
        assert get("/api/chargeback", {"window_s": ["inf"]})[0] == 400
        assert get("/api/chargeback", {"chips": ["abc"]})[0] == 400
        assert get("/api/chargeback", {"chips": ["0"]})[0] == 400
        assert get("/api/chargeback",
                   {"tenant": ["Not_A_Label!"]})[0] == 400

    def test_bill_conserves_over_a_two_tenant_plane(self):
        get = self._dash()
        status, doc = get("/api/chargeback",
                          {"window_s": ["100"], "chips": ["4"]})
        assert status == 200
        tenants = doc["tenants"]
        assert set(tenants) >= {"team-a", "team-b"}
        # conservation surfaced, not just checked server-side: every
        # tenant's buckets sum to the window, and chip-seconds across
        # tenants sum to the fleet ledger
        fleet = 0.0
        for bill in tenants.values():
            good = bill["goodput"]
            assert sum(good["buckets_s"].values()) == pytest.approx(
                good["wall_s"])
            fleet += sum(good["buckets_s"].values()) * good["chips"]
        assert fleet == pytest.approx(100.0 * doc["chips"])
        # team-b trained 80 of the 100s window; team-a 50
        assert tenants["team-b"]["goodput"]["goodput_pct"] \
            == pytest.approx(80.0)
        assert tenants["team-a"]["goodput"]["goodput_pct"] \
            == pytest.approx(50.0)
        assert tenants["team-a"]["slo"][0]["met"] is True

    def test_tenant_param_narrows_the_bill(self):
        get = self._dash()
        status, doc = get("/api/chargeback", {"tenant": ["team-a"],
                                              "window_s": ["100"]})
        assert status == 200
        assert set(doc["tenants"]) == {"team-a"}
        status, doc = get("/api/chargeback", {"tenant": ["team-zz"],
                                              "window_s": ["100"]})
        assert status == 200
        assert doc["tenants"] == {}


# -- bench contract (CI ratchet) ---------------------------------------------


@pytest.mark.usefixtures("virtual_time_guard")
class TestChargebackBenchContract:
    def test_double_run_is_byte_identical(self):
        from tools.chargeback_bench import SMOKE_CONFIG, run_bench

        r1 = run_bench(**SMOKE_CONFIG)
        r2 = run_bench(**SMOKE_CONFIG)
        r1.pop("machine")
        r2.pop("machine")
        assert json.dumps(r1, sort_keys=True) \
            == json.dumps(r2, sort_keys=True)

    def test_check_green_against_committed_bank(self):
        from tools.chargeback_bench import DEFAULT_OUT, check_against

        assert check_against(DEFAULT_OUT) == 0

    def test_check_fails_on_poisoned_bank(self, tmp_path):
        from tools.chargeback_bench import DEFAULT_OUT, check_against

        with open(DEFAULT_OUT) as fh:
            bank = json.load(fh)
        bank["smoke"]["decision_fingerprint"] = "0" * 64
        poisoned = tmp_path / "bank.json"
        poisoned.write_text(json.dumps(bank))
        assert check_against(str(poisoned)) == 1

    def test_banked_attribution_is_correct(self):
        from tools.chargeback_bench import (
            BURN_TENANT, DEFAULT_OUT, STORM_TENANT,
        )

        with open(DEFAULT_OUT) as fh:
            bank = json.load(fh)
        for section in ("full", "smoke"):
            run = bank[section]
            assert run["conservation"] == "ok"
            assert run["tenant_alerts"]["TenantRetryStorm"] \
                == [STORM_TENANT]
            assert run["tenant_alerts"]["TenantSLOBurn"] \
                == [BURN_TENANT]
            bills = run["invoice"]
            assert sum(bills[STORM_TENANT]["retry_tokens"].values()) > 0
            assert bills[BURN_TENANT]["slo_met"] is False
            for tenant, bill in bills.items():
                if tenant not in (STORM_TENANT, BURN_TENANT):
                    assert sum(bill["retry_tokens"].values()) == 0
                    assert bill["slo_met"] is not False
