"""Request-level resilience for the serving plane (ISSUE 14): deadline
propagation and sweeps, per-replica circuit breakers, hedged dispatch
under a token-bucket retry budget, criticality-band shedding, and
Retry-After backpressure — the deterministic core drills on a manual
clock, the replica-side slot-cancel zero-leak proof, and the
serve_bench --resilience ratchet contract."""

import json
import threading

import pytest

from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.runtime.metrics import MetricsRegistry
from kubeflow_tpu.serving.router import (
    BAND_CRITICAL, BAND_DEFAULT, BAND_SHEDDABLE, BREAKER_CLOSED,
    BREAKER_HALF_OPEN, BREAKER_OPEN, HEADER_DEADLINE, DeadlineExceeded,
    Member, ResilienceConfig, RouterBusy, RouterFrontend, TokenRouter,
    TransportError,
)

pytestmark = pytest.mark.serving


class ManualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _router(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("prom_sink", False)
    kw.setdefault("tracer", obs_trace.Tracer())
    kw.setdefault("resilience", ResilienceConfig())
    return TokenRouter(service="svc", namespace="ns", **kw)


def _members(r, n):
    r.set_members([Member(name=f"r{i}") for i in range(n)])


def _seed_latency(router, clock, n=20, latency=1.0, tokens=1):
    """Complete ``n`` requests at a fixed latency so the hedge quantile
    has samples (and every replica has EWMA history)."""
    for _ in range(n):
        t = router.submit(tokens)
        assert t.member is not None
        clock.advance(latency)
        router.complete(t)


# -- deadlines ---------------------------------------------------------------


class TestDeadlines:
    def test_dead_on_arrival_raises_without_queueing(self):
        clock = ManualClock(100.0)
        r = _router(clock=clock)
        _members(r, 1)
        with pytest.raises(DeadlineExceeded):
            r.submit(8, deadline=99.0)
        assert r.queue_depth() == 0
        assert 'outcome="deadline"' in r.registry.render()

    def test_queued_ticket_swept_at_deadline_before_dispatch(self):
        clock = ManualClock()
        r = _router(clock=clock, replica_token_budget=10)
        _members(r, 1)
        t1 = r.submit(8)                      # occupies the replica
        t2 = r.submit(8, deadline=5.0)        # queued behind it
        assert t1.member is not None and t2.member is None
        clock.advance(6.0)                    # past t2's deadline
        dispatched = r.complete(t1)           # capacity appears too late
        assert dispatched == []               # t2 was swept, not served
        assert t2.dropped_reason == "deadline"
        assert t2.done.is_set()               # a parked shell wakes up
        assert r.queue_depth() == 0

    def test_sweep_fires_on_submit_too(self):
        clock = ManualClock()
        r = _router(clock=clock, replica_token_budget=10)
        _members(r, 1)
        r.submit(8)
        stale = r.submit(8, deadline=2.0)
        clock.advance(3.0)
        fresh = r.submit(8, deadline=20.0)    # admission sweeps the queue
        assert stale.dropped_reason == "deadline"
        assert fresh.member is None and r.queue_depth() == 1

    def test_fail_past_deadline_drops_instead_of_retrying(self):
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 2)
        t = r.submit(8, deadline=5.0)
        clock.advance(6.0)
        r.fail(t, requeue=True)               # transport died after the dl
        assert t.member is None
        assert t.dropped_reason == "deadline"


# -- circuit breakers --------------------------------------------------------


class TestCircuitBreaker:
    def test_opens_after_consecutive_failures(self):
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 1)
        t = r.submit(8)
        for _ in range(3):                    # breaker_failures = 3
            assert t.member is not None
            redispatched = r.fail(t, requeue=True)
            if redispatched:
                t = redispatched[0]
        assert r.breaker_states()["r0"] == BREAKER_OPEN

    def _opened(self, clock):
        r = _router(clock=clock)
        _members(r, 1)
        t = r.submit(8)
        for _ in range(3):
            redispatched = r.fail(t, requeue=True)
            t = redispatched[0] if redispatched else t
        assert r.breaker_states()["r0"] == BREAKER_OPEN
        # flush the wedged ticket so later asserts see a clean queue
        r.fail(t, requeue=False)
        return r

    def test_open_breaker_receives_no_work(self):
        clock = ManualClock()
        r = self._opened(clock)
        t = r.submit(8)
        assert t.member is None               # queued: r0 is ineligible

    def test_cooloff_half_opens_with_a_single_probe(self):
        clock = ManualClock()
        r = self._opened(clock)
        clock.advance(5.5)                    # past breaker_cooloff_s
        probe = r.submit(8)
        assert probe.member is not None       # the probe dispatch
        assert r.breaker_states()["r0"] == BREAKER_HALF_OPEN
        second = r.submit(8)
        assert second.member is None          # one probe at a time

    def test_probe_success_recloses(self):
        clock = ManualClock()
        r = self._opened(clock)
        clock.advance(5.5)
        probe = r.submit(8)
        clock.advance(0.2)
        r.complete(probe)
        assert r.breaker_states()["r0"] == BREAKER_CLOSED

    def test_probe_failure_reopens(self):
        clock = ManualClock()
        r = self._opened(clock)
        clock.advance(5.5)
        probe = r.submit(8)
        r.fail(probe, requeue=False)          # the probe dies
        assert r.breaker_states()["r0"] == BREAKER_OPEN

    def test_slow_replica_drains_by_latency_score(self):
        """EWMA latency scales the pick key: the browned-out (10x slow)
        replica loses a dispatch that raw least-tokens would hand it."""
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 2)
        for _ in range(6):                    # r0 fast, r1 slow
            a = r.submit(1)
            b = r.submit(1)
            fast = a if a.member.name == "r0" else b
            slow = b if fast is a else a
            clock.advance(0.1)
            r.complete(fast)
            clock.advance(0.9)
            r.complete(slow)
        t1 = r.submit(8)
        t2 = r.submit(4)
        assert t1.member.name == "r0" and t2.member.name == "r1"
        # r0 carries MORE tokens (8 vs 4) — raw least-outstanding would
        # pick r1 — but r1's 10x latency multiplier prices it out
        t3 = r.submit(4)
        assert t3.member.name == "r0"


# -- criticality bands -------------------------------------------------------


class TestBandShedding:
    def _full(self, clock, band):
        r = _router(clock=clock, max_queue=2)
        _members(r, 0)                        # no capacity: all queue
        queued = [r.submit(8, band=band) for _ in range(2)]
        return r, queued

    def test_critical_arrival_evicts_newest_sheddable(self):
        clock = ManualClock()
        r, queued = self._full(clock, BAND_SHEDDABLE)
        crit = r.submit(8, band=BAND_CRITICAL)
        victim = queued[1]                    # NEWEST lower-band ticket
        assert victim.dropped_reason == "shed_band"
        assert victim.retry_after >= 1.0
        assert victim.done.is_set()
        assert crit.member is None and r.queue_depth() == 2
        assert 'band="sheddable"' in r.registry.render()

    def test_no_lower_band_rejects_the_arrival(self):
        clock = ManualClock()
        r, queued = self._full(clock, BAND_CRITICAL)
        with pytest.raises(RouterBusy) as exc:
            r.submit(8, band=BAND_SHEDDABLE)
        assert exc.value.retry_after >= 1.0
        assert all(t.dropped_reason is None for t in queued)

    def test_equal_band_rejects_the_arrival(self):
        clock = ManualClock()
        r, queued = self._full(clock, BAND_DEFAULT)
        with pytest.raises(RouterBusy):
            r.submit(8, band=BAND_DEFAULT)

    def test_drain_serves_critical_before_older_sheddable(self):
        clock = ManualClock()
        r = _router(clock=clock, replica_token_budget=10)
        _members(r, 1)
        blocker = r.submit(8)
        shed = r.submit(8, band=BAND_SHEDDABLE)   # queued FIRST
        crit = r.submit(8, band=BAND_CRITICAL)    # queued second
        dispatched = r.complete(blocker)
        assert dispatched == [crit]               # band beats FIFO
        assert shed.member is None

    def test_legacy_router_keeps_fifo_drain(self):
        r = _router(resilience=None, replica_token_budget=10)
        _members(r, 1)
        blocker = r.submit(8)
        first = r.submit(8, band=BAND_SHEDDABLE)
        r.submit(8, band=BAND_CRITICAL)
        assert r.complete(blocker) == [first]     # strict FIFO


# -- retry budget ------------------------------------------------------------


class TestRetryBudget:
    def test_exhausted_budget_drops_with_reason(self):
        clock = ManualClock()
        cfg = ResilienceConfig(retry_budget_cap=1.0, retry_budget_ratio=0.0)
        r = _router(clock=clock, resilience=cfg)
        _members(r, 1)
        t = r.submit(8)
        redispatched = r.fail(t, requeue=True)    # spends the last token
        t = redispatched[0]
        assert t.dropped_reason is None
        r.fail(t, requeue=True)                   # budget is dry now
        assert t.dropped_reason == "retry_budget"
        assert t.retry_after >= 1.0
        assert t.member is None and r.queue_depth() == 0

    def test_admissions_refill_the_bucket(self):
        clock = ManualClock()
        cfg = ResilienceConfig(retry_budget_cap=2.0, retry_budget_ratio=0.5)
        r = _router(clock=clock, resilience=cfg)
        _members(r, 1)
        t = r.submit(8)
        r.fail(t, requeue=True)                   # 2.0 + 0.5 - 1.0 = 1.5
        before = r.retry_budget()
        for _ in range(4):
            r.complete(r.submit(1))               # +0.5 each, capped at 2
        assert r.retry_budget() == pytest.approx(
            min(before + 4 * 0.5, 2.0))


class TestTenantRetryIsolation:
    def test_storm_exhausts_only_the_noisy_tenants_bucket(self):
        """ISSUE 20 satellite: retry/hedge tokens are bucketed PER
        TENANT — tenant A's retry storm drains A's bucket to zero while
        tenant B seeds its own bucket from the pool headroom and its
        retries still spend."""
        clock = ManualClock()
        cfg = ResilienceConfig(retry_budget_cap=2.0,
                               retry_budget_ratio=0.0)
        r = _router(clock=clock, resilience=cfg)
        _members(r, 2)
        assert r.retry_budget(tenant="A") == 0.0  # unseen: no bucket yet
        ta = r.submit(8, tenant="A")
        assert r.retry_budget(tenant="A") == pytest.approx(2.0)
        for _ in range(2):                        # A's retry storm
            ta = r.fail(ta, requeue=True)[0]
            assert ta.dropped_reason is None
        r.fail(ta, requeue=True)                  # A's bucket is dry
        assert ta.dropped_reason == "retry_budget"
        assert r.retry_budget(tenant="A") == pytest.approx(0.0)
        # B seeds its OWN bucket from the headroom A never consumed —
        # the storm next door did not spend B's tokens
        tb = r.submit(8, tenant="B")
        assert r.retry_budget(tenant="B") == pytest.approx(2.0)
        redispatched = r.fail(tb, requeue=True)
        survivor = redispatched[0] if redispatched else tb
        assert survivor.dropped_reason is None    # B's retry still spends
        assert r.retry_budget(tenant="B") == pytest.approx(1.0)
        assert r.retry_budget(tenant="A") == pytest.approx(0.0)
        assert r.retry_budget() == pytest.approx(1.0)
        assert 'tenant="A"' in r.registry.render()


# -- hedging -----------------------------------------------------------------


class TestHedging:
    def test_hedge_delay_needs_samples_then_tracks_quantile(self):
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 2)
        assert r.hedge_delay() is None
        _seed_latency(r, clock, n=20, latency=1.0)
        assert r.hedge_delay() == pytest.approx(1.0)

    def test_try_hedge_charges_both_replicas_and_budget(self):
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 2)
        _seed_latency(r, clock, n=20, latency=1.0)
        budget0 = r.retry_budget()
        t = r.submit(8)
        primary = t.member.name
        hedge = r.try_hedge(t)
        assert hedge is not None and hedge.name != primary
        assert r.inflight_tokens(primary) == 8
        assert r.inflight_tokens(hedge.name) == 8
        assert r.retry_budget() == pytest.approx(budget0 - 1.0)
        assert r.try_hedge(t) is None             # one hedge per ticket

    def test_hedge_winner_releases_both_legs(self):
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 2)
        _seed_latency(r, clock, n=20, latency=1.0)
        t = r.submit(8)
        hedge = r.try_hedge(t)
        clock.advance(0.5)
        r.complete(t, winner=hedge.name)
        assert r.inflight_tokens() == 0
        assert t.hedge_member is None
        assert 'outcome="won"' in r.registry.render()

    def test_primary_win_cancels_the_hedge_leg(self):
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 2)
        _seed_latency(r, clock, n=20, latency=1.0)
        t = r.submit(8)
        r.try_hedge(t)
        r.complete(t)                             # primary answered
        assert r.inflight_tokens() == 0
        assert 'outcome="canceled"' in r.registry.render()

    def test_no_distinct_replica_means_no_hedge(self):
        clock = ManualClock()
        r = _router(clock=clock)
        _members(r, 1)
        _seed_latency(r, clock, n=20, latency=1.0)
        t = r.submit(8)
        assert r.try_hedge(t) is None

    def test_hedge_denied_past_deadline_or_without_budget(self):
        clock = ManualClock()
        cfg = ResilienceConfig(retry_budget_cap=0.5,
                               retry_budget_ratio=0.0)
        r = _router(clock=clock, resilience=cfg)
        _members(r, 2)
        t = r.submit(8, deadline=clock.t + 10.0)
        assert r.try_hedge(t) is None             # budget below 1.0
        r2 = _router(clock=clock)
        _members(r2, 2)
        t2 = r2.submit(8, deadline=clock.t + 1.0)
        clock.advance(2.0)
        assert r2.try_hedge(t2) is None           # deadline passed


# -- Retry-After propagation -------------------------------------------------


class TestRetryAfter:
    def test_router_busy_carries_drain_rate_estimate(self):
        clock = ManualClock()
        r = _router(clock=clock, max_queue=3, replica_token_budget=10)
        _members(r, 1)
        for _ in range(5):                        # 1 completion per second
            t = r.submit(8)
            clock.advance(1.0)
            r.complete(t)
        r.submit(8)                               # occupies the replica
        for _ in range(3):
            r.submit(8)
        with pytest.raises(RouterBusy) as exc:
            r.submit(8)
        # depth 3 + the arrival, at ~1/s -> ~4s, clamped to [1, 120]
        assert 1.0 <= exc.value.retry_after <= 10.0

    def test_http_transport_parses_retry_after_header(self, monkeypatch):
        import io
        import urllib.error
        import urllib.request

        from kubeflow_tpu.serving.router import HttpTransport

        def boom(req, timeout=None):
            raise urllib.error.HTTPError(
                req.full_url, 429, "Too Many Requests",
                {"Retry-After": "7"}, io.BytesIO(b"{}"))

        monkeypatch.setattr(urllib.request, "urlopen", boom)
        tr = HttpTransport("http://replica.invalid")
        with pytest.raises(TransportError) as exc:
            tr.predict("lm", b"{}")
        assert exc.value.status == 429
        assert exc.value.retry_after == 7.0

    def test_frontend_backoff_floor_honors_retry_after(self):
        """A replica's Retry-After beats the frontend's exponential
        backoff schedule: the first retry waits the FLOOR, not 50ms."""
        clock = ManualClock()
        r = _router(clock=clock)
        sleeps: list = []

        class FlakyTransport:
            calls = 0

            def predict(self, model, body, headers=None):
                FlakyTransport.calls += 1
                if FlakyTransport.calls == 1:
                    raise TransportError(503, "overloaded",
                                         retry_after=2.0)
                return json.dumps({"predictions": [[1]]}).encode()

        r.set_members([Member(name="r0", transport=FlakyTransport())])
        fe = RouterFrontend(r, max_new_tokens=4, sleep=sleeps.append)
        fe.hedging = False
        req = _FakeReq({"instances": [{"tokens": [1, 2]}]})
        out = fe.predict(req)
        assert out == {"predictions": [[1]]}
        assert sleeps and sleeps[0] == pytest.approx(2.0)

    def test_drop_reasons_map_to_http_statuses(self):
        from kubeflow_tpu.serving.router import Ticket

        t = Ticket(tokens=1)
        t.dropped_reason = "deadline"
        assert RouterFrontend._drop_error(t).status == 504
        t.dropped_reason = "shed_band"
        t.retry_after = 3.0
        err = RouterFrontend._drop_error(t)
        assert err.status == 429
        assert err.headers["Retry-After"] == "3"
        t.dropped_reason = "retry_budget"
        assert RouterFrontend._drop_error(t).status == 503

    def test_frontend_shrinks_deadline_header_replica_ward(self):
        """The replica sees the REMAINING budget, not the original."""
        clock = ManualClock(10.0)
        r = _router(clock=clock)
        seen: list = []

        class Capture:
            def predict(self, model, body, headers=None):
                seen.append(headers or {})
                clock.advance(1.0)
                return json.dumps({"predictions": [[1]]}).encode()

        r.set_members([Member(name="r0", transport=Capture())])
        fe = RouterFrontend(r, max_new_tokens=4, sleep=lambda s: None)
        fe.hedging = False
        req = _FakeReq({"instances": [{"tokens": [1]}]},
                       headers={HEADER_DEADLINE: "8.0"})
        fe.predict(req)
        assert float(seen[0][HEADER_DEADLINE]) == pytest.approx(8.0)

    def test_empty_deadline_header_means_no_deadline(self):
        """The REAL shell's HttpReq.header returns "" (not None) for a
        missing header — it must read as 'no deadline', not 400. Pinned
        live by tests/test_router_live.py; this is the fast repro."""
        r = _router()

        class Ok:
            def predict(self, model, body, headers=None):
                assert not (headers or {}).get(HEADER_DEADLINE)
                return json.dumps({"predictions": [[1]]}).encode()

        r.set_members([Member(name="r0", transport=Ok())])
        fe = RouterFrontend(r, max_new_tokens=4, sleep=lambda s: None)
        fe.hedging = False

        class _ShellReq(_FakeReq):
            def header(self, name, default=None):
                # the httpd shell's semantics: default is ""
                return self._headers.get(name.lower(), "")

        out = fe.predict(_ShellReq({"instances": [{"tokens": [1]}]}))
        assert out == {"predictions": [[1]]}


class _FakeReq:
    """The slice of HttpReq the frontend touches."""

    def __init__(self, body_obj, headers=None, model="lm"):
        self.body = json.dumps(body_obj).encode()
        self.params = {"model": model}
        self._headers = {k.lower(): v for k, v in (headers or {}).items()}

    def json(self):
        return json.loads(self.body)

    def header(self, name, default=None):
        return self._headers.get(name.lower(), default)


# -- replica-side overload gate ----------------------------------------------


class TestServerOverload:
    def test_max_inflight_429_carries_retry_after(self):
        from kubeflow_tpu.serving.server import REPLICA_METER, ServedModel
        from kubeflow_tpu.utils.httpd import ApiHttpError

        m = ServedModel(name="overload-test", predict_fn=lambda b: b,
                        pad_batches=False, max_inflight=1)
        REPLICA_METER.enter("overload-test", 1)   # a stuck peer request
        try:
            with pytest.raises(ApiHttpError) as exc:
                m.predict([[1, 2]])
            assert exc.value.status == 429
            assert int(exc.value.headers["Retry-After"]) >= 1
        finally:
            REPLICA_METER.exit("overload-test")
        assert m.predict([[1, 2], [3, 4]]) == [[1, 2], [3, 4]]


# -- the replica-side slot cancel (zero-leak contract) -----------------------


@pytest.fixture(scope="module")
def paged_lm():
    import jax
    import numpy as np

    from kubeflow_tpu.models.registry import get_model

    model = get_model("transformer-test", vocab_size=64, max_seq_len=24,
                      kv_pages=33, kv_page_size=4)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 1), np.int32), train=False)
    return model, variables


class _AfterAdmitClock:
    """0.0 until the decoder has admitted a request, then just past the
    500.0 deadline: the round-boundary sweep right after admission sees
    the deadline expired — a deterministic mid-flight cancel, no
    sleeps. Deliberately INSIDE the waiter's +30s wedge-guard grace
    (submit_padded polls the same clock while the first decode round
    jit-compiles; jumping past deadline+30 would let that poll raise
    before the loop's cancel is recorded)."""

    def __init__(self):
        self.dec = None

    def __call__(self) -> float:
        if self.dec is not None and self.dec.stats()["admitted"] >= 1:
            return 501.0
        return 0.0


class TestSlotDecoderDeadline:
    def test_queue_side_gate_cancels_before_prefill(self, paged_lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = paged_lm
        clock = ManualClock(50.0)
        dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                          max_new_tokens=4, clock=clock)
        try:
            with pytest.raises(DeadlineExceeded):
                dec.submit([1, 2, 3], deadline=49.0)   # already past
            st = dec.stats()
            assert st["deadline_canceled"] == 1
            assert st["admitted"] == 0                 # never cost a slot
            assert st["kv_pages_free"] == st["kv_pages_total"]
            dec.alloc.check()
        finally:
            dec.close()

    def test_mid_decode_cancel_frees_slot_and_pages(self, paged_lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = paged_lm
        clock = _AfterAdmitClock()
        # prefix_cache off: the LRU prefix index retaining prompt pages
        # across frees is reuse, not the leak this test guards against
        dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                          max_new_tokens=12, clock=clock,
                          prefix_cache=False)
        clock.dec = dec
        try:
            with pytest.raises(DeadlineExceeded):
                dec.submit([1, 2, 3], max_new=12, deadline=500.0)
            st = dec.stats()
            assert st["admitted"] == 1                 # it DID hold a slot
            assert st["deadline_canceled"] == 1
            assert st["completed"] == 0
            # the cancel returned every page: zero-leak contract
            assert st["kv_pages_free"] == st["kv_pages_total"]
            dec.alloc.check()
            assert dec.active_slots == 0
            # the decoder is still healthy after the cancel
            assert len(dec.submit([4, 5], max_new=2)) == 2
        finally:
            dec.close()

    def test_no_deadline_requests_are_untouched(self, paged_lm):
        from kubeflow_tpu.serving.continuous import SlotDecoder

        model, variables = paged_lm
        clock = ManualClock(1e9)                       # far future always
        dec = SlotDecoder(model, variables, slots=2, prompt_len=8,
                          max_new_tokens=4, clock=clock)
        try:
            assert len(dec.submit([1, 2, 3])) == 4     # deadline=None
            assert dec.stats()["deadline_canceled"] == 0
        finally:
            dec.close()


# -- the serve_bench --resilience contract -----------------------------------


def _bench():
    import os
    import sys

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(here, "tools"))
    try:
        import serve_bench as sb
    finally:
        sys.path.pop(0)
    return sb


@pytest.mark.usefixtures("virtual_time_guard")
class TestResilienceBenchContract:
    def test_banked_results_satisfy_acceptance(self):
        """BENCH_SERVE_r03.json is the PR's acceptance artifact: the
        resilient arm shelters critical-band goodput through the
        brownout while the control arm degrades, hedges actually rescue
        work, no critical request is ever shed, the breaker completes
        its round trip, and the KV cancel drill recovered every page."""
        import os

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(here, "BENCH_SERVE_r03.json")) as fh:
            banked = json.load(fh)
        sec = banked["resilience"]
        cmp_ = sec["comparison"]
        assert cmp_["critical_goodput_resilient"] >= 0.9
        assert cmp_["critical_goodput_control"] < 0.7
        assert cmp_["hedge_wins"] >= 1
        assert cmp_["critical_sheds"] == 0
        assert cmp_["breaker_round_trip"] is True
        assert cmp_["replay_identical"] is True
        drill = sec["kv_drill"]
        assert drill["pages_recovered"] is True
        assert drill["invariant_clean"] is True
        assert drill["mid_flight_frees"] > 0

    def test_same_seed_replays_byte_identical(self):
        import random

        sb = _bench()
        cfg = dict(sb.RES_CONFIG)
        trace = sb.build_res_trace(cfg, random.Random(cfg["seed"]))
        a = sb.run_resilience_arm("resilient", cfg, trace)
        b = sb.run_resilience_arm("resilient", cfg, trace)
        assert a["decision_fingerprint"] == b["decision_fingerprint"]
        assert a == b

    def test_check_gate_round_trip(self, tmp_path):
        """--check passes against a just-banked run and fails loudly on
        a poisoned decision fingerprint or a KV drill regression — the
        ratchet has teeth."""
        sb = _bench()
        banked = {"resilience": sb.run_resilience_bench(
            dict(sb.RES_CONFIG))}
        ok = tmp_path / "bank_ok.json"
        ok.write_text(json.dumps(banked))
        assert sb.check_resilience_bench(str(ok)) == 0
        bad = json.loads(ok.read_text())
        bad["resilience"]["resilient"]["decision_fingerprint"] = "deadbeef"
        bad_path = tmp_path / "bank_bad.json"
        bad_path.write_text(json.dumps(bad))
        assert sb.check_resilience_bench(str(bad_path)) == 1
        empty = tmp_path / "bank_empty.json"
        empty.write_text(json.dumps({"router": {}}))
        assert sb.check_resilience_bench(str(empty)) == 2


# -- chaos-parameterized brownout reruns -------------------------------------


from conftest import CHAOS_SEEDS  # noqa: E402


@pytest.mark.chaos
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_brownout_drill_invariants_hold_across_seeds(seed):
    """The resilience drill's INVARIANTS (not its tuned thresholds) must
    hold for any fault schedule: deterministic replay, zero critical
    sheds, and the resilient arm never WORSE than the control arm on
    critical-band goodput through the brownout."""
    import random

    sb = _bench()
    cfg = dict(sb.RES_CONFIG)
    cfg["seed"] = seed
    trace = sb.build_res_trace(cfg, random.Random(seed))
    resilient = sb.run_resilience_arm("resilient", cfg, trace)
    control = sb.run_resilience_arm("control", cfg, trace)
    replay = sb.run_resilience_arm("resilient", cfg, trace)
    assert resilient["decision_fingerprint"] == \
        replay["decision_fingerprint"]
    assert resilient["sheds"][BAND_CRITICAL] == 0
    assert resilient["brownout_goodput"]["critical"] >= \
        control["brownout_goodput"]["critical"]
    assert resilient["breaker_opened"] and resilient["breaker_reclosed"]
