#!/usr/bin/env python3
"""Headline benchmark: ResNet-50 training throughput on TPU.

The reference's benchmark workload is tf_cnn_benchmarks ResNet-50
(`--model=resnet50 --batch_size=32 --variable_update=parameter_server`,
tf-controller-examples/tf-cnn/create_job_specs.py:101-121) with synthetic
data. This is the same workload on the TPU-native stack: bf16 ResNet-50
v1.5, pjit train step, synthetic input (input pipeline off the critical
path, matching the tf_cnn_benchmarks synthetic-data methodology).

Prints ONE JSON line:
  {"metric": "resnet50_train_mfu", "value": <mfu>, "unit": "fraction",
   "vs_baseline": <mfu / 0.60>, ...extras}

vs_baseline is measured against the north-star target of 60% MFU
(BASELINE.json: "ResNet-50 ... at >=60% MFU"), since the reference
publishes no absolute numbers (BASELINE.md).
"""

import argparse
import json
import logging
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=256,
                   help="global batch (per-chip here; reference used 32/GPU worker)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--model", default="resnet50")
    args = p.parse_args()

    logging.basicConfig(level=logging.WARNING)

    import jax

    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.metrics import StepMeter, peak_flops
    from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer

    devs = jax.devices()
    kind = devs[0].device_kind
    on_tpu = devs[0].platform in ("tpu", "axon")

    cfg = TrainConfig.from_dict(dict(
        model=args.model,
        task="classification",
        global_batch=args.batch,
        image_size=args.image_size,
        num_classes=1000,
        mesh=MeshSpec(data=len(devs)),
        optimizer="sgdm",
        learning_rate=0.1,
        total_steps=args.steps,
        warmup_steps=5,
        log_every=10**9,  # quiet
    ))
    trainer = Trainer(cfg)
    state = trainer.init_state()
    data = trainer.data_iter()
    from kubeflow_tpu.runtime.data import shard_batch

    # Resident device batch: synthetic-data methodology measures device
    # throughput, not host->device link speed.
    batch = shard_batch(next(data), next(iter(jax.tree.leaves(trainer.batch_shardings))))

    # warmup (includes compile; at least one step so `m` is bound and the
    # timed loop never pays compile). float() forces a device->host
    # readback, the only reliable sync point through remote-exec tunnels.
    for _ in range(max(1, args.warmup)):
        state, m = trainer.train_step(state, batch)
    _ = float(m["loss"])

    # Chained timing: dispatch all steps (each depends on the previous
    # state), sync once at the end. Avoids paying tunnel RTT per step.
    import time

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = trainer.train_step(state, batch)
    final_loss = float(m["loss"])
    elapsed = time.perf_counter() - t0

    meter = StepMeter(trainer.flops_per_step(), len(devs), kind)
    meter._times.append(elapsed / args.steps)
    mfu = meter.mfu
    assert final_loss == final_loss, "loss is NaN"
    result = {
        "metric": f"{args.model}_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction",
        "vs_baseline": round(mfu / 0.60, 4),
        "images_per_sec": round(meter.throughput(args.batch), 1),
        "step_time_ms": round(meter.step_time * 1e3, 2),
        "global_batch": args.batch,
        "device": kind,
        "n_devices": len(devs),
        "peak_flops_per_chip": peak_flops(kind),
        "on_tpu": on_tpu,
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
