"""Declarative IAM binding patches — scripts/gke/iam_patch.py rebuilt.

The reference script shells out to `gcloud projects get-iam-policy`,
merges a declarative bindings YAML into it with retry-on-conflict, and
`set-iam-policy`s the result (iam_patch.py:12-17 usage header). Here the
merge is cloudauth.update_policy (gcpUtils.go:70 semantics, shared with
the tpctl plane) and the cloud calls go through a CrmBackend — the
stdlib HttpCrmBackend in production, injectable for tests.

Usage:
  python -m kubeflow_tpu.tpctl.iam_patch --action=add --project=p \
      --bindings-file=bindings.yaml --token-file=token.txt
bindings.yaml:
  bindings:
    - members: [set-kubeflow-iap-account, user:x@y.com]
      roles: [roles/iap.httpsResourceAccessor]
"""

from __future__ import annotations

import argparse
import logging
import sys
import time
from typing import Callable

from kubeflow_tpu.tpctl import cloudauth

log = logging.getLogger("kubeflow_tpu.iam_patch")


def load_bindings(path: str) -> list[dict]:
    try:
        import yaml  # type: ignore

        with open(path) as f:
            doc = yaml.safe_load(f)
    except ImportError:  # minimal fallback parser
        from kubeflow_tpu.utils.yaml_lite import loads as yloads

        with open(path) as f:
            doc = yloads(f.read())
    bindings = (doc or {}).get("bindings")
    if not isinstance(bindings, list):
        raise ValueError(f"{path}: expected top-level 'bindings' list")
    return bindings


def patch_iam_policy(
    project: str,
    token: str,
    bindings: list[dict],
    backend: cloudauth.CrmBackend,
    *,
    action: str = "add",
    cluster: str = "",
    email: str = "",
    retries: int = 5,
    sleep: Callable[[float], None] = time.sleep,
) -> dict:
    """Get-merge-set with retry (the reference retries the whole cycle on
    set conflicts, iam_patch.py's loop). Returns the final policy."""
    if action not in ("add", "remove"):
        raise ValueError(f"action must be add|remove, got {action!r}")
    if retries < 1:
        raise ValueError(f"retries must be >= 1, got {retries}")
    last_err: Exception | None = None
    for attempt in range(retries):
        policy = backend.get_iam_policy(project, token)
        updated = cloudauth.update_policy(
            policy, bindings, cluster=cluster, project=project, email=email,
            action=action)
        try:
            backend.set_iam_policy(project, token, updated)
            return updated
        except Exception as e:  # concurrent editor: re-read and re-merge
            if cloudauth.is_auth_rejection(e):
                raise  # permission denied is not a merge conflict
            last_err = e
            log.warning("set-iam-policy attempt %d failed: %s", attempt + 1, e)
            sleep(min(2.0 * (attempt + 1), 10.0))
    raise last_err  # type: ignore[misc]


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--action", default="add", choices=["add", "remove"])
    p.add_argument("--project", required=True)
    p.add_argument("--bindings-file", required=True)
    p.add_argument("--token-file", required=True,
                   help="file containing the OAuth bearer token")
    p.add_argument("--cluster", default="")
    p.add_argument("--email", default="")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    token = open(args.token_file).read().strip()
    bindings = load_bindings(args.bindings_file)
    backend = cloudauth.HttpCrmBackend()
    policy = patch_iam_policy(args.project, token, bindings, backend,
                              action=args.action, cluster=args.cluster,
                              email=args.email)
    log.info("policy now has %d bindings", len(policy.get("bindings", [])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
