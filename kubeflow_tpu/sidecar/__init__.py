"""TPU job lifecycle sidecar (reference: components/openmpi-controller)."""

from kubeflow_tpu.sidecar.controller import SidecarController  # noqa: F401
