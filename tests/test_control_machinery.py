"""K8s API machinery + controller engine tests.

Mirrors the unit tier of the reference (SURVEY.md §4 tier 1): fake-client
driven controller semantics, here against the in-memory FakeCluster.
"""

import pytest

from kubeflow_tpu.control import reconcilehelper as rh
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.runtime import Controller, Reconciler, Request, Result, seed_controller


def make_pod(name, ns="default", labels=None, phase="Pending"):
    pod = ob.new_object("v1", "Pod", name, ns, labels=labels, spec={"containers": []})
    pod["status"] = {"phase": phase}
    return pod


class TestObjects:
    def test_label_selector(self):
        labels = {"app": "nb", "tier": "web"}
        assert ob.match_labels(labels, {"matchLabels": {"app": "nb"}})
        assert not ob.match_labels(labels, {"matchLabels": {"app": "x"}})
        assert ob.match_labels(labels, None)
        sel = {"matchExpressions": [{"key": "tier", "operator": "In", "values": ["web", "db"]}]}
        assert ob.match_labels(labels, sel)
        sel = {"matchExpressions": [{"key": "zone", "operator": "DoesNotExist"}]}
        assert ob.match_labels(labels, sel)

    def test_parse_label_selector(self):
        sel = ob.parse_label_selector("a=b, c!=d, e")
        assert sel["matchLabels"] == {"a": "b"}
        ops = {(x["key"], x["operator"]) for x in sel["matchExpressions"]}
        assert ops == {("c", "NotIn"), ("e", "Exists")}

    def test_conditions_transition_time(self):
        obj = {}
        assert ob.cond_set(obj, "Running", "True", "Started")
        t1 = ob.cond_get(obj, "Running")["lastTransitionTime"]
        # same status → no transition change
        ob.cond_set(obj, "Running", "True", "StillGoing")
        assert ob.cond_get(obj, "Running")["lastTransitionTime"] == t1
        assert ob.cond_get(obj, "Running")["reason"] == "StillGoing"
        assert ob.cond_is_true(obj, "Running")

    def test_json_patch(self):
        doc = {"spec": {"containers": [{"env": [{"name": "A", "value": "1"}]}]}}
        out = ob.json_patch(
            doc,
            [
                {"op": "add", "path": "/spec/containers/0/env/-",
                 "value": {"name": "B", "value": "2"}},
                {"op": "replace", "path": "/spec/containers/0/env/0/value", "value": "9"},
            ],
        )
        envs = out["spec"]["containers"][0]["env"]
        assert envs == [{"name": "A", "value": "9"}, {"name": "B", "value": "2"}]
        assert doc["spec"]["containers"][0]["env"][0]["value"] == "1"  # original untouched

    def test_merge_patch_null_deletes(self):
        out = ob.merge_patch({"a": {"b": 1, "c": 2}}, {"a": {"b": None, "d": 3}})
        assert out == {"a": {"c": 2, "d": 3}}


class TestFakeCluster:
    def test_crud_and_rv_conflict(self):
        c = FakeCluster()
        pod = c.create(make_pod("p1"))
        assert ob.meta(pod)["uid"]
        stale = ob.deep_copy(pod)
        pod["spec"]["containers"] = [{"name": "x"}]
        c.update(pod)
        stale["spec"]["containers"] = [{"name": "y"}]
        with pytest.raises(ob.Conflict):
            c.update(stale)

    def test_duplicate_create_conflicts(self):
        c = FakeCluster()
        c.create(make_pod("p1"))
        with pytest.raises(ob.Conflict):
            c.create(make_pod("p1"))

    def test_generation_bumps_on_spec_change_only(self):
        c = FakeCluster()
        nb = c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "default",
                                    spec={"image": "a"}))
        assert ob.meta(nb)["generation"] == 1
        nb["status"] = {"readyReplicas": 1}
        nb = c.update_status(nb)
        assert ob.meta(nb)["generation"] == 1
        nb["spec"]["image"] = "b"
        nb = c.update(nb)
        assert ob.meta(nb)["generation"] == 2

    def test_update_status_subresource_isolated(self):
        c = FakeCluster()
        nb = c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "default",
                                    spec={"image": "a"}))
        mutated = ob.deep_copy(nb)
        mutated["spec"]["image"] = "EVIL"
        mutated["status"] = {"phase": "Ready"}
        c.update_status(mutated)
        got = c.get("kubeflow.org/v1beta1", "Notebook", "n", "default")
        assert got["spec"]["image"] == "a"
        assert got["status"]["phase"] == "Ready"

    def test_list_selectors(self):
        c = FakeCluster()
        c.create(make_pod("a", labels={"job": "j1"}))
        c.create(make_pod("b", labels={"job": "j2"}))
        c.create(make_pod("c", ns="other", labels={"job": "j1"}))
        assert len(c.list("v1", "Pod")) == 3
        assert len(c.list("v1", "Pod", namespace="default")) == 2
        assert [ob.meta(p)["name"] for p in c.list("v1", "Pod", label_selector="job=j1",
                                                   namespace="default")] == ["a"]
        c.patch("v1", "Pod", "a", {"status": {"phase": "Running"}}, "default")
        running = c.list("v1", "Pod", field_selector={"status.phase": "Running"})
        assert [ob.meta(p)["name"] for p in running] == ["a"]

    def test_finalizer_blocks_deletion(self):
        c = FakeCluster()
        prof = ob.new_object("kubeflow.org/v1", "Profile", "team-a", spec={"owner": "u"})
        ob.meta(prof)["finalizers"] = ["profile-finalizer"]
        prof = c.create(prof)
        c.delete("kubeflow.org/v1", "Profile", "team-a")
        got = c.get("kubeflow.org/v1", "Profile", "team-a")
        assert "deletionTimestamp" in ob.meta(got)
        c.remove_finalizer(got, "profile-finalizer")
        assert c.get_or_none("kubeflow.org/v1", "Profile", "team-a") is None

    def test_owner_gc_cascade(self):
        c = FakeCluster()
        job = c.create(ob.new_object("kubeflow.org/v1alpha1", "JAXJob", "j", "default",
                                     spec={}))
        pod = make_pod("j-worker-0")
        ob.set_owner(pod, job)
        c.create(pod)
        svc = ob.new_object("v1", "Service", "j", "default", spec={"clusterIP": "None"})
        ob.set_owner(svc, job)
        c.create(svc)
        c.delete("kubeflow.org/v1alpha1", "JAXJob", "j", "default")
        assert c.get_or_none("v1", "Pod", "j-worker-0", "default") is None
        assert c.get_or_none("v1", "Service", "j", "default") is None

    def test_create_after_owner_delete_is_garbage_collected(self):
        """The reconcile-vs-delete window the happens-before tracer
        exposed: a child created with an ownerReference to an
        already-deleted owner must be reaped immediately (kube GC
        semantics), with watchers seeing ADDED then DELETED."""
        c = FakeCluster()
        job = c.create(ob.new_object("kubeflow.org/v1alpha1", "JAXJob", "j",
                                     "default", spec={}))
        stream = c.watch("v1", "Pod", "default")
        c.delete("kubeflow.org/v1alpha1", "JAXJob", "j", "default")
        pod = make_pod("j-worker-0")
        ob.set_owner(pod, job)
        c.create(pod)
        assert c.get_or_none("v1", "Pod", "j-worker-0", "default") is None
        seen = []
        while True:
            ev = stream.poll()
            if ev is None:
                break
            seen.append(ev.type)
        assert seen == ["ADDED", "DELETED"]

    def test_dangling_owner_ref_pruned_with_rv_bump_and_event(self):
        """Partial prune (one live owner, one dangling) must keep the
        child but bump resourceVersion and emit MODIFIED like every
        other mutation path, or watcher caches go stale forever."""
        c = FakeCluster()
        live = c.create(ob.new_object("v1", "ConfigMap", "live", "default"))
        dead = c.create(ob.new_object("v1", "ConfigMap", "dead", "default"))
        c.delete("v1", "ConfigMap", "dead", "default")
        stream = c.watch("v1", "Secret", "default")
        child = ob.new_object("v1", "Secret", "kid", "default")
        ob.meta(child)["ownerReferences"] = [
            {"uid": ob.meta(live)["uid"], "kind": "ConfigMap",
             "name": "live"},
            {"uid": ob.meta(dead)["uid"], "kind": "ConfigMap",
             "name": "dead"},
        ]
        c.create(child)
        got = c.get("v1", "Secret", "kid", "default")
        refs = [r["name"] for r in ob.meta(got)["ownerReferences"]]
        assert refs == ["live"]
        seen = []
        while True:
            ev = stream.poll()
            if ev is None:
                break
            seen.append(ev)
        assert [ev.type for ev in seen] == ["ADDED", "MODIFIED"]
        # the prune bumped the rv past the ADDED event's, so a watcher
        # cache rebuilt from the stream can never resurrect 'dead'
        assert int(ob.meta(seen[1].object)["resourceVersion"]) > int(
            ob.meta(seen[0].object)["resourceVersion"])
        assert [r["name"] for r in
                ob.meta(seen[1].object)["ownerReferences"]] == ["live"]

    def test_watch_stream(self):
        c = FakeCluster()
        w = c.watch("v1", "Pod", namespace="default")
        c.create(make_pod("p"))
        c.create(make_pod("q", ns="other"))  # filtered out
        ev = w.poll()
        assert ev.type == "ADDED" and ob.meta(ev.object)["name"] == "p"
        assert w.poll() is None
        w.stop()

    def test_admission_hook_on_create(self):
        c = FakeCluster()

        def inject(verb, obj):
            if verb == "CREATE" and obj["kind"] == "Pod":
                obj.setdefault("metadata", {}).setdefault("annotations", {})["mutated"] = "yes"
            return obj

        c.add_admission_hook(inject)
        pod = c.create(make_pod("p"))
        assert ob.annotations_of(pod)["mutated"] == "yes"

    def test_events(self):
        c = FakeCluster()
        nb = c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "default", spec={}))
        c.record_event(nb, "Created", "statefulset created")
        evs = c.list("v1", "Event", namespace="default")
        assert len(evs) == 1
        assert evs[0]["involvedObject"]["name"] == "n"


class TestReconcileHelper:
    def test_service_preserves_cluster_ip(self):
        c = FakeCluster()
        owner = c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "default",
                                       spec={}))
        desired = ob.new_object("v1", "Service", "n", "default",
                                spec={"ports": [{"port": 80}], "selector": {"app": "n"}})
        created = rh.reconcile_child(c, owner, desired)
        created["spec"]["clusterIP"] = "10.0.0.7"  # simulate allocation
        c.update(created)
        # change desired ports; clusterIP must survive the update
        desired2 = ob.new_object("v1", "Service", "n", "default",
                                 spec={"ports": [{"port": 8080}], "selector": {"app": "n"}})
        updated = rh.reconcile_child(c, owner, desired2)
        assert updated["spec"]["clusterIP"] == "10.0.0.7"
        assert updated["spec"]["ports"] == [{"port": 8080}]

    def test_statefulset_copies_replicas_and_template_only(self):
        c = FakeCluster()
        owner = c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "default",
                                       spec={}))
        desired = ob.new_object("apps/v1", "StatefulSet", "n", "default",
                                spec={"replicas": 1, "template": {"spec": {"c": 1}},
                                      "serviceName": "n"})
        found = rh.reconcile_child(c, owner, desired)
        # cluster adds a field the controller must not fight over
        found["spec"]["podManagementPolicy"] = "OrderedReady"
        c.update(found)
        desired["spec"]["replicas"] = 0  # culling scale-to-zero
        updated = rh.reconcile_child(c, owner, desired)
        assert updated["spec"]["replicas"] == 0
        assert updated["spec"]["podManagementPolicy"] == "OrderedReady"

    def test_idempotent_no_update(self):
        c = FakeCluster()
        owner = c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "default",
                                       spec={}))
        desired = ob.new_object("v1", "Service", "n", "default", spec={"ports": [{"port": 80}]})
        first = rh.reconcile_child(c, owner, ob.deep_copy(desired))
        rv = ob.meta(first)["resourceVersion"]
        second = rh.reconcile_child(c, owner, ob.deep_copy(desired))
        assert ob.meta(second)["resourceVersion"] == rv  # no write happened


class _CountingReconciler(Reconciler):
    def __init__(self):
        self.seen = []
        self.requeue_once = set()

    def reconcile(self, client, req):
        self.seen.append(req)
        if req in self.requeue_once:
            self.requeue_once.discard(req)
            return Result(requeue_after=60.0)
        return None


class TestControllerEngine:
    def test_primary_and_owns_dispatch(self):
        c = FakeCluster()
        rec = _CountingReconciler()
        ctl = Controller("jaxjob", c, rec).watches_primary(
            "kubeflow.org/v1alpha1", "JAXJob").owns("v1", "Pod")
        seed_controller(ctl)
        job = c.create(ob.new_object("kubeflow.org/v1alpha1", "JAXJob", "j", "ns1", spec={}))
        ctl.run_until_idle()
        assert Request("ns1", "j") in rec.seen
        rec.seen.clear()
        pod = make_pod("j-w-0", ns="ns1")
        ob.set_owner(pod, job)
        c.create(pod)
        ctl.run_until_idle()
        assert rec.seen == [Request("ns1", "j")]  # owned pod maps to owner

    def test_requeue_after_advance(self):
        c = FakeCluster()
        rec = _CountingReconciler()
        ctl = Controller("nb", c, rec).watches_primary("kubeflow.org/v1beta1", "Notebook")
        seed_controller(ctl)
        c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "ns", spec={}))
        rec.requeue_once.add(Request("ns", "n"))
        ctl.run_until_idle()
        assert len(rec.seen) == 1
        ctl.run_until_idle(advance_delayed=True)  # fast-forward the 60s requeue
        assert len(rec.seen) == 2

    def test_error_retry(self):
        c = FakeCluster()

        class Flaky(Reconciler):
            def __init__(self):
                self.calls = 0

            def reconcile(self, client, req):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("transient")

        rec = Flaky()
        ctl = Controller("x", c, rec).watches_primary("kubeflow.org/v1beta1", "Notebook")
        seed_controller(ctl)
        c.create(ob.new_object("kubeflow.org/v1beta1", "Notebook", "n", "ns", spec={}))
        ctl.run_until_idle(advance_delayed=True)
        ctl.run_until_idle(advance_delayed=True)
        assert rec.calls >= 2
