"""RestClient against a real HTTP apiserver (VERDICT r1 weak #5).

Every other control-plane test talks to FakeCluster in-process; here the
same store is served over HTTP (control/k8s/apiserver.py) and driven
through RestClient — the client-go analogue controllers use on a live
cluster. Covers the claims rest.py makes: CRUD verbs, status subresource,
merge/json patch, label/field selectors, 404/409 mapping, chunked watch
streams, and a controller running identically on both backends.
"""

import threading
import time

import pytest

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller, worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.apiserver import ApiServer, client_for, parse_api_path
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.runtime import seed_controller


@pytest.fixture()
def server():
    s = ApiServer().serve_background()
    yield s
    s.shutdown()


@pytest.fixture()
def client(server):
    return client_for(server)


def wait_for(fn, timeout=10.0, period=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(period)
    raise TimeoutError("condition not met")


class TestPathParsing:
    def test_core_namespaced(self):
        p = parse_api_path("/api/v1/namespaces/ns1/pods/p1")
        assert (p.api_version, p.kind, p.namespace, p.name) == \
            ("v1", "Pod", "ns1", "p1")

    def test_group_crd_with_status(self):
        p = parse_api_path(
            "/apis/kubeflow.org/v1/namespaces/ns1/jaxjobs/j/status")
        assert p.api_version == "kubeflow.org/v1"
        assert (p.kind, p.name, p.subresource) == ("JAXJob", "j", "status")

    def test_cluster_scoped(self):
        p = parse_api_path("/apis/kubeflow.org/v1/profiles/team-a")
        assert (p.kind, p.namespace, p.name) == ("Profile", None, "team-a")

    def test_unknown_plural_rejected(self):
        with pytest.raises(LookupError):
            parse_api_path("/api/v1/frobnicators")


class TestCrudOverHttp:
    def test_create_get_roundtrip(self, client):
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"k": "v"}
        client.create(cm)
        got = client.get("v1", "ConfigMap", "cm", "default")
        assert got["data"] == {"k": "v"}
        assert ob.meta(got)["resourceVersion"]

    def test_get_missing_raises_notfound(self, client):
        with pytest.raises(ob.NotFound):
            client.get("v1", "ConfigMap", "nope", "default")
        assert client.get_or_none("v1", "ConfigMap", "nope", "default") is None

    def test_create_duplicate_raises_conflict(self, client):
        obj = ob.new_object("v1", "ConfigMap", "cm", "default")
        client.create(obj)
        with pytest.raises(ob.Conflict):
            client.create(obj)

    def test_update_and_stale_rv_conflict(self, client):
        """The optimistic-concurrency 409 path controllers rely on."""
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"v": "1"}
        client.create(cm)
        fresh = client.get("v1", "ConfigMap", "cm", "default")
        stale = ob.deep_copy(fresh)
        fresh["data"]["v"] = "2"
        client.update(fresh)
        stale["data"]["v"] = "3"
        with pytest.raises(ob.Conflict):
            client.update(stale)

    def test_status_subresource_does_not_touch_spec(self, client):
        client.create(JT.new_jaxjob("j1", replicas=1))
        job = client.get(JT.API_VERSION, JT.KIND, "j1", "default")
        job["status"] = {"conditions": [{"type": "Created", "status": "True"}]}
        job["spec"]["replicas"] = 99  # must be ignored by /status
        client.update_status(job)
        got = client.get(JT.API_VERSION, JT.KIND, "j1", "default")
        assert got["status"]["conditions"][0]["type"] == "Created"
        assert got["spec"]["replicas"] == 1

    def test_merge_and_json_patch(self, client):
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"a": "1"}
        client.create(cm)
        client.patch("v1", "ConfigMap", "cm", {"data": {"b": "2"}}, "default")
        got = client.get("v1", "ConfigMap", "cm", "default")
        assert got["data"] == {"a": "1", "b": "2"}
        client.patch("v1", "ConfigMap", "cm",
                     [{"op": "remove", "path": "/data/a"}], "default")
        got = client.get("v1", "ConfigMap", "cm", "default")
        assert got["data"] == {"b": "2"}

    def test_delete(self, client):
        client.create(ob.new_object("v1", "ConfigMap", "cm", "default"))
        client.delete("v1", "ConfigMap", "cm", "default")
        assert client.get_or_none("v1", "ConfigMap", "cm", "default") is None

    def test_list_with_selectors(self, client):
        for i, role in enumerate(["web", "web", "db"]):
            client.create(ob.new_object("v1", "Pod", f"p{i}", "default",
                                        labels={"role": role}))
        assert len(client.list("v1", "Pod", "default")) == 3
        web = client.list("v1", "Pod", "default",
                          label_selector={"matchLabels": {"role": "web"}})
        assert {ob.meta(p)["name"] for p in web} == {"p0", "p1"}
        by_name = client.list("v1", "Pod", "default",
                              field_selector={"metadata.name": "p2"})
        assert len(by_name) == 1
        # list items get apiVersion/kind backfilled (apiserver omits them)
        assert by_name[0]["kind"] == "Pod"

    def test_cluster_scoped_objects(self, client):
        client.create(ob.new_object("v1", "Namespace", "team-x"))
        assert client.get("v1", "Namespace", "team-x")["kind"] == "Namespace"


class TestWatchOverHttp:
    def test_watch_streams_added_and_modified(self, client, server):
        stream = client.watch("v1", "ConfigMap", "default")
        events = []
        got_two = threading.Event()

        def consume():
            for ev in stream:
                events.append(ev)
                if len(events) >= 2:
                    got_two.set()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # let the watch connect
        cm = ob.new_object("v1", "ConfigMap", "cm", "default")
        cm["data"] = {"v": "1"}
        client.create(cm)
        obj = client.get("v1", "ConfigMap", "cm", "default")
        obj["data"]["v"] = "2"
        client.update(obj)
        assert got_two.wait(10.0), f"saw only {events}"
        stream.stop()
        assert [e.type for e in events[:2]] == ["ADDED", "MODIFIED"]
        assert events[1].object["data"]["v"] == "2"


class TestControllerOverHttp:
    def test_jaxjob_gang_identical_on_both_backends(self, server, client):
        """VERDICT 'done' bar: one controller test passing identically on
        FakeCluster and RestClient backends."""
        # -- HTTP backend: production run() mode (threads + watch streams)
        ctl = build_controller(client)
        ctl.run(workers=1)
        try:
            client.create(JT.new_jaxjob("train", replicas=2,
                                        accelerator="tpu-v5-lite-podslice",
                                        topology="2x4"))
            pods = wait_for(
                lambda: (lambda ps: ps if len(ps) == 2 else None)(
                    client.list("v1", "Pod", "default")))
        finally:
            ctl.stop()
        http_names = {ob.meta(p)["name"] for p in pods}

        # -- in-process FakeCluster backend: hermetic drain mode
        fake = FakeCluster()
        fctl = seed_controller(build_controller(fake))
        fake.create(JT.new_jaxjob("train", replicas=2,
                                  accelerator="tpu-v5-lite-podslice",
                                  topology="2x4"))
        for _ in range(6):
            fctl.run_until_idle(advance_delayed=True)
        fake_names = {ob.meta(p)["name"]
                      for p in fake.list("v1", "Pod", namespace="default")}

        assert http_names == fake_names == {worker_name("train", i)
                                            for i in range(2)}
        # env contract survives the HTTP round trip
        pod = client.get("v1", "Pod", worker_name("train", 1), "default")
        env = {e["name"]: e["value"]
               for e in pod["spec"]["containers"][0]["env"]}
        assert env[JT.ENV_NPROC] == "2"


class TestLeaderElectionOverHttp:
    def test_two_electors_through_rest_client(self, server):
        """Leader election over the real HTTP wire: JSON-serialized
        MicroTime strings, 409 arbitration between two RestClients."""
        from kubeflow_tpu.control.k8s.rest import RestClient
        from kubeflow_tpu.control.leases import LeaderElector

        t = {"now": 5000.0}
        a = LeaderElector(RestClient(base_url=server.url),
                          "nb-controller", identity="pod-a",
                          clock=lambda: t["now"])
        b = LeaderElector(RestClient(base_url=server.url),
                          "nb-controller", identity="pod-b",
                          clock=lambda: t["now"])
        assert a.try_acquire() is True
        assert b.try_acquire() is False
        t["now"] += 16  # expiry -> takeover over HTTP
        assert b.try_acquire() is True
        assert a.try_acquire() is False
        b.release()
        assert a.try_acquire() is True
