"""JAXJob controller semantics against the fake cluster.

The behaviors the reference delegated to the external tf-operator +
launcher.py, specified by their consumers (SURVEY.md §3.2): gang pod
creation, env-var topology injection, condition lifecycle matching the
katib polling contract, and gang restart (which the reference's
per-replica restartPolicy never provided).
"""

import pytest

from kubeflow_tpu.control.jaxjob import types as T
from kubeflow_tpu.control.jaxjob.controller import build_controller, worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.runtime import seed_controller


@pytest.fixture()
def world():
    cluster = FakeCluster()
    ctl = seed_controller(build_controller(cluster, record_events=True))
    kubelet = FakeKubelet(cluster)
    return cluster, ctl, kubelet


def drain(ctl):
    # a few advance rounds so requeue_after paths fire without sleeping
    for _ in range(6):
        ctl.run_until_idle(advance_delayed=True)


def make_job(cluster, **kw):
    job = T.new_jaxjob("train", replicas=kw.pop("replicas", 4),
                       accelerator=kw.pop("accelerator", "tpu-v5-lite-podslice"),
                       topology=kw.pop("topology", "2x4"), **kw)
    return cluster.create(job)


class TestGangCreation:
    def test_creates_service_and_full_gang(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=4)
        drain(ctl)
        svc = cluster.get("v1", "Service", "train", "default")
        assert svc["spec"]["clusterIP"] == "None"
        pods = cluster.list("v1", "Pod", namespace="default")
        assert len(pods) == 4
        names = {ob.meta(p)["name"] for p in pods}
        assert names == {worker_name("train", i) for i in range(4)}

    def test_env_injection_contract(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=2)
        drain(ctl)
        pod1 = cluster.get("v1", "Pod", worker_name("train", 1), "default")
        env = {e["name"]: e["value"] for e in pod1["spec"]["containers"][0]["env"]}
        assert env[T.ENV_COORD] == "train-worker-0.train.default.svc:8476"
        assert env[T.ENV_NPROC] == "2"
        assert env[T.ENV_PID] == "1"
        assert env[T.ENV_NAME] == "train"
        # stable DNS wiring
        assert pod1["spec"]["hostname"] == "train-worker-1"
        assert pod1["spec"]["subdomain"] == "train"

    def test_tpu_resources_and_node_selectors(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=1)
        drain(ctl)
        pod = cluster.get("v1", "Pod", worker_name("train", 0), "default")
        limits = pod["spec"]["containers"][0]["resources"]["limits"]
        assert limits[T.RESOURCE_TPU] == 4
        sel = pod["spec"]["nodeSelector"]
        assert sel[T.NODESELECTOR_ACCEL] == "tpu-v5-lite-podslice"
        assert sel[T.NODESELECTOR_TOPOLOGY] == "2x4"

    def test_no_tpu_block_means_no_tpu_resources(self, world):
        cluster, ctl, _ = world
        job = T.new_jaxjob("cpu-job", replicas=1)
        cluster.create(job)
        drain(ctl)
        pod = cluster.get("v1", "Pod", worker_name("cpu-job", 0), "default")
        assert "resources" not in pod["spec"]["containers"][0] or (
            T.RESOURCE_TPU
            not in pod["spec"]["containers"][0].get("resources", {}).get("limits", {})
        )

    def test_validation_failure_sets_failed_condition(self, world):
        cluster, ctl, _ = world
        bad = T.new_jaxjob("bad", replicas=0)
        cluster.create(bad)
        drain(ctl)
        got = cluster.get(T.API_VERSION, T.KIND, "bad", "default")
        c = ob.cond_get(got, T.COND_FAILED)
        assert c and c["status"] == "True" and c["reason"] == "ValidationFailed"
        assert not cluster.list("v1", "Pod", namespace="default")


class TestLifecycle:
    def test_conditions_follow_pod_phases(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_CREATED)
        assert not ob.cond_is_true(job, T.COND_RUNNING)

        kubelet.step()  # Pending -> Running
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_RUNNING)
        assert job["status"]["replicaStatuses"]["active"] == 2
        assert "startTime" in job["status"]

        kubelet.succeed(worker_name("train", 0))
        kubelet.succeed(worker_name("train", 1))
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_SUCCEEDED)
        assert not ob.cond_is_true(job, T.COND_RUNNING)  # katib contract: flips off
        assert "completionTime" in job["status"]

    def test_events_recorded(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=1)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        reasons = {e["reason"] for e in cluster.list("v1", "Event", namespace="default")}
        assert "JAXJobCreated" in reasons
        assert "JAXJobRunning" in reasons

    def test_deleting_job_cascades_to_pods(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=2)
        drain(ctl)
        assert len(cluster.list("v1", "Pod", namespace="default")) == 2
        cluster.delete(T.API_VERSION, T.KIND, "train", "default")
        assert cluster.list("v1", "Pod", namespace="default") == []
        assert cluster.get_or_none("v1", "Service", "train", "default") is None


class TestGangRestart:
    def test_worker_failure_restarts_whole_gang(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=3)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        kubelet.fail(worker_name("train", 1))
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["restarts"] == 1
        # the whole gang was recreated: all pods fresh (Pending again)
        pods = cluster.list("v1", "Pod", namespace="default")
        assert len(pods) == 3
        assert all((p.get("status") or {}).get("phase", "Pending") == "Pending"
                   for p in pods)
        c = ob.cond_get(job, T.COND_RESTARTING)
        assert c and c["status"] == "True"

    def test_restart_never_policy_fails_immediately(self, world):
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("train", replicas=2, restart_policy=T.RESTART_NEVER)
        cluster.create(job)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        kubelet.fail(worker_name("train", 0))
        drain(ctl)
        got = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(got, T.COND_FAILED)
        assert got["status"].get("restarts", 0) == 0

    def test_restarts_exhaust_to_failed(self, world):
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("train", replicas=1, max_restarts=2)
        cluster.create(job)
        for i in range(3):
            drain(ctl)
            kubelet.step()
            drain(ctl)
            kubelet.fail(worker_name("train", 0))
            drain(ctl)
        got = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(got, T.COND_FAILED)
        assert got["status"]["restarts"] == 2

    def test_deleted_worker_triggers_gang_restart(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=3)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        cluster.delete("v1", "Pod", worker_name("train", 2), "default")
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["restarts"] >= 1
        assert len(cluster.list("v1", "Pod", namespace="default")) == 3


class TestIdempotency:
    def test_reconcile_is_idempotent(self, world):
        """The kfctl_second_apply.py analogue: re-reconciling a settled job
        changes nothing."""
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        pods_before = {
            ob.meta(p)["name"]: ob.meta(p)["resourceVersion"]
            for p in cluster.list("v1", "Pod", namespace="default")
        }
        from kubeflow_tpu.control.runtime import Request

        for _ in range(3):
            ctl.reconciler.reconcile(cluster, Request("default", "train"))
        pods_after = {
            ob.meta(p)["name"]: ob.meta(p)["resourceVersion"]
            for p in cluster.list("v1", "Pod", namespace="default")
        }
        assert pods_before == pods_after
