"""PodDefault mutating admission webhook.

Reference: components/admission-webhook (SURVEY.md §2.2) — label-matched
injection of env/envFrom/volumes/volumeMounts/tolerations/labels/
annotations into pods at admission time; how notebooks transparently get
secrets, tokens and volumes. The TPU build keeps the exact mechanism
(JSONPatch reply, conflict-safe merge) and uses it to inject TPU runtime
defaults (e.g. JAX_PLATFORMS, libtpu mounts) into notebook/job pods.
"""

from kubeflow_tpu.control.poddefault.webhook import (  # noqa: F401
    API_VERSION,
    KIND,
    PodDefaultMutator,
    new_poddefault,
)
