"""Static-hygiene tier — the testing/test_flake8.py analogue (SURVEY.md
§4 tier 3). No flake8 in the image, so the checks are stdlib: every
module compiles, no debugger hooks or conflict markers ship, public
modules carry docstrings. tools/ and examples/ ride the same gates
(syntax/debugger/marker only — round tooling may be terse), so a torn
watcher script or manifest can't silently rot between rounds."""

import ast
import os
import pathlib

import pytest

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kubeflow_tpu"

PY_FILES = sorted(
    p for p in PACKAGE.rglob("*.py")
    if "__pycache__" not in p.parts
) + [REPO / "bench.py", REPO / "__graft_entry__.py"]

# the test corpus and round tooling are lint-gated for the
# syntax/marker/debugger checks (not the docstring rule: helpers and
# one-off sweep scripts may be terse)
TEST_FILES = sorted(
    p for p in (REPO / "tests").rglob("*.py")
    if "__pycache__" not in p.parts
)
TOOL_FILES = sorted(
    p for p in (REPO / "tools").rglob("*.py")
    if "__pycache__" not in p.parts
)
EXAMPLE_FILES = sorted(
    p for pat in ("*.yaml", "*.yml")
    for p in (REPO / "examples").rglob(pat)
)


@pytest.mark.parametrize("path", PY_FILES + TEST_FILES + TOOL_FILES,
                         ids=lambda p: str(p.relative_to(REPO)))
def test_module_is_clean(path):
    """Syntax / debugger-hook / conflict-marker gates, delegated to the
    hygiene pass (kubeflow_tpu/analysis/hygiene.py) so pytest and
    tools/lint_all.sh enforce one implementation, not two drifting ones."""
    from kubeflow_tpu.analysis import hygiene

    findings = hygiene.check_py(str(path), path.read_text())
    assert not findings, "\n".join(f.render() for f in findings)


@pytest.mark.parametrize(
    "path",
    [p for p in PY_FILES if p.name != "__main__.py"],
    ids=lambda p: str(p.relative_to(REPO)),
)
def test_module_has_docstring(path):
    tree = ast.parse(path.read_text())
    assert ast.get_docstring(tree), f"{path}: missing module docstring"


@pytest.mark.parametrize("path", EXAMPLE_FILES,
                         ids=lambda p: str(p.relative_to(REPO)))
def test_example_manifest_is_clean(path):
    """examples/ manifests: parse as YAML, ship no conflict markers
    (the hygiene pass's yaml gate, enforced from pytest too)."""
    from kubeflow_tpu.analysis import hygiene

    src = path.read_text()
    findings = hygiene.check_yaml(str(path), src)
    assert not findings, "\n".join(f.render() for f in findings)
    assert src.strip(), f"{path}: empty manifest"


def test_no_reference_tree_imports():
    """The build must be standalone: nothing may import from or open
    /root/reference (the read-only upstream)."""
    for p in PY_FILES:
        assert "/root/reference" not in p.read_text(), p
