"""Attention entry point: one call site, backend chosen per platform.

The reference platform never owns attention math (it ships TF images);
for the TPU build it is in-scope. `attention()` routes to:

- the Pallas flash-attention kernel on TPU (fused, O(L) memory, MXU-tiled);
- a plain XLA einsum path elsewhere (tests on the virtual CPU mesh) and
  for shapes the kernel doesn't support.

All shapes are [batch, length, heads, head_dim] ("BLHD"), GQA supported by
passing fewer KV heads than Q heads.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, num_q_heads: int) -> jax.Array:
    """Broadcast KV heads up to Q heads for grouped-query attention."""
    num_kv = k.shape[2]
    if num_kv == num_q_heads:
        return k
    assert num_q_heads % num_kv == 0, (num_q_heads, num_kv)
    return jnp.repeat(k, num_q_heads // num_kv, axis=2)


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    segment_ids: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """XLA attention in f32 accumulation. BLHD in, BLHD out.
    window > 0 = sliding-window: query i attends keys in
    (i - window, i] (end-aligned like the causal mask)."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        mask = jnp.tril(jnp.ones((lq, lk), dtype=bool), k=lk - lq)
        logits = jnp.where(mask[None, None], logits, -1e30)
    if window > 0:
        qpos = jnp.arange(lq)[:, None] + (lk - lq)
        kpos = jnp.arange(lk)[None, :]
        near = qpos - kpos < window
        logits = jnp.where(near[None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.partial(jax.jit,
                   static_argnames=("causal", "impl", "block_q", "block_k",
                                    "window"))
def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "auto",
    segment_ids: jax.Array | None = None,
    block_q: int = 0,
    block_k: int = 0,
    window: int = 0,
) -> jax.Array:
    """Dispatching attention. impl: auto | flash | reference.

    segment_ids (sequence-packing masks) run through the Pallas kernel
    too — the reference path's [B, H, L, L] scores are unusable at
    training lengths (58 GB at seq 2048, BASELINE.md round 2).
    """
    if impl == "reference":
        return reference_attention(q, k, v, causal=causal,
                                   segment_ids=segment_ids, window=window)
    on_tpu = jax.devices()[0].platform == "tpu"
    if impl == "flash" or (impl == "auto" and on_tpu and _flash_supported(q, k)):
        import os

        from kubeflow_tpu.ops.flash_attention import (
            DEFAULT_BLOCK_Q,
            DEFAULT_BLOCK_K,
            flash_attention,
        )

        # kernel tile sizes: explicit args win (config-plumbed operating
        # points), else the env override (autotuning sweeps set it per
        # subprocess; read at trace time), else the swept default
        bq = block_q or int(os.environ.get("KFTPU_FLASH_BLOCK_Q",
                                           DEFAULT_BLOCK_Q))
        bk = block_k or int(os.environ.get("KFTPU_FLASH_BLOCK_K",
                                           DEFAULT_BLOCK_K))
        return flash_attention(q, k, v, causal=causal,
                               block_q=bq, block_k=bk,
                               segment_ids=segment_ids, window=window)
    return reference_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids, window=window)


def _flash_supported(q: jax.Array, k: jax.Array) -> bool:
    # kernel wants seq multiples of its block size and head_dim % 128 == 0
    d = q.shape[-1]
    return q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0 and d in (64, 128, 256)
