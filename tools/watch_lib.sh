# Shared watcher machinery: chip-yield protocol + stage ledger.
# Sourced by tools/round5_watch.sh and tools/round5b_watch.sh — the
# protocol lives in ONE place so a fix can never apply to one phase and
# silently miss the other (the round-4 -> round-5 protocol supersession
# happened exactly because each round's watcher was a diverging copy).
#
# Contract for sourcing scripts: set LOG and LEDGER first; optionally
# WATCH_TAG (log-line prefix). Provides note/extern_active/probe/
# run_stage and writes $$ to $PIDFILE for the handoff supervisor.
LOCK=/tmp/kftpu_extern_bench.lock
PIDFILE="${PIDFILE:-/tmp/kftpu_watch.pid}"
WATCH_TAG="${WATCH_TAG:-}"
mkdir -p "$LEDGER"
echo $$ > "$PIDFILE"

note() { echo "$(date -u +%H:%M:%S)${WATCH_TAG} $*" >> "$LOG"; }

# True iff an external bench's lockfile exists and its pid is alive.
# A stale lock (bench SIGKILLed before atexit) is removed on sight.
extern_active() {
  [ -e "$LOCK" ] || return 1
  local pid
  pid=$(cat "$LOCK" 2>/dev/null)
  if [ -n "$pid" ] && kill -0 "$pid" 2>/dev/null; then return 0; fi
  rm -f "$LOCK"
  return 1
}

probe() {
  extern_active && return 1
  timeout 90 env KFTPU_STAGE_RUN=1 \
    python -c "import jax; jax.devices()" >/dev/null 2>&1
}

# run NAME TIMEOUT CMD... — execute once, mark done on rc==0. Stage
# stdout/stderr goes to $LEDGER/$name.out and is appended to LOG.
# Yields the chip (killing the in-flight stage) within ~5s of an
# external bench taking the lock; a failure counts toward the 2-strike
# .skip only when deterministic (rc not a timeout kill AND a
# post-failure probe succeeds).
run_stage() {
  local name="$1" tmo="$2"; shift 2
  [ -e "$LEDGER/$name.done" ] && return 0
  [ -e "$LEDGER/$name.skip" ] && return 0
  if extern_active; then
    note "external bench holds the chip — yielding before $name"
    return 1
  fi
  if ! probe; then note "tunnel dropped before $name"; return 1; fi
  note "stage $name: $*"
  setsid env KFTPU_STAGE_RUN=1 timeout "$tmo" "$@" \
    > "$LEDGER/$name.out" 2>&1 &
  local pid=$!
  while kill -0 "$pid" 2>/dev/null; do
    if extern_active; then
      note "external bench appeared — killing in-flight stage $name"
      kill -TERM -- -"$pid" 2>/dev/null
      sleep 5
      kill -KILL -- -"$pid" 2>/dev/null
      wait "$pid" 2>/dev/null
      while extern_active; do sleep 10; done
      note "external bench finished — resuming"
      return 1  # yielded, not failed: no strike, stage re-runs next pass
    fi
    sleep 5
  done
  wait "$pid"
  local rc=$?
  if [ "$rc" -eq 0 ]; then
    touch "$LEDGER/$name.done"; note "stage $name DONE"
    cat "$LEDGER/$name.out" >> "$LOG"
    return 0
  fi
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    note "stage $name timed out (rc=$rc) — no strike"
  elif probe; then
    echo x >> "$LEDGER/$name.fail"
    if [ "$(wc -l < "$LEDGER/$name.fail")" -ge 2 ]; then
      mv "$LEDGER/$name.fail" "$LEDGER/$name.skip"
      note "stage $name FAILED twice deterministically (rc=$rc) — skipping"
    else
      note "stage $name FAILED (rc=$rc) — one deterministic retry left"
    fi
  else
    note "stage $name failed (rc=$rc) with the tunnel down — no strike"
  fi
  cat "$LEDGER/$name.out" >> "$LOG"
  return 1
}
