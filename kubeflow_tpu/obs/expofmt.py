"""Prometheus text-exposition (0.0.4) parsing — the ONE spelling.

Two consumers share this module: the JAXService autoscaler's
``RegistrySignals`` (serving/router.py) parsing a scraped ``/metrics``
body back into signals, and the fleet scrape plane
(``obs/tsdb.ScrapeLoop``) ingesting every target's exposition into the
TSDB. Hoisted out of ``RegistrySignals`` so the router and the scraper
cannot drift into two parsers with two sets of escaping bugs —
``tests/test_obs_plane.py`` pins both that the router has no leftover
inline parser and that parsing ``MetricsRegistry.render()`` output
round-trips the registry's own structured samples exactly.

The grammar is the subset our registries emit: ``# HELP``/``# TYPE``
comment lines, then ``name{label="value",...} number`` samples. Label
values reverse the writer's escaping (``\\``, ``\"``, ``\n`` —
``runtime/metrics.py:_escape_label``); values inside quotes may contain
commas and ``}``, which the naive ``split(",")`` parser this replaces
got wrong. Unparseable lines are SKIPPED, never raised: a scrape of a
half-written or foreign exposition must degrade to the samples it can
read (the Prometheus contract).
"""

from __future__ import annotations

import re
import struct
from dataclasses import dataclass
from typing import Iterator

# metric/series names (PromQL also allows ':' in recorded-rule names)
NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?\s*$")
_LABEL_RE = re.compile(
    r'\s*(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*'
    r'"(?P<value>(?:[^"\\]|\\.)*)"\s*(?:,|$)')
_UNESCAPE = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        pair = value[i:i + 2]
        if pair in _UNESCAPE:
            out.append(_UNESCAPE[pair])
            i += 2
        else:
            out.append(value[i])
            i += 1
    return "".join(out)


@dataclass(frozen=True)
class Sample:
    """One exposition sample. ``name`` is the SERIES name — a histogram
    family renders as distinct ``_bucket``/``_sum``/``_count`` series
    and stays that way here (the TSDB and PromQL-lite operate on
    series, exactly like Prometheus)."""

    name: str
    labels: tuple[tuple[str, str], ...]
    value: float

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)


def parse_labels(body: str) -> tuple[tuple[str, str], ...] | None:
    """``k1="v1",k2="v2"`` -> sorted tuple; None when malformed."""
    out: list[tuple[str, str]] = []
    pos = 0
    body = body.strip()
    while pos < len(body):
        m = _LABEL_RE.match(body, pos)
        if not m:
            return None
        out.append((m.group("key"), _unescape(m.group("value"))))
        pos = m.end()
    return tuple(sorted(out))


def parse_line(line: str) -> Sample | None:
    """One sample line -> Sample; None for comments/blank/garbage."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    m = _SAMPLE_RE.match(line)
    if not m:
        return None
    labels_body = m.group("labels")
    labels = parse_labels(labels_body) if labels_body else ()
    if labels is None:
        return None
    try:
        value = float(m.group("value"))
    except ValueError:
        return None
    return Sample(m.group("name"), labels, value)


def parse(text: str) -> Iterator[Sample]:
    """Every parseable sample in an exposition body, document order."""
    for line in text.splitlines():
        s = parse_line(line)
        if s is not None:
            yield s


def samples(text: str, name: str) -> list[tuple[dict, float]]:
    """All samples of ONE series name as ``(labels, value)`` pairs —
    the shape ``MetricsRegistry.series()`` returns, so a scraped-body
    signal source and the in-process fast path are interchangeable
    (``RegistrySignals`` consumes both)."""
    return [(s.labels_dict(), s.value) for s in parse(text)
            if s.name == name]


# The staleness marker is Prometheus's SPECIFIC NaN bit pattern
# (0x7ff0000000000002), not "any NaN": a target legitimately exporting
# `jaxrt_loss NaN` after divergence must stay visible as data — only
# the marker the TSDB itself wrote may hide a series.
STALE_NAN = struct.unpack("<d", struct.pack("<Q", 0x7ff0000000000002))[0]
_STALE_BITS = struct.pack("<d", STALE_NAN)


def is_stale(value: float) -> bool:
    """True only for the exact staleness bit pattern the TSDB writes —
    ordinary NaN data (which compares unequal to everything, including
    itself) is NOT stale."""
    try:
        return struct.pack("<d", value) == _STALE_BITS
    except (struct.error, TypeError):
        return False
