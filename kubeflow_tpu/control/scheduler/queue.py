"""The gang queue: priority + FIFO admission order with requeue backoff.

One entry per gang (namespace/job). ``ready()`` yields entries in strict
admission order — higher priority first, FIFO within a priority — and
gates each entry on its backoff deadline. A gang that failed admission
is ``requeue()``d with exponential backoff (base * 2^(attempts-1),
capped), so an unplaceable gang polls the cluster ever more slowly
instead of hammering it; ``remove()`` on admission drops the entry and
its backoff state.

The clock is injectable (tests drive a fake clock; production uses
time.monotonic). All state lives behind one lock: entries are frozen
dataclasses replaced wholesale under ``_lock``, the fresh-container
idiom the dyntrace happens-before validator (TPU_RACE_TRACE=1) can
observe and tpulint's LOCK201 lockset checker can prove.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time


@dataclasses.dataclass(frozen=True)
class Entry:
    """One queued gang. Frozen: updates replace the entry under the
    queue lock (never mutate in place)."""

    namespace: str
    name: str
    priority: int
    seq: int
    enqueued_at: float
    attempts: int = 0
    not_before: float = 0.0

    @property
    def key(self) -> tuple[str, str]:
        return (self.namespace, self.name)


class GangQueue:
    def __init__(
        self,
        clock=time.monotonic,
        base_backoff: float = 0.5,
        max_backoff: float = 30.0,
        jitter: float = 0.0,
        rng: random.Random | None = None,
    ):
        self.clock = clock
        self.base_backoff = base_backoff
        self.max_backoff = max_backoff
        # jitter spreads same-shaped gangs' retries apart (thundering-
        # herd control after a big node comes back); 0.0 (default) keeps
        # the schedule exactly pinnable in tests. rng injectable so a
        # seeded chaos run replays the same jittered schedule; the
        # default is seeded too (DET602), so enabling jitter without
        # wiring an rng still replays byte-identically.
        self.jitter = jitter
        self._rng = rng if rng is not None else random.Random(0)
        self._lock = threading.Lock()
        self._entries: dict[tuple[str, str], Entry] = {}
        # namespaces ever queued: keeps the queue-depth gauge reporting
        # an explicit 0 after a namespace drains (Prometheus semantics)
        self._namespaces: dict[str, None] = {}
        self._seq = 0

    def offer(self, namespace: str, name: str, priority: int = 0) -> Entry:
        """Add a gang (idempotent). A re-offer keeps the entry's seq and
        backoff state but tracks a changed priority."""
        now = self.clock()
        with self._lock:
            key = (namespace, name)
            cur = self._entries.get(key)
            if cur is None:
                self._seq += 1
                cur = Entry(namespace, name, priority, self._seq, now)
                self._entries[key] = cur
                self._namespaces[namespace] = None
            elif cur.priority != priority:
                cur = dataclasses.replace(cur, priority=priority)
                self._entries[key] = cur
            return cur

    def remove(self, namespace: str, name: str) -> None:
        with self._lock:
            self._entries.pop((namespace, name), None)

    def requeue(self, namespace: str, name: str) -> float:
        """Admission failed: back the gang off exponentially. Returns
        the delay until the entry is ready again (0.0 if unknown)."""
        now = self.clock()
        with self._lock:
            key = (namespace, name)
            cur = self._entries.get(key)
            if cur is None:
                return 0.0
            attempts = cur.attempts + 1
            delay = min(self.base_backoff * (2 ** (attempts - 1)),
                        self.max_backoff)
            if self.jitter > 0:
                delay *= 1.0 + self.jitter * self._rng.random()
            self._entries[key] = dataclasses.replace(
                cur, attempts=attempts, not_before=now + delay)
            return delay

    def kick(self) -> None:
        """Expire every entry's backoff deadline (keep attempt counts):
        new capacity just appeared, so waiting out the rest of an
        exponential delay would only idle the fleet. The next failed
        admission still backs off from the accumulated attempts."""
        with self._lock:
            for key, e in list(self._entries.items()):
                if e.not_before:
                    self._entries[key] = dataclasses.replace(
                        e, not_before=0.0)

    def kick_one(self, namespace: str, name: str) -> None:
        """Expire ONE gang's backoff: its own pod set just changed (a
        worker appeared or fell over), so retry on the new state now."""
        with self._lock:
            key = (namespace, name)
            cur = self._entries.get(key)
            if cur is not None and cur.not_before:
                self._entries[key] = dataclasses.replace(
                    cur, not_before=0.0)

    def ordered(self) -> list[Entry]:
        """ALL entries in admission order: priority descending, then
        FIFO (seq). The scheduling pass walks this so a backed-off head
        still blocks lower-priority gangs (strict FIFO) — backoff only
        paces the head's own retries, it never lets others jump it."""
        with self._lock:
            entries = list(self._entries.values())
        return sorted(entries, key=lambda e: (-e.priority, e.seq))

    def ready(self, now: float | None = None) -> list[Entry]:
        """Entries whose backoff has expired, in admission order."""
        if now is None:
            now = self.clock()
        return [e for e in self.ordered() if e.not_before <= now]

    def ordered_by_namespace(self) -> dict[str, list[Entry]]:
        """Admission order per namespace (the scheduling pass walks each
        namespace independently: one tenant's stuck head must not block
        another's admission)."""
        out: dict[str, list[Entry]] = {}
        for e in self.ordered():
            out.setdefault(e.namespace, []).append(e)
        return out

    def next_wakeup(self, now: float | None = None) -> float | None:
        """Seconds until the earliest backed-off entry becomes ready;
        None when nothing is waiting on a deadline."""
        if now is None:
            now = self.clock()
        with self._lock:
            future = [e.not_before for e in self._entries.values()
                      if e.not_before > now]
        if not future:
            return None
        return max(min(future) - now, 0.0)

    def get(self, namespace: str, name: str) -> Entry | None:
        with self._lock:
            return self._entries.get((namespace, name))

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def depths(self) -> dict[str, int]:
        """Queue depth per namespace, including 0 for just-drained ones.
        A drained namespace is reported at 0 once and then pruned — a
        fleet churning through ephemeral tenant namespaces must not
        grow this map (or the gauge's update set) forever."""
        with self._lock:
            out = {ns: 0 for ns in self._namespaces}
            for ns, _name in self._entries:
                out[ns] = out.get(ns, 0) + 1
            for ns, n in out.items():
                if n == 0:
                    self._namespaces.pop(ns, None)
            return out
