"""tpulint reporters: human text, machine JSON, SARIF, and the baseline
ratchet.

The JSON schema is versioned so round tooling (tools/lint_all.sh, CI
dashboards) can consume it without scraping: ``{"version": 1,
"count": N, "findings": [{rule, path, line, col, message}, ...]}``.

SARIF 2.1.0 output (``--format sarif``) lets CI upload findings as
code-scanning artifacts; the baseline helpers implement the ratchet —
``tools/lint_baseline.json`` pins today's findings, and a diff run
fails only on *new* ones, so a rule can tighten without a flag-day.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Iterable

from kubeflow_tpu.analysis.core import Finding

JSON_VERSION = 1
BASELINE_VERSION = 1
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def render_text(findings: Iterable[Finding]) -> str:
    """One `path:line:col: RULE message` per finding plus a summary."""
    findings = list(findings)
    lines = [f.render() for f in findings]
    if findings:
        by_rule = Counter(f.rule for f in findings)
        breakdown = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
        lines.append(f"tpulint: {len(findings)} finding"
                     f"{'s' if len(findings) != 1 else ''} ({breakdown})")
    else:
        lines.append("tpulint: clean")
    return "\n".join(lines)


def render_json(findings: Iterable[Finding]) -> str:
    findings = list(findings)
    return json.dumps({
        "version": JSON_VERSION,
        "count": len(findings),
        "findings": [f.to_dict() for f in findings],
    }, indent=2, sort_keys=True)


def _rule_meta(rule_id: str) -> str:
    """Short description for SARIF rule metadata (registry or hygiene)."""
    from kubeflow_tpu.analysis import hygiene
    from kubeflow_tpu.analysis.core import PARSE_RULE, REGISTRY, all_rules

    all_rules()  # ensure builtins are registered
    if rule_id in REGISTRY:
        return REGISTRY[rule_id].short
    if rule_id == PARSE_RULE:
        return "file does not parse"
    return hygiene.HYGIENE_RULES.get(rule_id, "")


def render_sarif(findings: Iterable[Finding]) -> str:
    """SARIF 2.1.0: one run, tool 'tpulint', result per finding. The
    shape GitHub code scanning (and most SARIF viewers) ingest."""
    findings = list(findings)
    rule_ids = sorted({f.rule for f in findings})
    return json.dumps({
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpulint",
                "informationUri": "docs/static-analysis.md",
                "rules": [{"id": rid,
                           "shortDescription": {"text": _rule_meta(rid)}}
                          for rid in rule_ids],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "warning",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                }}],
            } for f in findings],
        }],
    }, indent=2, sort_keys=True)


# -- baseline ratchet --------------------------------------------------------

def finding_key(f: Finding) -> tuple:
    return (f.rule, f.path, f.line, f.message)


def render_baseline(findings: Iterable[Finding]) -> str:
    keys = sorted(list(finding_key(f)) for f in findings)
    return json.dumps({"version": BASELINE_VERSION, "findings": keys},
                      indent=2) + "\n"


def load_baseline(text: str) -> Counter:
    doc = json.loads(text)
    return Counter(tuple(k) for k in doc.get("findings", []))


def new_findings(findings: Iterable[Finding], baseline: Counter
                 ) -> list[Finding]:
    """Findings not covered by the baseline (multiset semantics: two
    identical findings need two baseline entries)."""
    budget = Counter(baseline)
    out = []
    for f in findings:
        k = finding_key(f)
        if budget[k] > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out
