"""Execute the REAL web-UI JavaScript against the real backends.

Parity target: the reference drives its spawner through Selenium
(testing/test_jwa.py, 423 LoC of WebDriver against a live browser). This
container has no browser, so kubeflow_tpu/testing/jsdom.py rebuilds the
capability: the interpreter runs the exact `<script>` payloads served by
dashboard_ui.py / jwa_ui.py, with fetch() bridged into the same Router
objects production serves. Every flow below fails if the corresponding
UI JS breaks — the VERDICT #5 bar ("a test fails when the
registration-flow JS breaks").
"""

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.kfam.service import KfamService
from kubeflow_tpu.control.notebook import types as NT
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.testing.jsdom import Browser, JSObject, undefined
from kubeflow_tpu.webapps.dashboard import Dashboard
from kubeflow_tpu.webapps.dashboard_ui import PAGE as DASH_PAGE

USER = "alice@example.com"


def dash_browser(cluster) -> Browser:
    kfam = KfamService(cluster, cluster_admin="root@example.com")
    b = Browser(Dashboard(cluster, kfam=kfam).router())
    b.default_headers["kubeflow-userid"] = USER
    return b


class TestInterpreterCore:
    """Language-level sanity for the harness itself."""

    def test_core_semantics(self):
        b = Browser()
        b.load('<div id="out"></div>', run_scripts=False)
        b.run("""
          const xs = [3, 1, 2].map(x => x * 2).filter(x => x > 2);
          let s = `n=${xs.length}`;
          for (const [k, v] of Object.entries({a: 1})) s += ` ${k}${v}`;
          s += ' ' + (2 ** 10) + ' ' + (0.1).toFixed(2);
          s += ' ' + JSON.parse(JSON.stringify({z: [1, 2]})).z.join('-');
          document.getElementById('out').textContent = s;
        """)
        assert b.text("out") == "n=2 a1 1024 0.10 1-2"

    def test_async_await_and_rejection(self):
        b = Browser()
        b.load('<div id="out"></div>', run_scripts=False)
        b.run("""
          const api = () => Promise.reject(new Error('down'));
          async function go() {
            try { await api(); return 'unreachable'; }
            catch (e) { return 'caught:' + e.message; }
          }
          go().then(v => document.getElementById('out').textContent = v);
        """)
        assert b.text("out") == "caught:down"

    def test_unsupported_syntax_is_loud(self):
        from kubeflow_tpu.testing.jsdom import JSError

        b = Browser()
        with pytest.raises(JSError):
            b.run("class Foo { bar() {} }")


class TestDashboardRegistration:
    """The registration walkthrough — the reference's registration-page
    flow (centraldashboard public/components/registration-page.js)."""

    def test_fresh_user_sees_walkthrough_and_creates_profile(self):
        cluster = FakeCluster()
        b = dash_browser(cluster)
        b.load(DASH_PAGE)
        # no namespaces -> walkthrough visible at step 0
        assert b.by_id("register").style.get("display") == "block"
        steps = b.document.querySelectorAll("#register .step")
        active = [s.dataset.get("step") for s in steps
                  if "active" in s.className.split()]
        assert active == ["0"]

        b.click("reg-start")
        # invalid name: error shown, next disabled
        b.type_into("reg-ns", "Bad_Name!")
        assert b.text("reg-err") == "invalid namespace name"
        assert b.by_id("reg-next").disabled is True
        # valid name enables next
        b.type_into("reg-ns", "alice-ns")
        assert b.text("reg-err") == ""
        assert b.by_id("reg-next").disabled is False
        b.click("reg-next")
        assert b.text("reg-confirm-name") == "alice-ns"
        assert b.text("reg-confirm-user") == USER

        b.click("reg-create")
        # the REAL backend created the Profile CR
        prof = cluster.get(PT.API_VERSION, PT.KIND, "alice-ns")
        assert PT.owner_name(prof) == USER
        active = [s.dataset.get("step")
                  for s in b.document.querySelectorAll("#register .step")
                  if "active" in s.className.split()]
        assert active == ["4"]  # finished panel

    def test_create_failure_surfaces_error_and_offers_retry(self):
        cluster = FakeCluster()
        b = dash_browser(cluster)
        # a Profile squatting on the name makes create fail server-side
        squat = ob.new_object(PT.API_VERSION, PT.KIND, "taken")
        squat["spec"] = {"owner": {"kind": "User", "name": "bob@example.com"}}
        cluster.create(squat)
        b.load(DASH_PAGE)
        b.click("reg-start")
        b.type_into("reg-ns", "taken")
        b.click("reg-next")
        b.click("reg-create")
        assert "failed:" in b.text("reg-msg")
        assert b.by_id("reg-retry").style.get("display") == ""
        # retry returns to the name step instead of dead-ending
        b.click("reg-retry")
        active = [s.dataset.get("step")
                  for s in b.document.querySelectorAll("#register .step")
                  if "active" in s.className.split()]
        assert active == ["1"]

    def test_existing_member_skips_walkthrough_and_loads_cards(self):
        cluster = FakeCluster()
        b = dash_browser(cluster)
        prof = ob.new_object(PT.API_VERSION, PT.KIND, "alice-ns")
        prof["spec"] = {"owner": {"kind": "User", "name": USER}}
        cluster.create(prof)
        cluster.create(ob.new_object("v1", "Namespace", "alice-ns"))
        b.load(DASH_PAGE)
        assert b.by_id("register").style.get("display") in (None, "", "none")
        sel = b.by_id("ns")
        assert [o.value for o in sel.options] == ["alice-ns"]
        # namespace cards were fetched for the selected namespace
        assert ("GET", "/api/activities/alice-ns") in b.requests
        assert ("GET", "/api/workgroup/get-contributors/alice-ns") in b.requests


class TestDashboardContributors:
    def _member_browser(self):
        cluster = FakeCluster()
        b = dash_browser(cluster)
        prof = ob.new_object(PT.API_VERSION, PT.KIND, "alice-ns")
        prof["spec"] = {"owner": {"kind": "User", "name": USER}}
        cluster.create(prof)
        cluster.create(ob.new_object("v1", "Namespace", "alice-ns"))
        b.load(DASH_PAGE)
        return cluster, b

    def test_add_and_remove_contributor_through_ui(self):
        cluster, b = self._member_browser()
        b.type_into("contrib-email", "bob@example.com")
        b.click("contrib-add")
        # rendered AND persisted (kfam wrote the RoleBinding)
        assert "bob@example.com" in b.by_id("contributors").textContent
        rbs = [rb for rb in cluster.list("rbac.authorization.k8s.io/v1",
                                         "RoleBinding", "alice-ns")
               if ob.annotations_of(rb).get(PT.ANNO_USER) == "bob@example.com"]
        assert rbs, "contributor RoleBinding not created"
        # remove via the row button the JS built
        rows = b.by_id("contributors").querySelectorAll("button")
        assert len(rows) == 1
        rows[0].click()
        assert "owner only" in b.by_id("contributors").textContent
        rbs = [rb for rb in cluster.list("rbac.authorization.k8s.io/v1",
                                         "RoleBinding", "alice-ns")
               if ob.annotations_of(rb).get(PT.ANNO_USER) == "bob@example.com"]
        assert not rbs

    def test_invalid_contributor_shows_error_not_crash(self):
        cluster, b = self._member_browser()
        b.type_into("contrib-email", "not-an-email")
        b.click("contrib-add")
        assert b.text("contrib-err") != ""
        assert "not-an-email" not in b.by_id("contributors").textContent


class TestDashboardServingCard:
    def test_unreachable_serving_distinct_from_no_models(self):
        """The ADVICE r2 fix, executed: a failed fetch must render
        'serving unreachable', an empty inventory 'no models'."""
        cluster = FakeCluster()
        kfam = KfamService(cluster, cluster_admin="root@example.com")

        def boom(url):
            raise OSError("connection refused")

        b = Browser(Dashboard(cluster, kfam=kfam, fetch_json=boom).router())
        b.default_headers["kubeflow-userid"] = USER
        b.load(DASH_PAGE)
        assert "serving unreachable" in b.by_id("served").textContent

        ok = Browser(Dashboard(cluster, kfam=kfam,
                               fetch_json=lambda u: {"models": []}).router())
        ok.default_headers["kubeflow-userid"] = USER
        ok.load(DASH_PAGE)
        assert "no models" in ok.by_id("served").textContent


class TestJwaSpawner:
    """The spawner flow the reference verifies with Selenium
    (testing/test_jwa.py): fill the form, launch, see it listed."""

    def _browser(self):
        from kubeflow_tpu.webapps.jwa import JupyterWebApp

        cluster = FakeCluster()
        prof = ob.new_object(PT.API_VERSION, PT.KIND, "team-a")
        prof["spec"] = {"owner": {"kind": "User", "name": USER}}
        cluster.create(prof)
        cluster.create(ob.new_object("v1", "Namespace", "team-a"))
        from kubeflow_tpu.webapps.jwa_ui import PAGE as JWA_PAGE

        b = Browser(JupyterWebApp(cluster).router())
        b.default_headers["kubeflow-userid"] = USER
        b.load(JWA_PAGE)
        return cluster, b

    def test_spawn_notebook_through_real_form(self):
        cluster, b = self._browser()
        # init() populated the selectors from api/config + api/namespaces
        assert [o.value for o in b.by_id("ns").options] == ["team-a"]
        assert len(b.by_id("images").options) >= 1
        name_input = b.by_id("spawn").querySelector('[name]')
        assert name_input.name == "name"
        name_input.value = "my-notebook"
        b.submit("spawn")
        nb = cluster.get(NT.API_VERSION, NT.KIND,
                         "my-notebook", "team-a")
        assert nb is not None
        # the listing refreshed and shows the new notebook
        assert "my-notebook" in b.by_id("list").textContent

    def test_invalid_name_rejected_by_backend_shown_in_ui(self):
        cluster, b = self._browser()
        b.by_id("spawn").querySelector('[name]').value = "Invalid Name!"
        b.submit("spawn")
        assert b.text("msg") != ""
        assert not cluster.list(NT.API_VERSION, NT.KIND,
                                namespace="team-a")

    def test_poddefault_checkboxes_flow_into_spawn(self):
        from kubeflow_tpu.control.poddefault import new_poddefault

        cluster, b = self._browser()
        cluster.create(new_poddefault(
            "tpu-access", "team-a", desc="Mount TPU libs",
            selector={"matchLabels": {"inject-tpu": "true"}}))
        # re-select the namespace so the poddefault list reloads
        b.select("ns", "team-a")
        boxes = b.by_id("poddefaults").querySelectorAll("input")
        assert len(boxes) == 1
        boxes[0].checked = True
        b.by_id("spawn").querySelector('[name]').value = "pd-notebook"
        b.submit("spawn")
        nb = cluster.get(NT.API_VERSION, NT.KIND,
                         "pd-notebook", "team-a")
        labels = (((nb["spec"].get("template") or {}).get("metadata") or {})
                  .get("labels") or {})
        assert labels.get("inject-tpu") == "true"


class TestBackendNameValidation:
    """Server-side validation the harness forced into existence: the
    browser regex is advisory; the backends must 400 invalid names."""

    def test_workgroup_create_rejects_invalid_namespace(self):
        from kubeflow_tpu.utils.httpd import HttpReq

        cluster = FakeCluster()
        b = dash_browser(cluster)
        b.load(DASH_PAGE, run_scripts=False)
        import json as _j

        req = HttpReq(method="POST", path="/api/workgroup/create", params={},
                      query={}, headers={"kubeflow-userid": USER},
                      body=_j.dumps({"namespace": "Bad_Name!"}).encode())
        resp = b.routers[-1][1].dispatch(req)
        assert resp.status == 400
        assert not cluster.list(PT.API_VERSION, PT.KIND)

    def test_nonstring_notebook_name_is_400_not_500(self):
        from kubeflow_tpu.webapps.jwa import JupyterWebApp
        from kubeflow_tpu.utils.httpd import HttpReq
        import json as _j

        cluster = FakeCluster()
        r = JupyterWebApp(cluster).router()
        req = HttpReq(method="POST", path="/api/namespaces/ns/notebooks",
                      params={}, query={}, headers={},
                      body=_j.dumps({"name": 123}).encode())
        assert r.dispatch(req).status == 400

    def test_derived_fallback_name_is_sanitized(self):
        from kubeflow_tpu.utils.names import sanitize_dns1123

        assert sanitize_dns1123("Alice.B") == "alice-b"
        assert sanitize_dns1123("---") == "user"


class TestWorkgroupSettingsCard:
    """Admin all-namespaces view + the nuke-self danger-zone flow
    (reference: namespace-selector all-namespaces + manage-workgroup)."""

    def test_admin_sees_all_namespaces_list(self):
        cluster = FakeCluster()
        kfam = KfamService(cluster, cluster_admin=USER)  # alice IS admin
        for n in ("team-a", "team-b"):
            cluster.create(ob.new_object("v1", "Namespace", n))
        b = Browser(Dashboard(cluster, kfam=kfam).router())
        b.default_headers["kubeflow-userid"] = USER
        b.load(DASH_PAGE)
        assert b.by_id("admin-ns").style.get("display") == "block"
        assert "team-a" in b.by_id("all-ns").textContent
        assert "team-b" in b.by_id("all-ns").textContent

    def test_non_admin_card_stays_hidden(self):
        cluster = FakeCluster()
        b = dash_browser(cluster)  # admin is root@, not alice
        b.load(DASH_PAGE)
        assert b.by_id("admin-ns").style.get("display") in (None, "none")

    def test_nuke_flow_requires_confirmation_and_deletes_profiles(self):
        cluster = FakeCluster()
        b = dash_browser(cluster)
        prof = ob.new_object(PT.API_VERSION, PT.KIND, "alice-ns")
        prof["spec"] = {"owner": {"kind": "User", "name": USER}}
        cluster.create(prof)
        cluster.create(ob.new_object("v1", "Namespace", "alice-ns"))
        b.load(DASH_PAGE)
        # cancel path: nothing deleted
        b.click("nuke-btn")
        assert b.by_id("nuke-confirm").style.get("display") == ""
        b.click("nuke-no")
        assert cluster.get_or_none(PT.API_VERSION, PT.KIND, "alice-ns")
        # confirm path: profiles gone, UI returns to the walkthrough
        b.click("nuke-btn")
        b.click("nuke-yes")
        assert cluster.get_or_none(PT.API_VERSION, PT.KIND, "alice-ns") is None
        assert "deleted 1" in b.text("nuke-msg")
        assert b.by_id("register").style.get("display") == "block"


class TestDashboardNavigation:
    """Hash routing + iframe app embedding (the reference SPA's
    iframe-based app navigation, main-page.js routing)."""

    def test_hash_routes_to_iframe_and_back(self):
        cluster = FakeCluster()
        b = dash_browser(cluster)
        prof = ob.new_object(PT.API_VERSION, PT.KIND, "alice-ns")
        prof["spec"] = {"owner": {"kind": "User", "name": USER}}
        cluster.create(prof)
        cluster.create(ob.new_object("v1", "Namespace", "alice-ns"))
        b.load(DASH_PAGE)
        main = b.document.querySelector("main")
        assert main.style.get("display") in (None, "")
        # navigate to an embedded app route
        routes = b.eval("Object.keys(APP_ROUTES)")
        assert routes, "dashboard defines no APP_ROUTES"
        target = routes[0]
        b.set_hash(target)
        assert main.style.get("display") == "none"
        frame = b.by_id("app-frame")
        assert frame.getAttribute("src")
        assert "ns=alice-ns" in frame.getAttribute("src")
        # active nav link follows the hash
        active = [a.getAttribute("href")
                  for a in b.document.querySelectorAll("#appnav a")
                  if "active" in a.className.split()]
        assert active == [target]
        # unknown route -> 404 view, never a blank page
        b.set_hash("#/bogus")
        assert b.by_id("notfound-view").style.get("display") == ""
        assert b.text("notfound-path") == "#/bogus"
        # home again
        b.set_hash("#/")
        assert main.style.get("display") == ""


class TestTensorboardsUi:
    """The Tensorboards CRUD app's page executed end to end (a consumer
    of crud_backend the reference never shipped a frontend for)."""

    def _browser(self):
        from kubeflow_tpu.webapps.tensorboards import PAGE, TensorboardsApp

        cluster = FakeCluster()
        cluster.create(ob.new_object("v1", "Namespace", "team-a"))
        b = Browser(TensorboardsApp(cluster).router())
        b.default_headers["kubeflow-userid"] = USER
        b.location["search"] = "?ns=team-a"
        b.load(PAGE)
        return cluster, b

    def test_create_list_delete_roundtrip(self):
        cluster, b = self._browser()
        assert "none yet" in b.by_id("rows").textContent
        b.by_id("name").value = "exp1"
        b.by_id("logspath").value = "gs://bkt/logs"
        b.click("create")
        tb = cluster.get("tensorboard.kubeflow.org/v1alpha1", "Tensorboard",
                         "exp1", "team-a")
        assert tb["spec"]["logspath"] == "gs://bkt/logs"
        assert "exp1" in b.by_id("rows").textContent
        # delete through the row button the JS built
        btns = b.by_id("rows").querySelectorAll("button")
        assert len(btns) == 1
        btns[0].click()
        assert cluster.get_or_none("tensorboard.kubeflow.org/v1alpha1",
                                   "Tensorboard", "exp1", "team-a") is None
        assert "none yet" in b.by_id("rows").textContent

    def test_invalid_inputs_surface_backend_errors(self):
        cluster, b = self._browser()
        b.by_id("name").value = "Bad Name!"
        b.by_id("logspath").value = "gs://bkt/logs"
        b.click("create")
        assert "invalid" in b.text("err")
        b.by_id("name").value = "ok-name"
        b.by_id("logspath").value = "relative/path"
        b.click("create")
        assert b.text("err")  # logspath must be cloud or absolute
        assert not cluster.list("tensorboard.kubeflow.org/v1alpha1",
                                "Tensorboard", namespace="team-a")


class TestHarnessSemantics:
    """JS-semantics corners where silent divergence from a browser would
    make UI tests lie (found by the jsdom-focused review)."""

    def _out(self, js):
        b = Browser()
        b.load('<div id="out"></div>', run_scripts=False)
        b.run(js)
        return b, b.text("out")

    def test_reference_identity_equality(self):
        _, out = self._out("""
          const a = [1, 2], b = [1, 2], o = {x: 1}, p = {x: 1};
          document.getElementById('out').textContent =
            [a === b, a === a, o === p, o == p, [o].includes(p),
             [o].includes(o)].join(',');
        """)
        assert out == "false,true,false,false,false,true"

    def test_unhandled_async_rejection_fails_the_test(self):
        from kubeflow_tpu.testing.jsdom import JSThrow

        b = Browser()
        b.load('<button id="go"></button>', run_scripts=False)
        b.run("""
          document.getElementById('go').addEventListener('click',
            async () => { throw new Error('broken handler'); });
        """)
        with pytest.raises(JSThrow, match="broken handler"):
            b.click("go")
        # top-level rejected chain also surfaces
        with pytest.raises(JSThrow, match="boom"):
            b.run("Promise.reject(new Error('boom'));")

    def test_cleared_timers_do_not_fire(self):
        b = Browser()
        b.load('<div id="out">0</div>', run_scripts=False)
        b.run("""
          let n = 0;
          const keep = setInterval(() => { n += 1; }, 1000);
          const kill = setInterval(() => { n += 100; }, 1000);
          clearInterval(kill);
          const once = setTimeout(() => { n += 10; }, 50);
          const never = setTimeout(() => { n += 1000; }, 50);
          clearTimeout(never);
          document.getElementById('out').textContent = 'armed';
          setInterval(() => {
            document.getElementById('out').textContent = String(n); }, 1);
        """)
        b.fire_timers()  # intervals render before timeouts drain
        assert b.text("out") == "1"  # keep fired; cleared interval didn't
        b.fire_timers()
        # n = keep(1) + once(10) + keep(1) = 12: the one-shot fired
        # exactly once, nothing cleared ever fired
        assert b.text("out") == "12"

    def test_regex_global_flag_and_groups(self):
        _, out = self._out("""
          const s = 'a-a-a'.replace(/a/g, 'b');
          const t = 'v1.2'.replace(/(\\d+)\\.(\\d+)/, '$2:$1');
          document.getElementById('out').textContent = s + ' ' + t;
        """)
        assert out == "b-b-b v2:1"

    def test_split_and_modulo_and_infinity(self):
        _, out = self._out("""
          document.getElementById('out').textContent =
            ['a b'.split().length, 'abc'.split('').join('|'),
             'a, b,c'.split(/,\\s*/).join('+'),
             (-5) % 3, '' + 1 / 0].join(' ');
        """)
        assert out == "1 a|b|c a+b+c -2 Infinity"

    def test_eval_rejects_trailing_tokens(self):
        from kubeflow_tpu.testing.jsdom import JSError

        b = Browser()
        b.load("<div></div>", run_scripts=False)
        with pytest.raises(JSError, match="trailing"):
            b.eval("1 + 1 garbage")

    def test_typeof_propagates_real_errors(self):
        from kubeflow_tpu.testing.jsdom import JSThrow

        b = Browser()
        b.load('<div id="out"></div>', run_scripts=False)
        b.run("""document.getElementById('out').textContent =
                   typeof neverDeclared;""")
        assert b.text("out") == "undefined"
        with pytest.raises(JSThrow):
            b.run("const o = {}; typeof o.missing.deep;")
