"""Worker payload for the SCHEDULER gang e2e test.

Joins the jax.distributed world from the JAXJOB_* env the controller
injected and proves ONE world formed across the scheduler-placed pods:
after initialize_from_env, jax.device_count() equals num_processes only
when every rank's topology exchange with the coordinator succeeded (a
lone process would see 1). Deliberately stops short of the full flax
trainer (that path is gang_worker.py's job): the scheduler e2e isolates
placement → world formation, so it must not inherit the trainer's
model-layer dependencies — or the CPU backend's lack of multiprocess
collectives.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# sitecustomize may have pre-registered a TPU backend; force cpu the same
# way tests/conftest.py does.
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.parallel import backends as B  # noqa: E402
from kubeflow_tpu.parallel import dist as D  # noqa: E402
from kubeflow_tpu.parallel.dist import initialize_from_env  # noqa: E402


def main() -> int:
    dist = initialize_from_env()
    if isinstance(D.active_backend(), B.LoopbackBackend):
        # tier-1 mode: the TCP join barrier only releases once every
        # rank has checked in, so reaching this line IS the formation
        # proof; the world stamp carries the agreed size
        world = D.active_world()
        assert world is not None, "loopback world did not form"
        size = world.num_processes
    else:
        # real jax.distributed: every process sees every process's
        # devices (ranks that failed to join would leave this at 1)
        assert jax.device_count() == dist.num_processes, \
            (jax.device_count(), dist.num_processes)
        assert jax.process_count() == dist.num_processes
        size = jax.device_count()
    assert size == dist.num_processes, (size, dist.num_processes)

    with open(os.environ["GANG_LOG"], "a") as f:
        f.write(json.dumps({"rank": dist.process_id,
                            "world": size}) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
