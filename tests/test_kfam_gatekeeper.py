"""KFAM REST + gatekeeper auth semantics (reference:
access-management/kfam/{api_default,bindings}.go, bindings_test.go;
gatekeeper/auth/AuthServer.go). Driven through the routers directly (no
sockets) except one live-HTTP smoke test."""

import pytest

from kubeflow_tpu.control.gatekeeper.auth import AuthServer, pwhash
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.kfam.service import USER_HEADER, KfamService, binding_name
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.utils.httpd import HttpReq


def mkreq(method, path, user=None, body=b"", query=None, headers=None):
    h = {k.lower(): v for k, v in (headers or {}).items()}
    if user:
        h[USER_HEADER] = user
    import json as _json

    if isinstance(body, (dict, list)):
        body = _json.dumps(body).encode()
    return HttpReq(method=method, path=path, params={}, query=query or {},
                   headers=h, body=body)


@pytest.fixture()
def kfam():
    cluster = FakeCluster()
    cluster.create(PT.new_profile("team-a", "alice@example.com"))
    svc = KfamService(cluster, cluster_admin="root@example.com")
    return cluster, svc, svc.router()


class TestKfamBindings:
    def binding_body(self, user="bob@example.com", ns="team-a", role="edit"):
        return {"user": {"kind": "User", "name": user},
                "referredNamespace": ns,
                "roleRef": {"kind": "ClusterRole", "name": f"kubeflow-{role}"}}

    def test_owner_can_create_binding(self, kfam):
        cluster, svc, router = kfam
        resp = router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                                     user="alice@example.com",
                                     body=self.binding_body()))
        assert resp.status == 200, resp.body
        rb = cluster.get("rbac.authorization.k8s.io/v1", "RoleBinding",
                         binding_name("bob@example.com", "edit"), "team-a")
        assert rb["roleRef"]["name"] == "kubeflow-edit"
        assert ob.annotations_of(rb)[PT.ANNO_USER] == "bob@example.com"
        pol = cluster.get("security.istio.io/v1beta1", "AuthorizationPolicy",
                          binding_name("bob@example.com", "edit"), "team-a")
        assert pol["spec"]["rules"]

    def test_non_owner_forbidden(self, kfam):
        _, _, router = kfam
        resp = router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                                     user="mallory@example.com",
                                     body=self.binding_body()))
        assert resp.status == 403

    def test_cluster_admin_allowed(self, kfam):
        _, _, router = kfam
        resp = router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                                     user="root@example.com",
                                     body=self.binding_body()))
        assert resp.status == 200

    def test_missing_identity_401(self, kfam):
        _, _, router = kfam
        resp = router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                                     body=self.binding_body()))
        assert resp.status == 401

    def test_read_bindings_filters(self, kfam):
        import json

        _, _, router = kfam
        for user, role in (("bob@example.com", "edit"), ("eve@example.com", "view")):
            router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                                  user="alice@example.com",
                                  body=self.binding_body(user=user, role=role)))
        all_b = json.loads(router.dispatch(
            mkreq("GET", "/kfam/v1/bindings")).body)["bindings"]
        assert len(all_b) == 2
        only_bob = json.loads(router.dispatch(
            mkreq("GET", "/kfam/v1/bindings",
                  query={"user": ["bob@example.com"]})).body)["bindings"]
        assert len(only_bob) == 1
        assert only_bob[0]["roleRef"]["name"] == "kubeflow-edit"
        only_view = json.loads(router.dispatch(
            mkreq("GET", "/kfam/v1/bindings",
                  query={"role": ["view"]})).body)["bindings"]
        assert [b["user"]["name"] for b in only_view] == ["eve@example.com"]

    def test_delete_binding(self, kfam):
        cluster, _, router = kfam
        router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                              user="alice@example.com", body=self.binding_body()))
        resp = router.dispatch(mkreq("DELETE", "/kfam/v1/bindings",
                                     user="alice@example.com",
                                     body=self.binding_body()))
        assert resp.status == 200
        assert cluster.get_or_none(
            "rbac.authorization.k8s.io/v1", "RoleBinding",
            binding_name("bob@example.com", "edit"), "team-a") is None

    def test_duplicate_binding_conflict(self, kfam):
        _, _, router = kfam
        router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                              user="alice@example.com", body=self.binding_body()))
        resp = router.dispatch(mkreq("POST", "/kfam/v1/bindings",
                                     user="alice@example.com",
                                     body=self.binding_body()))
        assert resp.status == 409


class TestKfamProfiles:
    def test_create_profile_via_api(self, kfam):
        cluster, _, router = kfam
        resp = router.dispatch(mkreq(
            "POST", "/kfam/v1/profiles", user="carol@example.com",
            body={"metadata": {"name": "team-b"}}))
        assert resp.status == 200
        prof = cluster.get(PT.API_VERSION, PT.KIND, "team-b")
        assert prof["spec"]["owner"]["name"] == "carol@example.com"

    def test_delete_profile_requires_owner(self, kfam):
        cluster, _, router = kfam
        assert router.dispatch(mkreq("DELETE", "/kfam/v1/profiles/team-a",
                                     user="mallory@example.com")).status == 403
        assert router.dispatch(mkreq("DELETE", "/kfam/v1/profiles/team-a",
                                     user="alice@example.com")).status == 200

    def test_query_cluster_admin(self, kfam):
        import json

        _, _, router = kfam
        out = json.loads(router.dispatch(mkreq(
            "GET", "/kfam/v1/clusteradmin",
            query={"user": ["root@example.com"]})).body)
        assert out["isClusterAdmin"] is True
        out = json.loads(router.dispatch(mkreq(
            "GET", "/kfam/v1/clusteradmin",
            query={"user": ["bob@example.com"]})).body)
        assert out["isClusterAdmin"] is False


class TestGatekeeper:
    @pytest.fixture()
    def gk(self):
        return AuthServer(username="admin", passhash=pwhash("hunter2", "s"), salt="s")

    def test_basic_auth_allows(self, gk):
        import base64

        cred = base64.b64encode(b"admin:hunter2").decode()
        resp = gk.check(mkreq("GET", "/auth",
                              headers={"Authorization": f"Basic {cred}"}))
        assert resp.status == 200
        assert resp.headers["kubeflow-userid"] == "admin"

    def test_wrong_password_browser_redirects(self, gk):
        import base64

        cred = base64.b64encode(b"admin:wrong").decode()
        resp = gk.check(mkreq("GET", "/auth",
                              headers={"Authorization": f"Basic {cred}",
                                       "Accept": "text/html"}))
        assert resp.status == 302
        assert resp.headers["Location"] == "/kflogin"

    def test_api_client_gets_401(self, gk):
        assert gk.check(mkreq("GET", "/auth")).status == 401

    def test_login_mints_cookie_and_cookie_allows(self, gk):
        resp = gk.login(mkreq("POST", "/login",
                              body={"username": "admin", "password": "hunter2"}))
        assert resp.status == 200
        cookie = resp.headers["Set-Cookie"].split(";")[0]
        resp2 = gk.check(mkreq("GET", "/auth", headers={"Cookie": cookie}))
        assert resp2.status == 200

    def test_expired_cookie_rejected(self, gk):
        tok = gk.mint_cookie("admin", now=0)  # minted at epoch -> expired
        resp = gk.check(mkreq("GET", "/auth",
                              headers={"Cookie": f"kubeflow-auth={tok}"}))
        assert resp.status == 401

    def test_tampered_cookie_rejected(self, gk):
        tok = gk.mint_cookie("admin")
        resp = gk.check(mkreq("GET", "/auth",
                              headers={"Cookie": f"kubeflow-auth={tok[:-4]}AAAA"}))
        assert resp.status == 401

    def test_live_http_roundtrip(self, gk):
        import requests

        svc = gk.serve(host="127.0.0.1").serve_background()
        try:
            r = requests.post(f"http://127.0.0.1:{svc.port}/login",
                              json={"username": "admin", "password": "hunter2"},
                              timeout=5)
            assert r.status_code == 200
            r2 = requests.get(f"http://127.0.0.1:{svc.port}/auth",
                              cookies=r.cookies, timeout=5)
            assert r2.status_code == 200
        finally:
            svc.shutdown()
