"""PodDefault webhook merge semantics (reference: admission-webhook
main_test.go — merge/conflict behaviors) plus the AdmissionReview HTTP
contract, driven over a real socket."""

import base64
import json

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.poddefault import PodDefaultMutator, new_poddefault
from kubeflow_tpu.control.poddefault.webhook import (
    ANNOTATION_PREFIX,
    apply_poddefaults,
    filter_poddefaults,
    safe_to_apply,
)


def make_pod(labels=None, env=None):
    pod = ob.new_object("v1", "Pod", "p", "default", labels=labels or {},
                        spec={"containers": [{"name": "main", "env": env or []}]})
    return pod


TPU_DEFAULT = dict(
    selector={"matchLabels": {"inject-tpu": "true"}},
    env=[{"name": "JAX_PLATFORMS", "value": "tpu"}],
    volumes=[{"name": "libtpu", "hostPath": {"path": "/usr/lib/libtpu"}}],
    volume_mounts=[{"name": "libtpu", "mountPath": "/usr/lib/libtpu"}],
)


class TestMerge:
    def test_label_selector_filtering(self):
        pds = [new_poddefault("tpu", **TPU_DEFAULT),
               new_poddefault("other", selector={"matchLabels": {"x": "y"}})]
        matched = filter_poddefaults(make_pod(labels={"inject-tpu": "true"}), pds)
        assert [ob.meta(p)["name"] for p in matched] == ["tpu"]
        assert filter_poddefaults(make_pod(), pds) == []

    def test_exclude_annotation(self):
        pod = make_pod(labels={"inject-tpu": "true"})
        ob.set_annotation(pod, f"{ANNOTATION_PREFIX}/exclude", "true")
        assert filter_poddefaults(pod, [new_poddefault("tpu", **TPU_DEFAULT)]) == []

    def test_apply_injects_env_volumes_and_marker(self):
        pod = make_pod(labels={"inject-tpu": "true"})
        pd = new_poddefault("tpu", **TPU_DEFAULT)
        ob.meta(pd)["resourceVersion"] = "42"
        apply_poddefaults(pod, [pd])
        c = pod["spec"]["containers"][0]
        assert {"name": "JAX_PLATFORMS", "value": "tpu"} in c["env"]
        assert c["volumeMounts"][0]["mountPath"] == "/usr/lib/libtpu"
        assert pod["spec"]["volumes"][0]["name"] == "libtpu"
        assert ob.annotations_of(pod)[f"{ANNOTATION_PREFIX}/poddefault-tpu"] == "42"

    def test_identical_env_is_idempotent(self):
        pod = make_pod(labels={"inject-tpu": "true"},
                       env=[{"name": "JAX_PLATFORMS", "value": "tpu"}])
        apply_poddefaults(pod, [new_poddefault("tpu", **TPU_DEFAULT)])
        envs = [e for e in pod["spec"]["containers"][0]["env"]
                if e["name"] == "JAX_PLATFORMS"]
        assert len(envs) == 1

    def test_conflicting_env_rejects_whole_set(self):
        pod = make_pod(labels={"inject-tpu": "true"},
                       env=[{"name": "JAX_PLATFORMS", "value": "cpu"}])
        err = safe_to_apply(pod, [new_poddefault("tpu", **TPU_DEFAULT)])
        assert err and "JAX_PLATFORMS" in err

    def test_conflicting_mount_path(self):
        a = new_poddefault("a", selector={}, volumes=[{"name": "v1", "emptyDir": {}}],
                           volume_mounts=[{"name": "v1", "mountPath": "/data"}])
        b = new_poddefault("b", selector={}, volumes=[{"name": "v2", "emptyDir": {}}],
                           volume_mounts=[{"name": "v2", "mountPath": "/data"}])
        err = safe_to_apply(make_pod(), [a, b])
        assert err and "/data" in err

    def test_labels_annotations_tolerations(self):
        pd = new_poddefault(
            "extras", selector={},
            labels={"team": "ml"}, annotations={"note": "hi"},
            tolerations=[{"key": "google.com/tpu", "operator": "Exists"}],
        )
        pod = make_pod()
        apply_poddefaults(pod, [pd])
        assert ob.labels_of(pod)["team"] == "ml"
        assert ob.annotations_of(pod)["note"] == "hi"
        assert pod["spec"]["tolerations"] == [
            {"key": "google.com/tpu", "operator": "Exists"}]
        # idempotent toleration merge
        apply_poddefaults(pod, [pd])
        assert len(pod["spec"]["tolerations"]) == 1


class TestAdmissionChain:
    def test_mutator_wired_into_fake_cluster(self):
        cluster = FakeCluster()
        cluster.create(new_poddefault("tpu", **TPU_DEFAULT))
        mutator = PodDefaultMutator(cluster)
        cluster.add_admission_hook(mutator.admission_hook)
        pod = cluster.create(make_pod(labels={"inject-tpu": "true"}))
        env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
        assert env["JAX_PLATFORMS"] == "tpu"

    def test_conflict_admits_unmodified(self):
        cluster = FakeCluster()
        cluster.create(new_poddefault("tpu", **TPU_DEFAULT))
        mutator = PodDefaultMutator(cluster)
        cluster.add_admission_hook(mutator.admission_hook)
        pod = cluster.create(make_pod(labels={"inject-tpu": "true"},
                                      env=[{"name": "JAX_PLATFORMS", "value": "cpu"}]))
        env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
        assert env["JAX_PLATFORMS"] == "cpu"  # admitted as-is, not corrupted

    def test_admission_review_http_roundtrip(self):
        import requests

        cluster = FakeCluster()
        cluster.create(new_poddefault("tpu", **TPU_DEFAULT))
        svc = PodDefaultMutator(cluster).serve(host="127.0.0.1").serve_background()
        try:
            pod = make_pod(labels={"inject-tpu": "true"})
            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {"uid": "u1", "namespace": "default", "object": pod},
            }
            r = requests.post(
                f"http://127.0.0.1:{svc.port}/apply-poddefault", json=review, timeout=5)
            assert r.status_code == 200
            resp = r.json()["response"]
            assert resp["allowed"] and resp["uid"] == "u1"
            patch = json.loads(base64.b64decode(resp["patch"]))
            patched = ob.json_patch(pod, patch)
            env = {e["name"]: e["value"]
                   for e in patched["spec"]["containers"][0]["env"]}
            assert env["JAX_PLATFORMS"] == "tpu"
        finally:
            svc.shutdown()

    def test_no_match_returns_no_patch(self):
        cluster = FakeCluster()
        mutator = PodDefaultMutator(cluster)
        out = mutator.review({"request": {"uid": "u2", "namespace": "default",
                                          "object": make_pod()}})
        assert out["response"]["allowed"]
        assert "patch" not in out["response"]
