"""Gatekeeper entry: python -m kubeflow_tpu.control.gatekeeper."""
import argparse

from kubeflow_tpu.control.gatekeeper.auth import AuthServer

p = argparse.ArgumentParser("gatekeeper")
p.add_argument("--port", type=int, default=8085)
args = p.parse_args()
svc = AuthServer().serve(port=args.port)
print(f"gatekeeper on :{svc.port}")
svc.serve_forever()
