"""Gang scheduler runtime: all-or-nothing admission, priority preemption.

A ``Reconciler`` on the same ``control/runtime.py`` machinery as every
other controller. Reconcile keys are gangs (namespace + job name, from
the pod label the JAXJob controller already stamps); pod events map to
their gang, node events retry everything queued.

Admission is kube-scheduler-shaped but slice-native:

1. walk each namespace's queued gangs in priority/FIFO order
   (``GangQueue.ordered_by_namespace`` — a backed-off head still blocks
   its namespace, see _schedule_pass);
2. for the head gang, compute per-node free chips (allocatable minus
   the requests of bound, non-terminal pods) and try to place EVERY
   worker on a feasible node (selector + taints + readiness) — best-fit
   on free chips so slices pack;
3. complete assignment -> bind all pods (spec.nodeName patch + lift the
   scheduling gate); any bind failure releases the partial reservation
   (unbind + re-gate) — no partial placement ever escapes;
4. no assignment -> try preempting lower-priority gangs (evict their
   pods as Failed/Evicted, which fires the JAXJob controller's existing
   gang-restart path), else requeue with exponential backoff.

The pass is strict-priority FIFO per namespace (Kueue StrictFIFO): a
blocked head gang blocks its namespace's queue behind it, so a large
high-priority job cannot be starved by a stream of small ones — while
one tenant's stuck gang never halts another tenant's admission.
"""

from __future__ import annotations

import logging
import threading
import time

import prometheus_client as prom

from kubeflow_tpu.control.cache import ClusterCache
from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import (
    _metric, schedule_latency, worker_index,
)
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.runtime import (
    Controller, Reconciler, Request, Result,
)
from kubeflow_tpu.control.scheduler import (
    ANNOTATION_ELASTIC_MIN, ANNOTATION_GANG_SIZE, ANNOTATION_PRIORITY,
    GATE_GANG, SCHEDULER_NAME,
)
from kubeflow_tpu.control.scheduler import capacity as CP
from kubeflow_tpu.control.scheduler import nodes as N
from kubeflow_tpu.control.scheduler.queue import GangQueue
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.runtime.metrics import REGISTRY, MetricsRegistry

# Queue-to-bound latency buckets: scheduling is sub-second when capacity
# exists, minutes when a gang waits behind backoff/preemption.
BIND_LATENCY_BUCKETS = (0.1, 0.5, 1, 2, 5, 10, 30, 60, 120, 300, 600)

# Pass-duration buckets: an indexed pass is sub-millisecond at hundreds
# of nodes; the tier-1 scale smoke budgets the tail (docs/scale.md).
PASS_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

# Prometheus sink: the jaxjob controller's _metric lazy-singleton
# registry, shared — one double-registration guard for the package.


def pass_seconds_prom():
    return _metric("scheduler_pass_seconds", prom.Histogram,
                   "scheduling pass duration (sync + health + admit)",
                   buckets=PASS_BUCKETS)


def nodes_scanned_prom():
    return _metric("scheduler_nodes_scanned_total", prom.Counter,
                   "nodes examined by best-fit placement walks")


def cache_reads_prom():
    return _metric("scheduler_cache_reads_total", prom.Counter,
                   "hot-path cluster reads by source",
                   labelnames=("source",))


log = logging.getLogger("kubeflow_tpu.scheduler")

# after a preemption the freed chips appear as soon as the eviction
# status lands — retry quickly rather than paying a backoff round
_RETRY_AFTER_PREEMPT = 0.05

# _WAIT: blocked for a non-capacity reason (gang mid-creation, transient
# bind failure) — never a preemption trigger. _UNPLACEABLE: a genuine
# failed capacity assignment — the only outcome that may evict others.
# _PARTIAL: an ELASTIC gang bound a subset >= its floor; the remainder
# re-queues at the back of the FIFO (grow-back). _GROW_WAIT: an elastic
# gang already running at/above its floor found no room to grow — backs
# off WITHOUT head-blocking its namespace and never preempts (growth is
# a preference; only sub-floor admission is a need).
_ADMITTED, _GONE, _WAIT, _UNPLACEABLE, _PARTIAL, _GROW_WAIT = \
    "admitted", "gone", "wait", "unplaceable", "partial", "grow-wait"

# Sentinel reconcile key: "retry everything queued". Node events and
# bound-pod phase changes enqueue this ONE key instead of one key per
# queued gang — each reconcile already runs a full global scheduling
# pass, so fanning out N keys per event was N-1 redundant passes.
RETRY_ALL = Request("", "-retry-all-")


def _gang_annotation(pods: list[dict], key: str) -> int | None:
    for p in pods:
        v = ob.annotations_of(p).get(key)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                return None
    return None


def _gang_context(pods: list[dict]) -> obs_trace.SpanContext | None:
    """The job's trace context, read from the traceparent annotation the
    JAXJob controller stamps on gang pods — admission/bind/preemption
    spans parent on it so the scheduler's work appears inside the job's
    own timeline, not in a disconnected trace."""
    for p in pods:
        ctx = obs_trace.parse_traceparent(
            ob.annotations_of(p).get(obs_trace.TRACEPARENT_ANNOTATION))
        if ctx is not None:
            return ctx
    return None


class GangScheduler(Reconciler):
    # Optional hook: called with each pass duration in seconds (the
    # scale benchmark collects raw samples for p50/p99 — histogram
    # buckets are too coarse for a tail assertion).
    pass_observer = None

    def __init__(
        self,
        queue: GangQueue | None = None,
        registry: MetricsRegistry = REGISTRY,
        record_events: bool = True,
        clock=None,
        jitter: float = 0.0,
        cache: ClusterCache | None = None,
    ):
        if queue is None:
            kw = {"jitter": jitter}
            if clock:
                kw["clock"] = clock
            queue = GangQueue(**kw)
        self.queue = queue
        self.registry = registry
        self.record_events = record_events
        # The indexed cluster cache (ISSUE 7). With it, every hot-path
        # read — gang pods, capacity, victim scan, node health — is an
        # O(bucket) snapshot lookup; without it (cache=None, the
        # pre-ISSUE-7 shape kept for the seed-vs-optimized benchmark)
        # each read is a full apiserver relist.
        self.cache = cache
        # legacy-path node-set memory for the health-pass short-circuit
        self._known_nodes: set[str] | None = None
        # last published cache stats, for counter deltas; read-compute-
        # update must be atomic or two workers publishing concurrently
        # double-count the same delta
        self._cache_stats: dict[str, int] = {}
        self._stats_lock = threading.Lock()
        # admission is a read-compute-bind transaction over cluster
        # state; two run(workers=N) threads interleaving passes would
        # each see the same free chips and double-book a node, so the
        # whole pass is serialized (kube-scheduler's single scheduling
        # cycle). Queue state has its own finer lock.
        self._pass_lock = threading.Lock()
        # injectable pass timer (DET601): pass-duration metrics come
        # off this hook so virtual-time benches can pin it
        self._perf = time.perf_counter

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, client, req: Request) -> Result | None:
        with self._pass_lock:
            t0 = self._perf()
            if self.cache is not None:
                # catch the snapshot up BEFORE reading: the event that
                # triggered this reconcile is already in the watch
                # queues, and the serialized pass keeps event
                # application single-writer
                self.cache.refresh()
            if req != RETRY_ALL:  # the sentinel names no gang to sync
                self._sync(client, req)
            else:
                # node events land here: before admitting anything,
                # evict gangs whose nodes died under them (freed chips
                # then feed the same pass). Under the pass lock: two
                # concurrent node-event reconciles must not double-
                # evict (and double-count) the same pods.
                self._health_pass(client)
            delay = self._schedule_pass(client)
            self._observe_pass(self._perf() - t0)
        self._publish_metrics()
        if delay is not None:
            return Result(requeue_after=max(delay, 0.01))
        return None

    def _observe_pass(self, dt: float) -> None:
        self.registry.histogram(
            "scheduler_pass_seconds", dt,
            help_="scheduling pass duration (sync + health + admit)",
            buckets=PASS_BUCKETS)
        pass_seconds_prom().observe(dt)
        if self.pass_observer is not None:
            self.pass_observer(dt)

    def _note(self, obj: dict | None) -> None:
        """Fold our own write response into the cache (assume-cache):
        the next admission in this same pass must see this bind."""
        if self.cache is not None and obj:
            self.cache.note_write(obj)

    def _count_read(self, source: str) -> None:
        self.registry.counter_inc(
            "scheduler_cache_reads_total",
            help_="hot-path cluster reads by source (cache hit rate)",
            source=source)
        cache_reads_prom().labels(source=source).inc()

    def _count_scanned(self, cap: CP.Capacity) -> None:
        if cap.scanned:
            self.registry.counter_inc(
                "scheduler_nodes_scanned_total",
                help_="nodes examined by best-fit placement walks",
                by=cap.scanned)
            nodes_scanned_prom().inc(cap.scanned)
            cap.scanned = 0

    def _sync(self, client, req: Request) -> None:
        """Fold this gang's current cluster state into the queue."""
        pods = self._gang_pods(client, req.namespace, req.name)
        pending = [p for p in pods if self._unbound_pending(p)]
        if not pending and self._cache_may_lag(pods, req.namespace,
                                               req.name):
            pods = self._confirm_gang(client, req.namespace, req.name)
            pending = [p for p in pods if self._unbound_pending(p)]
        if not pending:
            self.queue.remove(req.namespace, req.name)
            return
        prio = _gang_annotation(pods, ANNOTATION_PRIORITY) or 0
        newly = self.queue.get(req.namespace, req.name) is None
        self.queue.offer(req.namespace, req.name, priority=prio)
        if newly and self.record_events and hasattr(client, "record_event"):
            client.record_event(
                pending[0], "GangQueued",
                f"gang {req.namespace}/{req.name} queued for admission "
                f"(priority {prio})", component=SCHEDULER_NAME)

    def _schedule_pass(self, client) -> float | None:
        """Admit queued gangs, per namespace, in strict priority/FIFO
        order until that namespace's head blocks. Returns the shortest
        delay to requeue after, or None when idle.

        Head blocking is PER NAMESPACE (the queue is per-tenant, ISSUE
        3): an unplaceable gang in one namespace cannot starve another
        tenant whose pool has room. Within a namespace the walk covers
        ALL entries, not just backoff-expired ones — a backed-off head
        still holds its namespace's queue (nothing may jump it), its
        backoff only pacing how often admission is retried."""
        now = self.queue.clock()
        delays: list[float] = []
        # namespaces are processed in their HEAD entry's global
        # admission order (priority desc, then FIFO): after an eviction
        # the retrying preemptor is always first to the freed chips — a
        # lower-priority head in a later namespace can never steal them
        by_ns = self.queue.ordered_by_namespace()
        for _ns, entries in sorted(
                by_ns.items(),
                key=lambda kv: (-kv[1][0].priority, kv[1][0].seq)):
            for entry in entries:
                if entry.not_before > now:
                    delays.append(entry.not_before - now)  # head backing off
                    break
                outcome = self._try_admit(client, entry)
                if outcome in (_ADMITTED, _GONE):
                    self.queue.remove(entry.namespace, entry.name)
                    continue
                if outcome == _PARTIAL:
                    # the elastic gang got capacity down to its floor;
                    # its remainder moves to the BACK of the FIFO (fresh
                    # seq) with backoff, so a gang waiting to grow back
                    # can never starve the siblings queued behind it
                    prio = entry.priority
                    self.queue.remove(entry.namespace, entry.name)
                    self.queue.offer(entry.namespace, entry.name,
                                     priority=prio)
                    delays.append(
                        self.queue.requeue(entry.namespace, entry.name))
                    continue
                if outcome == _GROW_WAIT:
                    # running at/above its floor, nothing to grow into:
                    # back off but DO NOT head-block the namespace — a
                    # viable running gang is not starved, and holding
                    # the queue for its preference would starve others
                    delays.append(
                        self.queue.requeue(entry.namespace, entry.name))
                    self.registry.counter_inc(
                        "scheduler_requeues_total",
                        help_="gang admission attempts that failed and "
                              "backed off",
                        namespace=entry.namespace, tenant=entry.namespace)
                    continue
                # blocked: the namespace head holds its queue; on a
                # genuine capacity failure (never on a gang still being
                # created or a transient bind error) try to make room,
                # else back off
                if outcome == _WAIT:
                    # mid-creation / transient: poll at the base rate
                    # WITHOUT burning the exponential schedule or the
                    # failed-admission counter — this gang never had a
                    # real admission attempt rejected
                    delays.append(self.queue.base_backoff)
                    break
                if self._try_preempt(client, entry):
                    # end the WHOLE pass: gangs in not-yet-walked
                    # namespaces must not bind the chips this eviction
                    # just freed for the preemptor
                    return _RETRY_AFTER_PREEMPT
                delays.append(
                    self.queue.requeue(entry.namespace, entry.name))
                self.registry.counter_inc(
                    "scheduler_requeues_total",
                    help_="gang admission attempts that failed and "
                          "backed off",
                    namespace=entry.namespace, tenant=entry.namespace)
                break
        if delays:
            return min(delays)
        return self.queue.next_wakeup(now)

    # -- node health --------------------------------------------------------

    def _health_pass(self, client) -> None:
        """Evict bound gang pods whose node went NotReady or vanished
        (today's admission-time filter, nodes.py feasible(), protects
        only FUTURE placements). Eviction uses the kubelet-eviction
        shape — phase Failed, reason Evicted — so the JAXJob
        controller's existing ``_pod_preempted`` path gang-restarts the
        job on its preemption budget, and the recreated (gated) pods
        requeue for admission on the surviving nodes.

        Steady-state cost (ISSUE 7 satellite): with every node Ready
        this pass touches ZERO pods — the cache answers "any bound pod
        on a dead node?" from its by-node index, and the legacy path
        skips the pod list unless a node is unready or vanished since
        the last pass (it previously listed every Pod in the cluster on
        every RETRY_ALL reconcile)."""
        victims: list[tuple[dict, str]] = []
        new_known: set[str] | None = None
        if self.cache is not None:
            self._count_read("cache")
            for node, why in sorted(
                    self.cache.unhealthy_bound_nodes().items()):
                for p in self.cache.pods_on_node(node):
                    if (p.get("spec") or {}).get("schedulerName") \
                            != SCHEDULER_NAME:
                        continue
                    victims.append((p, f"node {node} {why} under gang"))
        else:
            self._count_read("list")
            views = {v.name: v for v in (N.node_view(n)
                                         for n in client.list("v1", "Node"))}
            unready = {n for n, v in views.items() if not v.ready}
            vanished = (self._known_nodes or set()) - set(views)
            first = self._known_nodes is None
            if not unready and not vanished and not first:
                # all Ready, nothing vanished: skip the pod list (safe
                # to commit the node set here — there is no work below
                # whose failure could lose a signal)
                self._known_nodes = set(views)
                return
            new_known = set(views)
            for p in client.list("v1", "Pod"):
                spec = p.get("spec") or {}
                if spec.get("schedulerName") != SCHEDULER_NAME:
                    continue
                node = spec.get("nodeName")
                if not node:
                    continue
                if (p.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
                    continue
                view = views.get(node)
                if view is not None and view.ready:
                    continue
                why = "deleted" if view is None else "NotReady"
                victims.append((p, f"node {node} {why} under gang"))
        for p, message in victims:
            m = ob.meta(p)
            cur = client.get_or_none("v1", "Pod", m["name"],
                                     m.get("namespace"))
            if cur is None:
                continue
            if (cur.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
                continue
            cur.setdefault("status", {})
            cur["status"].update(N.eviction_status(message))
            self._note(client.update_status(cur))
            log.info("evicted %s/%s: %s", m.get("namespace"), m["name"],
                     message)
            self.registry.counter_inc(
                "scheduler_node_evictions_total",
                help_="gang pods evicted because their node died",
                namespace=m.get("namespace") or "default")
            if self.record_events and hasattr(client, "record_event"):
                client.record_event(cur, "GangNodeLost", message, "Warning",
                                    component=SCHEDULER_NAME)
        # commit the node-set memory only once every eviction landed: a
        # raising list/update above leaves _known_nodes unchanged, so
        # the retrying reconcile still sees the vanished node (eviction
        # is idempotent — already-terminal victims are skipped)
        if new_known is not None:
            self._known_nodes = new_known

    # -- admission ----------------------------------------------------------

    def _gang_pods(self, client, namespace: str, name: str) -> list[dict]:
        if self.cache is not None:
            self._count_read("cache")
            pods = self.cache.gang_pods(namespace, name)
            return [p for p in pods
                    if (p.get("spec") or {}).get("schedulerName")
                    == SCHEDULER_NAME]
        return self._gang_pods_listed(client, namespace, name)

    def _gang_pods_listed(self, client, namespace: str,
                          name: str) -> list[dict]:
        self._count_read("list")
        pods = client.list(
            "v1", "Pod", namespace=namespace,
            label_selector={"matchLabels": {JT.LABEL_JOB_NAME: name}})
        return [p for p in pods
                if (p.get("spec") or {}).get("schedulerName")
                == SCHEDULER_NAME]

    def _cache_may_lag(self, pods: list[dict], namespace: str,
                       name: str) -> bool:
        """Whether 'no pending pods' is trustworthy enough to drop the
        gang. In pumped mode refresh() cannot drain the pump-owned
        streams, so a reconcile can read a snapshot that predates its
        own triggering event — and a gang dropped from the queue on
        that basis has nothing left to requeue it (gated Pending pods
        emit no further events). Only the states a stalled restart
        actually leaves behind need confirming (no pods / all terminal
        / still queued): live bound pods mean the gang is running, and
        its eventual terminal transitions re-enter here."""
        if self.cache is None or not self.cache.pumped:
            return False
        if not pods or all((p.get("status") or {}).get("phase")
                           in N.TERMINAL_PHASES for p in pods):
            return True
        return self.queue.get(namespace, name) is not None

    def _confirm_gang(self, client, namespace: str,
                      name: str) -> list[dict]:
        """Authoritative re-read before a destructive queue decision,
        folded back into the lagging cache (rv-guarded, so it can only
        advance the snapshot)."""
        pods = self._gang_pods_listed(client, namespace, name)
        for p in pods:
            self._note(p)
        return pods

    @staticmethod
    def _unbound_pending(pod: dict) -> bool:
        spec = pod.get("spec") or {}
        phase = (pod.get("status") or {}).get("phase", "Pending")
        if phase != "Pending" or spec.get("nodeName"):
            return False
        # kube semantics: a pod carrying ANY foreign gate is
        # unschedulable — admitting its gang would reserve chips (and
        # possibly preempt running work) for workers that cannot start
        # until that gate's controller lifts it
        return all(g.get("name") == GATE_GANG
                   for g in spec.get("schedulingGates") or [])

    def _try_admit(self, client, entry) -> str:
        pods = self._gang_pods(client, entry.namespace, entry.name)
        with obs_trace.TRACER.span(
                "scheduler.admit", parent=_gang_context(pods),
                namespace=entry.namespace, gang=entry.name,
                attempt=entry.attempts,
                queue_wait_s=round(
                    max(self.queue.clock() - entry.enqueued_at, 0.0),
                    6)) as sp:
            outcome = self._admit(client, entry, pods)
            sp.attrs["outcome"] = outcome
            return outcome

    def _admit(self, client, entry, pods: list[dict]) -> str:
        if self._repair_stragglers(client, entry.namespace, pods):
            pods = self._gang_pods(client, entry.namespace, entry.name)
        pending = sorted((p for p in pods if self._unbound_pending(p)),
                         key=lambda p: ob.meta(p)["name"])
        if not pending and self.cache is not None and self.cache.pumped:
            # a queued gang with nothing pending is about to be dropped
            # — confirm the lagging snapshot against the apiserver first
            pods = self._confirm_gang(client, entry.namespace, entry.name)
            pending = sorted((p for p in pods if self._unbound_pending(p)),
                             key=lambda p: ob.meta(p)["name"])
        if not pending:
            return _GONE  # bound elsewhere or deleted
        bound = [p for p in pods
                 if (p.get("spec") or {}).get("nodeName")
                 and (p.get("status") or {}).get("phase")
                 not in N.TERMINAL_PHASES]
        size = _gang_annotation(pods, ANNOTATION_GANG_SIZE) \
            or (len(pending) + len(bound))
        if len(pending) + len(bound) < size:
            return _WAIT  # gang mid-creation: wait for the full set
        emin = _gang_annotation(pods, ANNOTATION_ELASTIC_MIN)
        elastic = emin is not None and emin >= 1
        if len(pending) < size and not elastic:
            # rigid gangs: bound residue (half-started bind) is the
            # JAXJob controller's to resolve — unchanged semantics
            return _WAIT
        cap = self._capacity(client)
        try:
            assignment = self._assign(pending, cap, prefer_spot=elastic)
            if assignment is None and elastic:
                # partial admission: any subset keeping the world at or
                # above the elastic floor beats idling — the scheduler's
                # half of shrink-to-survivors. Rigid gangs never get
                # here: all-or-nothing stays the law.
                floor = max(emin - len(bound), 1)
                assignment = self._assign_partial(pending, cap, floor=floor)
                if assignment is None and len(bound) >= emin:
                    return _GROW_WAIT
            if assignment is None:
                if self.record_events and hasattr(client, "record_event"):
                    # dedup (obs/events.py) collapses the retry storm:
                    # one Event whose count tracks the failed attempts
                    client.record_event(
                        pending[0], "GangUnschedulable",
                        f"gang {entry.namespace}/{entry.name}: no node set "
                        f"fits all {len(pending)} workers"
                        + (f" (nor >= the elastic floor of {emin})"
                           if elastic else ""), "Warning",
                        component=SCHEDULER_NAME)
                return _UNPLACEABLE
        finally:
            self._count_scanned(cap)
        if not self._bind(client, entry, assignment):
            return _WAIT
        if any(cap.views[n].spot for n in assignment.values()):
            self.registry.counter_inc(
                "scheduler_spot_admissions_total",
                help_="gang admissions that placed workers on "
                      "spot-pool nodes",
                namespace=entry.namespace)
        if self._slice_groups(pending) is not None:
            self.registry.counter_inc(
                "scheduler_slice_admissions_total",
                help_="multislice gang admissions placed slice-by-slice "
                      "(one pool per slice, all-or-nothing across "
                      "slices)",
                namespace=entry.namespace)
        if len(assignment) < len(pending):
            if self.record_events and hasattr(client, "record_event"):
                client.record_event(
                    pending[0], "GangPartiallyAdmitted",
                    f"gang {entry.namespace}/{entry.name}: bound "
                    f"{len(assignment) + len(bound)}/{size} workers "
                    f"(elastic floor {emin}); remainder queued for "
                    f"grow-back", component=SCHEDULER_NAME)
            return _PARTIAL
        return _ADMITTED

    def _capacity(self, client) -> CP.Capacity:
        """The placement snapshot: per-node free chips = allocatable -
        requests of bound, non-terminal pods (an evicted gang's chips
        free immediately), plus the sorted per-pool buckets. Served
        from the cache's incremental indexes, or (legacy path, kept for
        the seed-vs-optimized benchmark) rebuilt from a full relist."""
        if self.cache is not None:
            self._count_read("cache")
            return self.cache.capacity()
        self._count_read("list")
        views = {v.name: v
                 for v in (N.node_view(n)
                           for n in client.list("v1", "Node"))}
        free = {name: v.allocatable_chips for name, v in views.items()}
        for p in client.list("v1", "Pod"):
            node = (p.get("spec") or {}).get("nodeName")
            if not node or node not in free:
                continue
            if (p.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
                continue
            free[node] -= N.pod_tpu_request(p)
        return CP.Capacity.from_views(views, free)

    @staticmethod
    def _slice_groups(pods: list[dict]) -> dict[int, list[dict]] | None:
        """Pods grouped by their slice label (JAXJob controller stamps
        LABEL_SLICE_INDEX on sliceCount > 1 gangs), slice ids ascending;
        None when the gang is not sliced (any pod without the label) —
        single-slice admission stays byte-identical to the flat path."""
        groups: dict[int, list[dict]] = {}
        for p in pods:
            idx = ob.labels_of(p).get(JT.LABEL_SLICE_INDEX)
            if idx is None:
                return None
            try:
                groups.setdefault(int(idx), []).append(p)
            except ValueError:
                return None
        return dict(sorted(groups.items()))

    @classmethod
    def _assign(cls, pods: list[dict], cap: CP.Capacity,
                prefer_spot: bool = False, txn: CP.CapacityTxn | None = None):
        """All-or-nothing placement: best-fit every worker or None.
        Each worker is a bisect into its pool's sorted free-capacity
        bucket plus a walk to the first feasible node (capacity.py) —
        the semantics of the old full scan (min free chips, then
        lexicographically-first name), minus the O(nodes) per worker.
        Trials never disturb the snapshot: placement happens on a
        copy-on-write ``CapacityTxn`` (``txn`` lets the preemption loop
        seed one with victim credits).

        Sliced gangs (LABEL_SLICE_INDEX on every pod) place slice by
        slice with same-pool-per-slice affinity — see _assign_sliced;
        all-or-nothing still holds ACROSS slices.

        ``prefer_spot`` (elastic gangs): when any feasible spot node has
        room, best-fit among spot nodes only — spot capacity is
        reclaim-tolerant work's to burn, keeping on-demand pools free
        for rigid gangs. Preferred, not required: with the spot pool
        full, placement falls back to any feasible node."""
        if txn is None:
            txn = cap.txn()
        groups = cls._slice_groups(pods)
        if groups is not None:
            out: dict[str, str] = {}
            for spods in groups.values():
                placed = cls._assign_slice(spods, txn, prefer_spot)
                if placed is None:
                    return None  # all-or-nothing across slices
                out.update(placed)
            return out
        out = {}
        for pod in pods:
            need = N.pod_tpu_request(pod)
            best = txn.best_fit(pod, need, prefer_spot)
            if best is None:
                return None
            txn.take(best, need)
            out[ob.meta(pod)["name"]] = best
        return out

    @classmethod
    def _assign_slice(cls, spods: list[dict], txn: CP.CapacityTxn,
                      prefer_spot: bool) -> dict[str, str] | None:
        """Place ONE slice entirely inside ONE (accelerator, topology)
        pool — the ICI domain is pool-shaped, so a slice split across
        pools could never form its mesh. Candidate pools are walked in
        pool-level best-fit order (ascending total free chips as this
        txn sees them, then key, deterministic); each trial runs on a
        FORK of the txn so a failed pool leaves no residue, and the
        first pool that fits the whole slice is replayed onto the
        parent txn. Different slices of one gang may land in different
        pools (the dcn axis crosses pools; only ici stays inside one).

        Nodes without BOTH pool labels live only in the catch-all
        bucket and are never slice candidates — a slice needs a pool
        identity to pin its topology."""
        sel = (spods[0].get("spec") or {}).get("nodeSelector") or {}
        accel = sel.get(JT.NODESELECTOR_ACCEL)
        topo = sel.get(JT.NODESELECTOR_TOPOLOGY)
        candidates = sorted(
            (key for key in txn.bucket_keys()
             if (accel is None or key[0] == accel)
             and (topo is None or key[1] == topo)),
            key=lambda k: (txn.bucket_free(k), k))
        ordered = sorted(spods, key=cls._replica_order)
        needs = [N.pod_tpu_request(p) for p in ordered]
        for key in candidates:
            trial = txn.fork()
            placed: dict[str, str] = {}
            try:
                for pod, need in zip(ordered, needs):
                    best = trial.best_fit(pod, need, prefer_spot,
                                          bucket_key=key)
                    if best is None:
                        placed = {}
                        break
                    trial.take(best, need)
                    placed[ob.meta(pod)["name"]] = best
            except Exception:
                trial.rollback()  # a torn trial must leave no residue
                raise
            if placed:
                trial.commit()  # replay the winning takes on the parent
                return placed
            trial.rollback()
        return None

    @staticmethod
    def _replica_order(pod: dict):
        """Numeric replica-index key (worker-10 must sort AFTER
        worker-2, which plain name order gets wrong for gangs >= 10):
        the partial-admission prefix keeps the lowest indices, so
        worker 0 — the coordinator pick — survives when anything does.
        ``worker_index`` is the ONE index parse, shared with the JAXJob
        controller's world-membership ordering — the admitted prefix
        and the world stamp must agree on what "lowest" means."""
        name = ob.meta(pod)["name"]
        return (worker_index(name), name)

    def _assign_partial(self, pods: list[dict], cap, free=None,
                        floor: int = 1):
        """Largest placeable prefix of at least ``floor`` workers, or
        None. Gang workers are homogeneous (same selector/chips), so a
        deterministic index-ordered prefix loses no generality. Prefix
        placeability is monotone in k (dropping a worker from a valid
        assignment stays valid), so binary search: O(log n) full
        best-fit passes instead of O(n) on the scheduler's hot path.

        ``cap`` is a ``Capacity`` snapshot; the pre-ISSUE-7
        ``(views, free)`` pair is still accepted (``free`` not None)
        and wrapped on the spot."""
        if free is not None:
            cap = CP.Capacity.from_views(cap, free)
        if floor > len(pods):
            return None
        groups = self._slice_groups(pods)
        if groups is not None:
            # slice-elastic: the world only ever holds COMPLETE slices,
            # so the admitted subset is a prefix of whole slices (lowest
            # slice ids first — slice 0 carries worker 0, the
            # coordinator pick). Same monotone binary search, over
            # slice count instead of worker count.
            sids = sorted(groups)
            per = max(len(g) for g in groups.values())
            floor_slices = max(1, -(-floor // per))
            if floor_slices > len(sids):
                return None
            best = None
            lo, hi = floor_slices, len(sids) - 1
            while lo <= hi:
                mid = (lo + hi) // 2
                subset = [p for s in sids[:mid] for p in groups[s]]
                a = self._assign(subset, cap, prefer_spot=True)
                if a is not None:
                    best = a
                    lo = mid + 1
                else:
                    hi = mid - 1
            return best
        pods = sorted(pods, key=self._replica_order)
        best = None
        lo, hi = floor, len(pods) - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            a = self._assign(pods[:mid], cap, prefer_spot=True)
            if a is not None:
                best = a
                lo = mid + 1
            else:
                hi = mid - 1
        return best

    def _bind(self, client, entry, assignment: dict[str, str]) -> bool:
        """Bind the whole gang in two phases: set every spec.nodeName
        WHILE the scheduling gates still hold the kubelets off, and only
        once all binds landed lift the gates. A failure in the BIND
        phase leaves only gated (unrunnable) pods to release, so a
        kubelet polling mid-bind can never start a partial gang. A
        failure in the LIFT phase (pod deleted under us — the JAXJob
        controller tearing the gang down) can leave an already-ungated
        pod briefly runnable; the release below re-gates whatever is
        still Pending and leaves Running pods to the JAXJob controller's
        gang-restart reconciliation (a lone worker is a missing-worker
        gang restart there) — full multi-pod atomicity does not exist
        over an apiserver."""
        bound: list[str] = []
        bound_objs: dict[str, dict] = {}
        with obs_trace.TRACER.span("scheduler.bind",
                                   workers=len(assignment)) as bind_span:
            try:
                for pod_name, node_name in sorted(assignment.items()):
                    bound_objs[pod_name] = client.patch(
                        "v1", "Pod", pod_name,
                        {"spec": {"nodeName": node_name}},
                        entry.namespace)
                    self._note(bound_objs[pod_name])
                    bound.append(pod_name)
                for pod_name in sorted(assignment):
                    # the bind-phase patch response already carries the
                    # pod's gate list — one coalesced write per object,
                    # no per-pod re-GET on the hot path
                    self._lift_gate(client, entry.namespace, pod_name,
                                    cur=bound_objs[pod_name])
            except ob.ApiError as e:
                log.warning("gang %s/%s: bind failed (%s); releasing %d pods",
                            entry.namespace, entry.name, e, len(bound))
                bind_span.status = "ERROR"
                bind_span.error = f"{type(e).__name__}: {e}"
                for pod_name in bound:
                    try:
                        self._release_pod(client, entry.namespace, pod_name)
                    except ob.ApiError:
                        log.exception("gang %s/%s: release of %s failed",
                                      entry.namespace, entry.name, pod_name)
                return False
        latency = max(self.queue.clock() - entry.enqueued_at, 0.0)
        schedule_latency().observe(latency)
        self.registry.histogram(
            "scheduler_bind_latency_seconds", latency,
            help_="queue-to-bound gang latency",
            buckets=BIND_LATENCY_BUCKETS,
            namespace=entry.namespace, tenant=entry.namespace)
        self.registry.counter_inc(
            "scheduler_gangs_admitted_total",
            help_="gangs fully bound", namespace=entry.namespace,
            tenant=entry.namespace)
        if self.record_events and hasattr(client, "record_event"):
            # the bind-phase patch responses already carry everything an
            # involvedObject needs — no per-pod re-GET on the hot pass
            for pod_name, node_name in sorted(assignment.items()):
                client.record_event(
                    bound_objs[pod_name], "Scheduled",
                    f"gang-bound {pod_name} to {node_name}",
                    component=SCHEDULER_NAME)
        return True

    def _repair_stragglers(self, client, namespace: str,
                           pods: list[dict]) -> bool:
        """Release half-bound leftovers: a pod that is Pending, BOUND,
        and still carrying OUR gate is the residue of a failed bind
        whose rollback also failed. Left alone it wedges its gang in
        _WAIT forever (bound pods are excluded from the pending set);
        releasing it here makes the rollback self-healing. Safe against
        our own in-flight binds: passes are serialized by _pass_lock, so
        no bind is mid-phase while this runs."""
        repaired = False
        for p in pods:
            spec = p.get("spec") or {}
            phase = (p.get("status") or {}).get("phase", "Pending")
            if phase != "Pending" or not spec.get("nodeName"):
                continue
            if not any(g.get("name") == GATE_GANG
                       for g in spec.get("schedulingGates") or []):
                continue
            try:
                self._release_pod(client, namespace, ob.meta(p)["name"])
                repaired = True
            except ob.ApiError:
                log.exception("straggler release of %s/%s failed",
                              namespace, ob.meta(p)["name"])
        return repaired

    def _lift_gate(self, client, namespace: str, pod_name: str,
                   cur: dict | None = None) -> None:
        """Remove OUR gate only — another controller's gate (a quota
        hold, say) is its to lift, never ours to clobber. ``cur`` (the
        bind-phase patch response) saves the re-GET on the hot path."""
        if cur is None:
            cur = client.get("v1", "Pod", pod_name, namespace)
        gates = [g for g in (cur.get("spec") or {}).get("schedulingGates")
                 or [] if g.get("name") != GATE_GANG]
        self._note(client.patch(
            "v1", "Pod", pod_name,
            {"spec": {"schedulingGates": gates or None}}, namespace))

    def _release_pod(self, client, namespace: str, pod_name: str) -> None:
        """Failed-bind rollback for one pod: unbind and restore OUR gate
        (preserving any foreign gates). Non-Pending pods are left alone
        — stripping a Running pod's binding would corrupt node
        accounting; the JAXJob controller owns its fate (gang restart)."""
        cur = client.get_or_none("v1", "Pod", pod_name, namespace)
        if cur is None:
            return
        if (cur.get("status") or {}).get("phase", "Pending") != "Pending":
            return
        gates = list((cur.get("spec") or {}).get("schedulingGates") or [])
        if not any(g.get("name") == GATE_GANG for g in gates):
            gates.append({"name": GATE_GANG})
        self._note(client.patch(
            "v1", "Pod", pod_name,
            {"spec": {"nodeName": None, "schedulingGates": gates}},
            namespace))

    # -- preemption ---------------------------------------------------------

    def _try_preempt(self, client, entry) -> bool:
        """Make room for a blocked gang by evicting lower-priority
        gangs, lowest first, until the blocked gang would fit. Eviction
        marks victims Failed/Evicted — the JAXJob controller's
        ``_pod_preempted`` path gang-restarts them (preemption budget,
        not the crash budget) and their recreated pods requeue behind
        the preemptor."""
        pods = self._gang_pods(client, entry.namespace, entry.name)
        pending = sorted((p for p in pods if self._unbound_pending(p)),
                         key=lambda p: ob.meta(p)["name"])
        if not pending:
            return False
        with obs_trace.TRACER.span(
                "scheduler.preempt", parent=_gang_context(pods),
                namespace=entry.namespace, gang=entry.name,
                priority=entry.priority) as sp:
            evicted = self._preempt(client, entry, pending)
            sp.attrs["evicted"] = evicted
            return evicted

    def _preempt(self, client, entry, pending: list[dict]) -> bool:
        cap = self._capacity(client)
        try:
            if self._assign(pending, cap) is not None:
                # fits without evicting anyone (state moved since the
                # failed admission attempt) — let the next pass admit it
                return False
            # only nodes the preemptor could actually use: evicting a
            # gang from a different pool (topology/accelerator mismatch)
            # frees nothing this gang can take, so such victims are
            # never touched
            usable = {name for name, v in cap.views.items()
                      if any(N.feasible(p, v) for p in pending)}
            # victim chips accumulate on ONE credits txn; each what-if
            # assignment runs on a fork so its takes never leak into
            # the next round's starting state
            credits = cap.txn()
            chosen: list[tuple[tuple[str, str], list[dict]]] = []
            for gang_key, gang_pods in self._victim_gangs(
                    client, entry.priority):
                if not any((p.get("spec") or {}).get("nodeName") in usable
                           for p in gang_pods):
                    continue
                for p in gang_pods:
                    node = (p.get("spec") or {}).get("nodeName")
                    if node in cap.free:
                        credits.credit(node, N.pod_tpu_request(p))
                chosen.append((gang_key, gang_pods))
                if self._assign(pending, cap, txn=credits.fork()) is not None:
                    self._evict(client, entry, chosen)
                    return True
            return False
        finally:
            self._count_scanned(cap)

    def _victim_gangs(self, client, priority: int):
        """Bound, non-terminal gangs of strictly lower priority, grouped
        and ordered lowest-priority first (then newest name-order last
        resort for determinism)."""
        gangs: dict[tuple[str, str], list[dict]] = {}
        prios: dict[tuple[str, str], int] = {}
        if self.cache is not None:
            self._count_read("cache")
            pods = self.cache.bound_pods()  # O(bound), no copies
        else:
            self._count_read("list")
            pods = client.list("v1", "Pod")
        for p in pods:
            spec = p.get("spec") or {}
            if spec.get("schedulerName") != SCHEDULER_NAME:
                continue
            if not spec.get("nodeName"):
                continue
            if (p.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
                continue
            job = ob.labels_of(p).get(JT.LABEL_JOB_NAME)
            if not job:
                continue
            try:
                prio = int(ob.annotations_of(p).get(ANNOTATION_PRIORITY, 0))
            except ValueError:
                prio = 0
            if prio >= priority:
                continue
            key = (ob.meta(p).get("namespace") or "default", job)
            gangs.setdefault(key, []).append(p)
            prios[key] = prio
        order = sorted(gangs, key=lambda k: (prios[k], k))
        return [(k, gangs[k]) for k in order]

    def _evict(self, client, entry, chosen) -> None:
        for (ns, name), gang_pods in chosen:
            message = (f"preempted by higher-priority gang "
                       f"{entry.namespace}/{entry.name}")
            for p in gang_pods:
                cur = client.get_or_none("v1", "Pod", ob.meta(p)["name"], ns)
                if cur is None:
                    continue
                cur.setdefault("status", {})
                cur["status"].update(N.eviction_status(message))
                self._note(client.update_status(cur))
            log.info("evicted gang %s/%s: %s", ns, name, message)
            self.registry.counter_inc(
                "scheduler_preemptions_total",
                help_="gangs evicted for a higher-priority gang",
                namespace=ns, tenant=ns)
            if self.record_events and hasattr(client, "record_event") \
                    and gang_pods:
                client.record_event(gang_pods[0], "GangPreempted", message,
                                    "Warning", component=SCHEDULER_NAME)

    # -- observability ------------------------------------------------------

    def _publish_metrics(self) -> None:
        for ns, depth in self.queue.depths().items():
            self.registry.gauge(
                "scheduler_queue_depth", depth,
                help_="gangs queued awaiting admission", namespace=ns,
                tenant=ns)
        if self.cache is None:
            return
        helps = {
            "events": "watch events applied to the cluster cache",
            "stale_events": "out-of-order/replayed events dropped by "
                            "the resourceVersion guard",
            "relists": "full relists the cache performed (initial sync "
                       "+ 410/expired recoveries)",
            "resubscribes": "watch streams the cache resubscribed",
        }
        with self._stats_lock:
            stats = self.cache.stats()
            deltas = {key: stats.get(key, 0) - self._cache_stats.get(key, 0)
                      for key in helps}
            self._cache_stats = stats
        for key, help_ in helps.items():
            if deltas[key]:
                self.registry.counter_inc(
                    f"cluster_cache_{key}_total", help_=help_,
                    by=deltas[key])


def _pod_mapper(rec: GangScheduler, client):
    """A pod event maps to its own gang (kicking that gang's backoff —
    its pod set changed, retry on the new state now); a BOUND pod's
    event also enqueues the single RETRY_ALL sentinel, kicking every
    backoff when the pod's chips just freed — terminal phase, or the
    pod is gone from the cluster (a Running pod deleted out from under
    its gang) — so new capacity never waits out an exponential delay."""

    def fn(pod: dict) -> list[Request]:
        spec = pod.get("spec") or {}
        m = ob.meta(pod)
        reqs: dict[Request, None] = {}
        if spec.get("schedulerName") == SCHEDULER_NAME:
            job = ob.labels_of(pod).get(JT.LABEL_JOB_NAME)
            if job:
                ns = m.get("namespace") or "default"
                rec.queue.kick_one(ns, job)
                reqs[Request(ns, job)] = None
        if spec.get("nodeName") and rec.queue.depth():
            freed = (pod.get("status") or {}).get("phase") \
                in N.TERMINAL_PHASES
            if not freed:
                # mappers see objects, not event types: a DELETED
                # Running pod is recognized by its absence from the
                # store (its last state still says Running)
                freed = client.get_or_none(
                    "v1", "Pod", m["name"], m.get("namespace")) is None
            if freed:
                rec.queue.kick()
            reqs[RETRY_ALL] = None
        return list(reqs)

    return fn


def _node_mapper(rec: GangScheduler):
    """Node capacity/health changed: expire every backoff (new capacity
    must not wait out an exponential delay) and run one global pass.

    With an EMPTY queue, only an unhealthy-looking node event triggers
    the sentinel (its reconcile runs the node-health pass over bound
    gangs): a healthy node's periodic heartbeat/capacity refresh must
    not cost a full-cluster list on an idle scheduler. A node DELETED
    while Ready is the one shape this gate can miss (the event carries
    the last state); the JAXJob controller's slice-health watch treats
    a missing node as unhealthy and covers it."""

    def fn(node: dict) -> list[Request]:
        if rec.queue.depth():
            rec.queue.kick()
            return [RETRY_ALL]
        if not N.node_view(node).ready:
            return [RETRY_ALL]
        return []

    return fn


def build_scheduler(
    client,
    registry: MetricsRegistry = REGISTRY,
    record_events: bool = True,
    clock=None,
    queue: GangQueue | None = None,
    jitter: float = 0.0,
    cache: bool = True,
) -> Controller:
    """``cache=True`` (the default) runs the scheduler on an indexed
    ``ClusterCache`` — one initial list per kind, then incremental
    watch maintenance. ``cache=False`` keeps the relist-per-pass shape
    for A/B comparison (tools/sched_bench.py's "seed" arm)."""
    cluster_cache = ClusterCache(client).connect() if cache else None
    rec = GangScheduler(queue=queue, registry=registry,
                        record_events=record_events, clock=clock,
                        jitter=jitter, cache=cluster_cache)
    ctl = Controller("gang-scheduler", client, rec, registry=registry)
    if cluster_cache is not None:
        ctl.uses(cluster_cache)
    ctl.maps("v1", "Pod", _pod_mapper(rec, client))
    ctl.maps("v1", "Node", _node_mapper(rec))
    return ctl
