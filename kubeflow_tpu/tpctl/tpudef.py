"""TpuDef — the declarative deployment config (KfDef analogue).

The reference treats a KfDef YAML as the single source of truth for a
deployment (written/loaded kfctlServer.go:108-133, versioned
v1alpha1/v1beta1); status conditions appended :320-327 make re-apply
idempotent (tested by testing/kfctl/kfctl_second_apply.py). TpuDef keeps
that contract with TPU-specific platform fields (project/zone/slice
accelerator types instead of GPU node pools).
"""

from __future__ import annotations

import dataclasses
import io
from typing import Any

import yaml

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.scheduler.topology import parse_topology

API_VERSION = "tpctl.kubeflow.org/v1alpha1"
KIND = "TpuDef"

COND_AVAILABLE = "TpuDefAvailable"   # KfAvailable analogue
COND_DEGRADED = "TpuDefDegraded"     # KfDegraded analogue

# component names known to the manifest renderer; the `applications` list
# in a TpuDef selects a subset (default: all)
ALL_COMPONENTS = (
    "crds",
    "namespace",
    "rbac",
    "jaxjob-controller",
    "gang-scheduler",
    "jaxservice-controller",
    "notebook-controller",
    "profile-controller",
    "tensorboard-controller",
    "poddefault-webhook",
    "kfam",
    "gatekeeper",
    "centraldashboard",
    "jupyter-web-app",
    "tensorboards-web-app",
    "serving",
    "metric-collector",
)


@dataclasses.dataclass
class TpuDef:
    name: str = "kubeflow-tpu"
    namespace: str = "kubeflow"
    platform: str = "existing"          # existing | gke-tpu
    project: str = ""                   # gcp project (gke-tpu)
    zone: str = ""
    accelerator: str = "tpu-v5-lite-podslice"
    topology: str = "2x4"
    applications: tuple[str, ...] = ALL_COMPONENTS
    image_prefix: str = "kubeflow-tpu"
    use_istio: bool = True
    # HA control plane: 2 replicas per controller + leader election
    ha_controllers: bool = False
    overlays: list[dict] = dataclasses.field(default_factory=list)
    raw: dict = dataclasses.field(default_factory=dict, repr=False)

    def slice_chips(self) -> int:
        """Total chips in the deployment's slice topology — parsed by
        the ONE shared parser (control/scheduler/topology.py, also used
        by JAXJob validation and the gang scheduler's node model)."""
        return parse_topology(self.topology).chips

    @classmethod
    def from_dict(cls, d: dict) -> "TpuDef":
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        apps = spec.get("applications")
        if apps is not None:
            names = [a if isinstance(a, str) else a.get("name") for a in apps]
            unknown = sorted(set(names) - set(ALL_COMPONENTS))
            if unknown:
                raise ValueError(f"unknown applications {unknown}; "
                                 f"valid: {sorted(ALL_COMPONENTS)}")
            apps = tuple(names)
        plat = spec.get("platform") or {}
        return cls(
            name=meta.get("name", "kubeflow-tpu"),
            namespace=spec.get("namespace", "kubeflow"),
            platform=plat.get("kind", "existing"),
            project=plat.get("project", ""),
            zone=plat.get("zone", ""),
            accelerator=plat.get("accelerator", "tpu-v5-lite-podslice"),
            topology=plat.get("topology", "2x4"),
            applications=apps or ALL_COMPONENTS,
            image_prefix=spec.get("imagePrefix", "kubeflow-tpu"),
            use_istio=bool(spec.get("useIstio", True)),
            ha_controllers=bool(spec.get("haControllers", False)),
            overlays=list(spec.get("overlays") or []),
            raw=d,
        )

    @classmethod
    def load(cls, path_or_stream) -> "TpuDef":
        if hasattr(path_or_stream, "read"):
            d = yaml.safe_load(path_or_stream)
        else:
            with open(path_or_stream) as f:
                d = yaml.safe_load(f)
        if not isinstance(d, dict):
            raise ValueError("TpuDef YAML must be a mapping")
        if d.get("kind") not in (KIND, None):
            raise ValueError(f"expected kind {KIND}, got {d.get('kind')!r}")
        return cls.from_dict(d)

    def to_object(self) -> dict:
        """The cluster-stored form (status conditions live here)."""
        obj = ob.new_object(API_VERSION, KIND, self.name)
        obj["spec"] = {
            "namespace": self.namespace,
            "platform": {
                "kind": self.platform,
                "project": self.project,
                "zone": self.zone,
                "accelerator": self.accelerator,
                "topology": self.topology,
            },
            "applications": list(self.applications),
            "imagePrefix": self.image_prefix,
            "useIstio": self.use_istio,
            "haControllers": self.ha_controllers,
            "overlays": self.overlays,
        }
        return obj

    def dump(self) -> str:
        buf = io.StringIO()
        yaml.safe_dump(self.to_object(), buf, sort_keys=False)
        return buf.getvalue()


def example_yaml() -> str:
    return """\
apiVersion: tpctl.kubeflow.org/v1alpha1
kind: TpuDef
metadata:
  name: kubeflow-tpu
spec:
  namespace: kubeflow
  platform:
    kind: existing          # or gke-tpu (provisions node pools via gcloud)
    accelerator: tpu-v5-lite-podslice
    topology: 2x4
  useIstio: true
  # applications: [crds, namespace, jaxjob-controller]   # default: all
  # overlays:               # kustomize-style strategic patches
  # - target: {kind: Deployment, name: jaxjob-controller}
  #   patch: {spec: {replicas: 2}}
"""
