"""Alert-driven remediation (ISSUE 13 tentpole): the RemediationEngine
decision pass (firing-only triggering, matchers, silences, cooldowns,
the global rate limit, dry-run byte-parity, the audit ring and its
dual-sink counter), the three shipped actions over a FakeCluster, and
the default alert->action pack."""

import pytest

from kubeflow_tpu.control.jaxservice import types as JS
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.scheduler import SCHEDULER_NAME
from kubeflow_tpu.obs import remediate as RM
from kubeflow_tpu.obs.events import EventRecorder
from kubeflow_tpu.runtime.metrics import MetricsRegistry

pytestmark = pytest.mark.obs


class ManualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def firing(alert="HotZone", labels=None, at=0.0, value=1.0):
    return {"alert": alert, "to": "firing",
            "labels": labels or {"namespace": "default"},
            "value": value, "at": at}


def engine(actions=None, **kw):
    kw.setdefault("clock", ManualClock())
    kw.setdefault("registry", MetricsRegistry())
    return RM.RemediationEngine(actions or [], **kw)


class TestDecisionPass:
    def test_firing_transition_executes_the_bound_action(self):
        ran = []
        eng = engine([RM.Remediation(
            "fix", "HotZone", lambda tr: ran.append(tr) or "fixed")])
        out = eng.observe([firing()], at=10.0)
        assert len(ran) == 1 and ran[0]["alert"] == "HotZone"
        assert out[0]["result"] == RM.EXECUTED
        assert out[0]["detail"] == "fixed"
        assert out[0]["at"] == 10.0

    def test_only_firing_triggers_never_pending_or_resolved(self):
        ran = []
        eng = engine([RM.Remediation(
            "fix", "HotZone", lambda tr: ran.append(tr) or "")])
        for to in ("pending", "resolved"):
            assert eng.observe(
                [dict(firing(), to=to)], at=0.0) == []
        assert ran == []

    def test_matchers_scope_the_binding(self):
        ran = []
        eng = engine([RM.Remediation(
            "fix", "HotZone", lambda tr: ran.append(tr) or "",
            matchers={"namespace": "prod"})])
        assert eng.observe(
            [firing(labels={"namespace": "dev"})], at=0.0) == []
        out = eng.observe(
            [firing(labels={"namespace": "prod"})], at=0.0)
        assert len(ran) == 1 and out[0]["result"] == RM.EXECUTED

    def test_unbound_alert_is_ignored(self):
        eng = engine([RM.Remediation("fix", "HotZone", lambda tr: "")])
        assert eng.observe([firing(alert="Other")], at=0.0) == []

    def test_cooldown_suppresses_within_window_allows_after(self):
        clock = ManualClock()
        ran = []
        eng = engine([RM.Remediation(
            "fix", "HotZone", lambda tr: ran.append(1) or "",
            cooldown_s=120.0)], clock=clock)
        assert eng.observe([firing()], at=0.0)[0]["result"] == RM.EXECUTED
        out = eng.observe([firing()], at=60.0)
        assert out[0]["result"] == RM.COOLDOWN
        assert len(ran) == 1  # the action itself never ran
        assert eng.observe([firing()], at=120.0)[0]["result"] \
            == RM.EXECUTED
        assert len(ran) == 2

    def test_global_rate_limit_bounds_an_alert_storm(self):
        eng = engine(
            [RM.Remediation(f"fix-{i}", f"A{i}", lambda tr: "",
                            cooldown_s=0.0) for i in range(4)],
            max_actions=2, rate_window_s=600.0)
        out = eng.observe([firing(alert=f"A{i}") for i in range(4)],
                          at=0.0)
        assert [d["result"] for d in out] == [
            RM.EXECUTED, RM.EXECUTED, RM.RATE_LIMITED, RM.RATE_LIMITED]
        # window slides: capacity returns after rate_window_s
        out = eng.observe([firing(alert="A2")], at=600.0)
        assert out[0]["result"] == RM.EXECUTED

    def test_dry_run_burns_cooldown_and_rate_budget(self):
        """Byte-identical decision log law: a dry-run fleet must make
        the SAME suppression decisions a live one would."""
        ran = []
        eng = engine([RM.Remediation(
            "fix", "HotZone", lambda tr: ran.append(1) or "",
            cooldown_s=120.0)], dry_run=True)
        assert eng.observe([firing()], at=0.0)[0]["result"] == RM.DRY_RUN
        assert ran == []  # never executed...
        # ...but the cooldown was burned exactly as live would
        assert eng.observe([firing()], at=60.0)[0]["result"] \
            == RM.COOLDOWN

    def test_silence_mutes_action_without_burning_cooldown(self):
        muted = {"on": True}
        eng = engine(
            [RM.Remediation("fix", "HotZone", lambda tr: "",
                            cooldown_s=300.0)],
            silenced=lambda alert, labels, at: muted["on"])
        assert eng.observe([firing()], at=0.0)[0]["result"] \
            == RM.SILENCED
        muted["on"] = False
        # un-silencing acts immediately: silence never burned cooldown
        assert eng.observe([firing()], at=1.0)[0]["result"] \
            == RM.EXECUTED

    def test_skip_action_and_error_results(self):
        def skip(tr):
            raise RM.SkipAction("no node label")

        def boom(tr):
            raise RuntimeError("apiserver down")

        eng = engine([RM.Remediation("s", "A", skip, cooldown_s=0.0),
                      RM.Remediation("e", "B", boom, cooldown_s=0.0)])
        out = eng.observe([firing(alert="A"), firing(alert="B")], at=0.0)
        assert out[0]["result"] == RM.SKIPPED
        assert out[0]["detail"] == "no node label"
        assert out[1]["result"] == RM.ERROR
        assert "apiserver down" in out[1]["detail"]

    def test_audit_ring_is_bounded_and_ordered(self):
        eng = engine([RM.Remediation("fix", "A", lambda tr: "",
                                     cooldown_s=0.0)],
                     max_actions=10**6, audit_limit=3)
        for i in range(5):
            eng.observe([firing(alert="A", at=float(i))], at=float(i))
        audit = eng.audit()
        assert len(audit) == 3
        assert [d["at"] for d in audit] == [2.0, 3.0, 4.0]

    def test_decisions_counted_in_both_sinks_and_events_emitted(self):
        cluster = FakeCluster()
        reg = MetricsRegistry()
        eng = engine(
            [RM.Remediation("fix", "HotZone", lambda tr: "did it",
                            cooldown_s=0.0)],
            registry=reg, recorder=EventRecorder(cluster))
        eng.observe([firing()], at=0.0)
        eng.observe([firing()], at=1.0)
        text = reg.render()
        assert ('obs_remediations_total{action="fix",result="executed",'
                'tenant="default"}') in text
        events = cluster.list("v1", "Event", namespace="default")
        execd = [e for e in events if e["reason"] == "RemediationExecuted"]
        assert len(execd) == 1  # dedup'd, count bumped
        assert "did it" in execd[0]["message"]
        assert execd[0]["count"] == 2

    def test_decisions_attributed_to_triggering_namespace(self):
        """The tenant dimension: the namespace whose alert fired rides
        the audit entry, the counter label, and the Event's
        involvedObject — chargeback can bill the remediation."""
        cluster = FakeCluster()
        reg = MetricsRegistry()
        eng = engine(
            [RM.Remediation("fix", "HotZone", lambda tr: "did it",
                            cooldown_s=0.0)],
            registry=reg, recorder=EventRecorder(cluster))
        eng.observe([firing(labels={"namespace": "team-a"})], at=0.0)
        audit = eng.audit()
        assert audit[-1]["tenant"] == "team-a"
        assert ('obs_remediations_total{action="fix",result="executed",'
                'tenant="team-a"} 1.0') in reg.render()
        events = cluster.list("v1", "Event", namespace="team-a")
        assert [e for e in events
                if e["reason"] == "RemediationExecuted"]
        # an explicit tenant label on the transition outranks namespace
        eng.observe([firing(labels={"namespace": "team-a",
                                    "tenant": "team-b"}, at=5.0)], at=5.0)
        assert eng.audit()[-1]["tenant"] == "team-b"

    def test_failed_action_emits_warning_event(self):
        cluster = FakeCluster()

        def boom(tr):
            raise RuntimeError("nope")

        eng = engine([RM.Remediation("fix", "HotZone", boom,
                                     cooldown_s=0.0)],
                     recorder=EventRecorder(cluster))
        eng.observe([firing()], at=0.0)
        events = [e for e in cluster.list("v1", "Event",
                                          namespace="default")
                  if e["reason"] == "RemediationFailed"]
        assert len(events) == 1 and events[0]["type"] == "Warning"

    def test_suppressed_decisions_do_not_spam_events(self):
        cluster = FakeCluster()
        eng = engine([RM.Remediation("fix", "HotZone", lambda tr: "",
                                     cooldown_s=600.0)],
                     recorder=EventRecorder(cluster))
        eng.observe([firing()], at=0.0)
        eng.observe([firing()], at=10.0)  # cooldown decision
        events = cluster.list("v1", "Event", namespace="default")
        assert len([e for e in events
                    if e["reason"] == "RemediationExecuted"]) == 1


class TestFlapDamping:
    def test_pending_inactive_oscillation_never_acts_or_burns_cooldown(
            self):
        """The structural flap guard: a series oscillating below the
        for-duration produces pending/inactive transitions only — no
        decision is made AND no cooldown is burned, so the first REAL
        firing still remediates instantly."""
        clock = ManualClock()
        ran = []
        eng = engine([RM.Remediation(
            "fix", "Flappy", lambda tr: ran.append(1) or "",
            cooldown_s=600.0)], clock=clock)
        # ten flap cycles: pending, then back to inactive (the rule
        # engine emits no transition dict at all for the quiet half)
        for i in range(10):
            assert eng.observe(
                [dict(firing(alert="Flappy"), to="pending")],
                at=float(i * 30)) == []
        assert ran == [] and eng.audit() == []
        # the real sustained breach fires -> acts immediately (no
        # cooldown was burned by the flaps)
        out = eng.observe([firing(alert="Flappy")], at=300.0)
        assert out[0]["result"] == RM.EXECUTED and ran == [1]


class TestActions:
    def _svc_world(self):
        cluster = FakeCluster()
        cluster.create(JS.new_jaxservice(
            "chat", model="m", min_replicas=2, max_replicas=4))
        svc = cluster.get(JS.API_VERSION, JS.KIND, "chat", "default")
        svc.setdefault("status", {})["targetReplicas"] = 2
        cluster.update_status(svc)
        return cluster

    def test_scale_up_nudge_annotates_target_plus_one(self):
        cluster = self._svc_world()
        act = RM.scale_up_nudge_action(cluster)
        detail = act(firing(labels={"namespace": "default",
                                    "service": "chat"}))
        assert "3" in detail
        svc = cluster.get(JS.API_VERSION, JS.KIND, "chat", "default")
        assert svc["metadata"]["annotations"][
            JS.ANNOTATION_SCALE_NUDGE] == "3"

    def test_scale_up_nudge_without_service_label_skips(self):
        act = RM.scale_up_nudge_action(self._svc_world())
        with pytest.raises(RM.SkipAction):
            act(firing(labels={"namespace": "default"}))

    def test_cache_relist_marks_and_refreshes(self):
        from kubeflow_tpu.control.cache import ClusterCache

        cluster = FakeCluster()
        cache = ClusterCache(cluster).connect()
        cache.refresh()
        base = cache.stats()["relists"]
        act = RM.cache_relist_action(cache)
        detail = act(firing(alert="SchedulerPassSlow", labels={}))
        assert "relisted" in detail
        assert cache.stats()["relists"] > base

    def test_cordon_drain_cordons_and_evicts_only_gang_pods(self):
        cluster = FakeCluster()
        from kubeflow_tpu.control.scheduler.nodes import new_tpu_node
        cluster.create(new_tpu_node("tpu-0", topology="2x4"))

        def pod(name, node, sched=None, phase="Running"):
            p = {"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": name, "namespace": "default"},
                 "spec": {"nodeName": node,
                          "containers": [{"name": "jax"}]},
                 "status": {"phase": phase}}
            if sched:
                p["spec"]["schedulerName"] = sched
            return cluster.create(p)

        pod("gang-0", "tpu-0", sched=SCHEDULER_NAME)
        pod("gang-done", "tpu-0", sched=SCHEDULER_NAME,
            phase="Succeeded")
        pod("plain-0", "tpu-0")               # default scheduler: kept
        pod("gang-elsewhere", "tpu-1", sched=SCHEDULER_NAME)
        act = RM.cordon_drain_action(cluster)
        detail = act(firing(alert="NodeSLOBurn",
                            labels={"node": "tpu-0"}))
        assert "cordoned tpu-0" in detail and "1 pod" in detail
        node = cluster.get("v1", "Node", "tpu-0")
        assert node["spec"]["unschedulable"] is True
        st = cluster.get("v1", "Pod", "gang-0", "default")["status"]
        assert st["phase"] == "Failed" and st["reason"] == "Evicted"
        for untouched in ("plain-0", "gang-elsewhere"):
            assert cluster.get("v1", "Pod", untouched,
                               "default")["status"]["phase"] == "Running"
        assert cluster.get("v1", "Pod", "gang-done",
                           "default")["status"]["phase"] == "Succeeded"

    def test_cordon_drain_without_node_label_skips(self):
        act = RM.cordon_drain_action(FakeCluster())
        with pytest.raises(RM.SkipAction):
            act(firing(alert="NodeSLOBurn", labels={}))


class TestDefaultPack:
    def test_bindings_cover_the_three_staged_incidents(self):
        from kubeflow_tpu.control.cache import ClusterCache

        cluster = FakeCluster()
        rems = RM.default_remediations(
            client=cluster, cache=ClusterCache(cluster).connect())
        assert {r.alert for r in rems} == {
            "KVPagesExhausted", "NodeSLOBurn", "SchedulerPassSlow"}
        # every binding carries a nonzero cooldown (remediations act on
        # control loops whose effect takes time to land)
        assert all(r.cooldown_s > 0 for r in rems)

    def test_missing_dependencies_drop_their_bindings(self):
        assert RM.default_remediations() == []
        only_client = RM.default_remediations(client=FakeCluster())
        assert {r.alert for r in only_client} == {
            "KVPagesExhausted", "NodeSLOBurn"}
